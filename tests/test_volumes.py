"""Persistent Volume zonal topology.

Behavioral spec: reference website concepts/scheduling.md:389-398 — the
scheduler follows Pod → PVC → StorageClass, restricts new nodes to the
class's allowedTopologies for unbound WaitForFirstConsumer claims, pins to
the PV's zone once one exists, and later consumers of the claim follow it.
"""

import pytest

from karpenter_provider_aws_tpu.apis import (
    NodePool, Operator as ReqOp, PersistentVolumeClaim, Pod, Requirement,
    StorageClass,
)
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.solver import Solver, build_problem
from karpenter_provider_aws_tpu.utils.clock import FakeClock

_FAMILIES = ("m5", "c5", "t3")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog() if s.family in _FAMILIES])


@pytest.fixture(scope="module")
def solver(lattice):
    return Solver(lattice)


def vol_pod(name, claims):
    return Pod(name=name, requests={"cpu": "1", "memory": "2Gi"},
               volume_claims=list(claims))


class TestVolumeTopologySolve:
    def test_unbound_wffc_restricts_to_allowed_topologies(self, solver, lattice):
        scs = {"ebs": StorageClass(name="ebs",
                                   zones=("us-west-2a", "us-west-2b"))}
        pvcs = {"data": PersistentVolumeClaim(name="data", storage_class="ebs")}
        problem = build_problem([vol_pod("p0", ["data"])],
                                [NodePool(name="default")], lattice,
                                pvcs=pvcs, storage_classes=scs)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        assert all(n.zone in ("us-west-2a", "us-west-2b") for n in plan.new_nodes)
        assert all(z in ("us-west-2a", "us-west-2b")
                   for n in plan.new_nodes for z in n.feasible_zones)

    def test_bound_pv_pins_exact_zone(self, solver, lattice):
        pvcs = {"data": PersistentVolumeClaim(name="data", storage_class="ebs",
                                              bound_zone="us-west-2c")}
        problem = build_problem([vol_pod("p0", ["data"])],
                                [NodePool(name="default")], lattice, pvcs=pvcs)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        assert [n.zone for n in plan.new_nodes] == ["us-west-2c"]

    def test_bound_pv_outside_pool_zones_is_unschedulable(self, solver, lattice):
        pool = NodePool(name="default", requirements=[
            Requirement(wk.LABEL_ZONE, ReqOp.IN, ("us-west-2a",))])
        pvcs = {"data": PersistentVolumeClaim(name="data",
                                              bound_zone="us-west-2c")}
        problem = build_problem([vol_pod("p0", ["data"])], [pool], lattice,
                                pvcs=pvcs)
        plan = solver.solve(problem)
        assert "p0" in plan.unschedulable

    def test_distinct_claims_distinct_groups(self, solver, lattice):
        pvcs = {"a": PersistentVolumeClaim(name="a", bound_zone="us-west-2a"),
                "b": PersistentVolumeClaim(name="b", bound_zone="us-west-2b")}
        problem = build_problem([vol_pod("pa", ["a"]), vol_pod("pb", ["b"])],
                                [NodePool(name="default")], lattice, pvcs=pvcs)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        zone_of = {p: n.zone for n in plan.new_nodes for p in n.pods}
        assert zone_of["pa"] == "us-west-2a" and zone_of["pb"] == "us-west-2b"

    def test_unknown_pvc_warns_but_schedules(self, solver, lattice):
        problem = build_problem([vol_pod("p0", ["ghost"])],
                                [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        assert any("unknown PVC" in w for w in plan.warnings)

    def test_unknown_storage_class_warns(self, solver, lattice):
        pvcs = {"data": PersistentVolumeClaim(name="data",
                                              storage_class="missing")}
        problem = build_problem([vol_pod("p0", ["data"])],
                                [NodePool(name="default")], lattice, pvcs=pvcs)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        assert any("unknown StorageClass" in w for w in plan.warnings)

    def test_shared_claim_pin_respects_consumer_constraints(self, solver, lattice):
        """The shared-claim pin must come from the INTERSECTION of consumer
        zone constraints: two pods requiring us-west-2b sharing a claim
        allowed in 2a/2b must land in 2b, not be rejected by a naive
        first-eligible 2a pin."""
        scs = {"ebs": StorageClass(name="ebs",
                                   zones=("us-west-2a", "us-west-2b"))}
        pvcs = {"data": PersistentVolumeClaim(name="data", storage_class="ebs")}
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"},
                    node_selector={wk.LABEL_ZONE: "us-west-2b"},
                    volume_claims=["data"]) for i in range(2)]
        problem = build_problem(pods, [NodePool(name="default")], lattice,
                                pvcs=pvcs, storage_classes=scs)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        assert {n.zone for n in plan.new_nodes} == {"us-west-2b"}

    def test_shared_claim_pin_follows_sibling_bound_claim(self, solver, lattice):
        """A consumer whose OTHER claim is bound to 2b drags the shared
        unbound claim's pin to 2b for every consumer."""
        scs = {"ebs": StorageClass(name="ebs",
                                   zones=("us-west-2a", "us-west-2b"))}
        pvcs = {"data": PersistentVolumeClaim(name="data", storage_class="ebs"),
                "pinB": PersistentVolumeClaim(name="pinB",
                                              bound_zone="us-west-2b")}
        pods = [vol_pod("pa", ["pinB", "data"]), vol_pod("pb", ["data"])]
        problem = build_problem(pods, [NodePool(name="default")], lattice,
                                pvcs=pvcs, storage_classes=scs)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        assert {n.zone for n in plan.new_nodes} == {"us-west-2b"}

    def test_shared_unbound_claim_pins_one_zone(self, solver, lattice):
        """Same-batch consumers of one unbound WFFC claim must land in ONE
        zone — the bind would otherwise strand the losers."""
        scs = {"ebs": StorageClass(name="ebs",
                                   zones=("us-west-2a", "us-west-2b"))}
        pvcs = {"data": PersistentVolumeClaim(name="data", storage_class="ebs")}
        pods = [vol_pod(f"p{i}", ["data"]) for i in range(6)]
        problem = build_problem(pods, [NodePool(name="default")], lattice,
                                pvcs=pvcs, storage_classes=scs)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        zones = {n.zone for n in plan.new_nodes}
        assert len(zones) == 1 and zones <= {"us-west-2a", "us-west-2b"}


class TestVolumeBindingLifecycle:
    def test_wffc_binds_on_landing_and_pins_successor(self, lattice):
        """First consumer lands somewhere in the allowed zones; the PV binds
        to that zone; a later pod using the same claim follows it."""
        clock = FakeClock()
        env = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                       cloud=FakeCloud(clock), clock=clock,
                       node_pools=[NodePool(name="default")])
        env.cluster.add_storage_class(
            StorageClass(name="ebs", zones=("us-west-2a", "us-west-2b")))
        env.cluster.add_pvc(PersistentVolumeClaim(name="data", storage_class="ebs"))
        env.cluster.add_pod(vol_pod("first", ["data"]))
        env.settle()
        pod = env.cluster.pods["first"]
        assert pod.node_name
        zone = env.cluster.nodes[pod.node_name].labels[wk.LABEL_ZONE]
        assert zone in ("us-west-2a", "us-west-2b")
        assert env.cluster.pvcs["data"].bound_zone == zone
        # the first consumer goes away; a successor reuses the claim
        env.cluster.delete_pod("first")
        env.cluster.add_pod(vol_pod("second", ["data"]))
        env.settle()
        pod2 = env.cluster.pods["second"]
        assert pod2.node_name
        assert env.cluster.nodes[pod2.node_name].labels[wk.LABEL_ZONE] == zone

    def test_cross_batch_consumer_converges_before_registration(self, lattice):
        """A consumer arriving while the first consumer's node is still
        registering must see the claim already pinned (bound at launch
        success, not at node registration)."""
        clock = FakeClock()
        env = Operator(options=Options(registration_delay=30.0), lattice=lattice,
                       cloud=FakeCloud(clock), clock=clock,
                       node_pools=[NodePool(name="default")])
        env.cluster.add_storage_class(
            StorageClass(name="ebs", zones=("us-west-2a", "us-west-2b")))
        env.cluster.add_pvc(PersistentVolumeClaim(name="data", storage_class="ebs"))
        env.cluster.add_pod(vol_pod("first", ["data"]))
        env.provisioner.provision_once()          # launch; node NOT registered
        (claim,) = env.cluster.claims.values()
        assert claim.zone is not None
        assert env.cluster.pvcs["data"].bound_zone == claim.zone
        env.cluster.add_pod(vol_pod("second", ["data"]))
        env.settle()
        for name in ("first", "second"):
            pod = env.cluster.pods[name]
            assert pod.node_name
            assert (env.cluster.nodes[pod.node_name].labels[wk.LABEL_ZONE]
                    == env.cluster.pvcs["data"].bound_zone)

    def test_immediate_binding_pins_before_any_pod(self, lattice):
        """Immediate StorageClass: the PV exists before the first consumer;
        the pod follows the claim's zone."""
        clock = FakeClock()
        env = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                       cloud=FakeCloud(clock), clock=clock,
                       node_pools=[NodePool(name="default")])
        env.cluster.add_storage_class(StorageClass(
            name="io2", zones=("us-west-2c",), binding_mode="Immediate"))
        env.cluster.add_pvc(PersistentVolumeClaim(name="fast", storage_class="io2"))
        assert env.cluster.pvcs["fast"].bound_zone == "us-west-2c"
        env.cluster.add_pod(vol_pod("p0", ["fast"]))
        env.settle()
        pod = env.cluster.pods["p0"]
        assert pod.node_name
        assert env.cluster.nodes[pod.node_name].labels[wk.LABEL_ZONE] == "us-west-2c"
