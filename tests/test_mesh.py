"""The mesh-promoted production path (ISSUE 12 / docs/reference/sharding.md):

- parallel/mesh.py plan_mesh — auto policy (single-device on the cpu
  backend whose virtual device count is a dry-run knob), forced N-way
  meshes, the off/1 passthrough, and the flag/env plumbing;
- the mesh-native Solver: single-device passthrough picks the
  non-sharded path, a forced 8-way virtual mesh matches the
  single-device plan BYTE-IDENTICALLY on a capped (full-dissolve)
  config, the steady-state delta path composes with the mesh
  (resident hits, dirty-block bytes only), and a mesh-sized shape
  change invalidates the resident problem cache instead of
  delta-hitting stale shards;
- the surfaces: meshDevices on the Solve wire, the claim provenance
  annotation, the sidecar health doc, the two new gauges, the kpctl
  SOLVER row, and the (G,B,mesh)-keyed cost model.
"""

import json

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Pod, serde
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.parallel import plan_mesh, shard_groups, split_counts
from karpenter_provider_aws_tpu.solver import Solver, build_problem
from karpenter_provider_aws_tpu.solver.solve import NodePlan


@pytest.fixture(scope="module")
def lattice():
    specs = [s for s in build_catalog() if s.family in ("m5", "c5")]
    return build_lattice(specs)


@pytest.fixture(scope="module")
def capped_lattice():
    # one big type only: every shard's slice under-fills its bin, the
    # merge dissolves ALL shard bins and re-packs the whole problem in
    # the single-device refinement — the exact-parity shape
    specs = [s for s in build_catalog() if s.name == "m5.4xlarge"]
    return build_lattice(specs)


def _canon(plan: NodePlan) -> str:
    """Canonical plan content (serde.plan_semantic_dict — timings and
    provenance stripped): the byte-identity the mesh-vs-single-device
    parity claims."""
    return json.dumps(serde.plan_semantic_dict(plan), sort_keys=True)


class TestMeshPlanner:
    def test_auto_on_cpu_backend_is_single_device(self):
        """The 8 virtual host-platform devices are a dry-run knob, not
        hardware: auto must stay single-device on the cpu backend."""
        plan = plan_mesh("auto")
        assert plan.devices == 1
        assert plan.mesh is None
        assert plan.source == "single"
        # "" and None spell auto too
        assert plan_mesh(None).devices == 1
        assert plan_mesh("").devices == 1

    def test_forced_mesh(self):
        plan = plan_mesh("8")
        assert plan.devices == 8
        assert plan.source == "forced"
        assert plan.mesh is not None
        assert plan.mesh.devices.size == 8
        assert plan.mesh.axis_names == ("pods",)

    @pytest.mark.parametrize("spec", ["off", "none", "single", "1", "OFF"])
    def test_passthrough_specs(self, spec):
        plan = plan_mesh(spec)
        assert plan.devices == 1 and plan.mesh is None
        assert plan.source == "off"

    @pytest.mark.parametrize("spec", ["banana", "0", "-3", "2.5"])
    def test_invalid_specs(self, spec):
        with pytest.raises(ValueError):
            plan_mesh(spec)

    def test_options_validation_and_env(self, monkeypatch):
        from karpenter_provider_aws_tpu.operator import Options
        Options(mesh="8").validate()
        Options(mesh="auto").validate()
        Options(mesh="off").validate()
        with pytest.raises(ValueError):
            Options(mesh="nope").validate()
        monkeypatch.setenv("SOLVER_MESH", "8")
        assert Options.from_env().mesh == "8"

    def test_cli_flag(self):
        from karpenter_provider_aws_tpu.cli import (build_parser,
                                                    options_from_args)
        args = build_parser().parse_args(["--mesh", "8"])
        assert options_from_args(args).mesh == "8"
        # unset leaves the Options default ("" = auto)
        args = build_parser().parse_args([])
        assert options_from_args(args).mesh == ""

    def test_shard_groups_load(self):
        count = np.array([8, 8, 1, 1], np.int32)
        keep = np.array([False, False, True, True])
        split = split_counts(count, 4, keep_whole=keep)
        load = shard_groups(split)
        assert load.sum() == count.sum()
        # split groups give every shard 2; the whole groups round-robin
        # onto shards 0 and 1, which then carry the imbalance
        assert load.tolist() == [5, 5, 4, 4]
        assert load.max() / load.mean() == pytest.approx(10 / 9)


class TestMeshNativeSolver:
    def test_single_device_passthrough(self, lattice):
        """No mesh planned → the non-sharded path, zero mesh counters."""
        solver = Solver(lattice)
        assert solver.mesh_devices == 1
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"})
                for i in range(40)]
        plan = solver.solve(build_problem(pods, [NodePool(name="default")],
                                          lattice))
        assert plan.mesh_devices == 1
        st = solver.stats()
        assert st["mesh_devices"] == 1
        assert st["mesh_solves"] == 0

    def test_mesh_native_solve_engages(self, lattice):
        solver = Solver(lattice, mesh=plan_mesh("8").mesh)
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"})
                for i in range(200)]
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        # NO per-call mesh argument: the production default is the mesh
        plan = solver.solve(problem)
        assert plan.mesh_devices == 8
        st = solver.stats()
        assert st["mesh_devices"] == 8
        assert st["mesh_solves"] == 1
        assert st["mesh_shard_imbalance"] >= 1.0

    def test_forced_mesh_matches_single_device_byte_identically(
            self, capped_lattice):
        """The acceptance parity: on the capped (full-dissolve) config
        the 8-way mesh plan is byte-identical to the single-device plan
        — not just cost-equal."""
        pods = [Pod(name=f"t{i}", requests={"cpu": "1", "memory": "2Gi"})
                for i in range(16)]
        pools = [NodePool(name="default")]
        problem = build_problem(pods, pools, capped_lattice)
        single = Solver(capped_lattice).solve(problem)
        meshed = Solver(capped_lattice,
                        mesh=plan_mesh("8").mesh).solve(problem)
        assert meshed.mesh_devices == 8
        assert _canon(meshed) == _canon(single)

    def test_delta_on_mesh_stays_resident(self, lattice):
        """solve_delta rides the mesh: the whole-problem entry goes
        resident on the first pass, later passes delta-hit and ship
        only dirty blocks — never a full re-upload."""
        solver = Solver(lattice, mesh=plan_mesh("8").mesh)
        # 40 scheduling signatures so the fused buffer spans multiple
        # delta blocks (a 1-block buffer legitimately re-uploads whole)
        pods = [Pod(name=f"p{s}-{i}",
                    requests={"cpu": f"{100 + s * 25}m", "memory": "1Gi"})
                for s in range(40) for i in range(5)]
        pools = [NodePool(name="default")]
        problem = build_problem(pods, pools, lattice)
        p1 = solver.solve_delta(problem)
        assert p1.mesh_devices == 8
        st1 = solver.stats()
        full_bytes = st1["resident_bytes_shipped"]
        assert st1["resident_problem_misses"] == 1  # cold entry
        # an unchanged problem delta-hits with zero new blocks
        solver.solve_delta(problem)
        st2 = solver.stats()
        assert st2["resident_problem_hits"] == 1
        assert st2["resident_bytes_shipped"] == full_bytes
        # a small churn (one group's count moves) ships only the dirty
        # block, never the full staging
        churned = build_problem(pods[:-3], pools, lattice)
        p3 = solver.solve_delta(churned, dirty_groups=(39,))
        st3 = solver.stats()
        assert st3["resident_problem_hits"] == 2
        delta_bytes = st3["resident_bytes_shipped"] - full_bytes
        assert 0 < delta_bytes < full_bytes
        # and the plans still cover the pending set exactly
        placed = sum(len(n.pods) for n in p3.new_nodes) + sum(
            len(v) for v in p3.existing_assignments.values())
        assert placed + len(p3.unschedulable) == len(pods) - 3

    def test_mesh_shape_change_invalidates_resident_cache(self, lattice):
        """A mesh-sized shape change must re-upload, never delta-hit
        buffers resident under the old mesh (stale shards)."""
        solver = Solver(lattice, mesh=plan_mesh("8").mesh)
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"})
                for i in range(160)]
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        solver.solve_delta(problem)
        solver.solve_delta(problem)
        assert solver.stats()["resident_problem_hits"] == 1
        solver.set_mesh(plan_mesh("4").mesh)
        assert solver.mesh_devices == 4
        plan = solver.solve_delta(problem)
        assert plan.mesh_devices == 4
        st = solver.stats()
        # the re-shaped pass is a MISS (full re-upload under the new
        # mesh), not a hit against the 8-way entries
        assert st["resident_problem_hits"] == 1
        assert st["resident_problem_misses"] == 2

    def test_device_retry_invalidates_replicated_lattice_memo(
            self, lattice):
        """A retryable device fault may have taken the replicated
        lattice buffers with it (backend restart / OOM eviction): the
        retry must rebuild them, not re-dispatch against the dead memo
        — one transient fault must never become a persistent mesh
        outage."""
        from karpenter_provider_aws_tpu.solver.faults import FaultInjector
        solver = Solver(lattice, mesh=plan_mesh("8").mesh)
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"})
                for i in range(60)]
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        solver.solve(problem)
        pre_consts = solver._mesh_consts
        pre_alloc = solver._mesh_alloc
        assert pre_consts is not None and pre_alloc is not None
        solver.inject_faults(FaultInjector(device_errors=1))
        plan = solver.solve(problem)
        assert plan.device_retries == 1
        assert plan.solver_path == "device"   # the retry recovered
        # both memo halves were dropped and rebuilt for the retry
        assert solver._mesh_consts is not pre_consts
        assert solver._mesh_alloc is not pre_alloc

    def test_reprice_rekeys_prices_but_not_alloc(self, lattice):
        """A weather reprice (price_version bump) must re-replicate
        avail/price only — the invariant alloc tensor stays resident."""
        solver = Solver(lattice, mesh=plan_mesh("8").mesh)
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"})
                for i in range(60)]
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        solver.solve(problem)
        pre_consts = solver._mesh_consts
        pre_alloc = solver._mesh_alloc
        object.__setattr__(lattice, "price_version",
                           lattice.price_version + 1)
        try:
            solver.solve(problem)
        finally:
            object.__setattr__(lattice, "price_version",
                               lattice.price_version - 1)
        assert solver._mesh_consts is not pre_consts   # re-keyed
        assert solver._mesh_alloc is pre_alloc         # stayed resident

    def test_per_call_mesh_still_overrides(self, lattice):
        """Tests and the multichip dry-run force shapes per call; an
        explicit mesh= wins over the production default."""
        solver = Solver(lattice)   # no production mesh
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"})
                for i in range(60)]
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        plan = solver.solve(problem, mesh=plan_mesh("8").mesh)
        assert plan.mesh_devices == 8
        assert solver.stats()["mesh_solves"] == 1


class TestMeshSurfaces:
    def test_plan_wire_round_trips_mesh_devices(self):
        plan = NodePlan([], {}, {}, 0.0, 0.0, 0.0, mesh_devices=8,
                        shard_imbalance=1.25)
        d = serde.plan_to_dict(plan)
        assert d["meshDevices"] == 8
        assert d["shardImbalance"] == 1.25
        back = serde.plan_from_dict(d)
        assert back.mesh_devices == 8
        assert back.shard_imbalance == 1.25
        # a pre-mesh sidecar's wire doc defaults to 1 / unsharded
        d.pop("meshDevices")
        d.pop("shardImbalance")
        back = serde.plan_from_dict(d)
        assert back.mesh_devices == 1
        assert back.shard_imbalance == 0.0

    def test_provenance_annotation(self, lattice):
        from karpenter_provider_aws_tpu.apis import wellknown as wk
        from karpenter_provider_aws_tpu.cache.unavailable import (
            UnavailableOfferings)
        from karpenter_provider_aws_tpu.cloud import FakeCloud
        from karpenter_provider_aws_tpu.cloudprovider.cloudprovider import (
            CloudProvider)
        from karpenter_provider_aws_tpu.controllers.provisioning import (
            Provisioner)
        from karpenter_provider_aws_tpu.state.cluster import ClusterState
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        clock = FakeClock()
        cloud = FakeCloud(clock)
        prov = Provisioner(
            ClusterState(clock), Solver(lattice),
            {"default": NodePool(name="default")},
            CloudProvider(lattice, cloud, UnavailableOfferings(clock),
                          None, clock),
            UnavailableOfferings(clock), clock=clock)
        meshed = NodePlan([], {}, {}, 0.0, 0.0, 0.0, mesh_devices=8)
        ann = prov._provenance_annotations(meshed)
        assert ann[wk.ANNOTATION_SOLVER_MESH_DEVICES] == "8"
        # single-device plans stay clean (absent, not "1")
        single = NodePlan([], {}, {}, 0.0, 0.0, 0.0)
        assert wk.ANNOTATION_SOLVER_MESH_DEVICES not in \
            prov._provenance_annotations(single)

    def test_sidecar_health_reports_mesh(self, lattice):
        from karpenter_provider_aws_tpu.parallel.sidecar import SolverService
        svc = SolverService(Solver(lattice, mesh=plan_mesh("8").mesh))
        doc = json.loads(svc.health(b"{}").decode())
        assert doc["meshDevices"] == 8

    def test_remote_solver_reports_sidecar_mesh(self, lattice, tmp_path):
        """In a --solver-address deployment the SIDECAR's mesh is the
        one that solves: the operator-side stats (and so the mesh
        gauges / kpctl top) must report the mesh observed on returned
        plans, not the local fallback's (usually meshless) plan."""
        from karpenter_provider_aws_tpu.parallel.sidecar import (
            RemoteSolver, serve)
        sidecar_solver = Solver(lattice, mesh=plan_mesh("8").mesh)
        addr = f"unix:{tmp_path}/mesh-sidecar.sock"
        server = serve(sidecar_solver, addr, admission_window=False)
        try:
            rs = RemoteSolver(lattice, addr)   # NO local mesh
            assert rs.stats()["mesh_devices"] == 1   # nothing observed yet
            pods = [Pod(name=f"p{i}",
                        requests={"cpu": "1", "memory": "2Gi"})
                    for i in range(24)]
            plan = rs.solve_relaxed(pods, [NodePool(name="default")])
            assert plan.mesh_devices == 8            # rode the wire
            assert plan.shard_imbalance >= 1.0       # so did the split
            st = rs.stats()
            assert st["mesh_devices"] == 8
            assert st["mesh_solves"] >= 1
            assert st["mesh_shard_imbalance"] >= 1.0
        finally:
            server.stop(grace=None)
        # the sidecar is GONE: the fallback local solver is what solves
        # now, and the surface must say so — an outage must never keep
        # advertising a mesh nothing is solving on (the cumulative
        # sharded-solve evidence stays)
        plan = rs.solve_relaxed(pods, [NodePool(name="default")])
        assert plan.degraded_reason == "sidecar-unreachable"
        st = rs.stats()
        assert st["mesh_devices"] == 1               # local fallback
        assert st["mesh_shard_imbalance"] == 0.0
        assert st["mesh_solves"] >= 1                # evidence retained

    def test_kpctl_solver_row_renders_mesh(self, monkeypatch):
        import pathlib
        monkeypatch.syspath_prepend(str(
            pathlib.Path(__file__).resolve().parent.parent / "tools"))
        import kpctl
        doc = {"providers": {"solver": {"mesh_devices": 8,
                                        "mesh_solves": 12,
                                        "pipeline": 1}}}
        lines = kpctl._render_top(doc, "srv")
        solver_row = next(l for l in lines if l.startswith("SOLVER"))
        assert "mesh 8dev" in solver_row
        assert "(12 sharded)" in solver_row

    def test_cost_model_keys_mesh_separately(self):
        from karpenter_provider_aws_tpu.solver.costmodel import (
            DeviceCostModel, shape_key)
        assert shape_key(64, 512) == "G64_B512"
        assert shape_key(64, 512, mesh_devices=1) == "G64_B512"
        assert shape_key(64, 512, mesh_devices=8) == "G64_B512_D8"
        m = DeviceCostModel()
        # a fast mesh solve must not become the single-device entry's
        # best-demonstrated floor (the PR 12 collision bugfix)
        m.observe_solve(shape_key(64, 512), 40.0)
        m.observe_solve(shape_key(64, 512, mesh_devices=8), 8.0)
        shapes = m.summary()["shapes"]
        assert shapes["G64_B512"]["best_ms"] == 40.0
        assert shapes["G64_B512_D8"]["best_ms"] == 8.0

    def test_operator_emits_mesh_gauges(self, lattice):
        from karpenter_provider_aws_tpu.cloud import FakeCloud
        from karpenter_provider_aws_tpu.operator import Operator, Options
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        clock = FakeClock()
        op = Operator(options=Options(mesh="8"), lattice=lattice,
                      cloud=FakeCloud(clock), clock=clock)
        assert op.mesh_plan.devices == 8
        assert op.solver.mesh_devices == 8
        op.emit_gauges()
        text = op.metrics.render()
        assert "karpenter_solver_mesh_devices 8.0" in text
        assert "karpenter_solver_shard_imbalance_ratio" in text
        # default auto boot on the cpu backend stays single-device
        op2 = Operator(lattice=lattice, cloud=FakeCloud(clock), clock=clock)
        assert op2.mesh_plan.devices == 1
        op2.emit_gauges()
        assert "karpenter_solver_mesh_devices 1.0" in op2.metrics.render()
