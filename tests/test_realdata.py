"""Real-data catalog: reference fixtures → JSON → lattice.

The imported facts (tools/import_reference_data.py from the reference's
zz_generated tables) must survive into the lattice EXACTLY: hardware
shapes from pkg/fake/zz_generated.describe_instance_types.go, ENI/pod
density + trunking from zz_generated.vpclimits.go, prices from
zz_generated.pricing_aws.go (us-east-1), and the trn1 Neuron hardcodes
(types.go:281-291).
"""

import pathlib
import subprocess
import sys

import pytest

from karpenter_provider_aws_tpu.apis.resources import RESOURCE_AXES
from karpenter_provider_aws_tpu.lattice import build_lattice
from karpenter_provider_aws_tpu.lattice.realdata import (
    DEFAULT_PATH, load_catalog, parse_family,
)

REPO = pathlib.Path(__file__).resolve().parent.parent
REFERENCE = pathlib.Path("/root/reference")


def ax(name):
    return RESOURCE_AXES.index(name)


@pytest.fixture(scope="module")
def specs():
    return load_catalog()


@pytest.fixture(scope="module")
def lattice(specs):
    return build_lattice(specs)


class TestLoader:
    def test_all_fixture_types_load(self, specs):
        names = {s.name for s in specs}
        assert {"m5.large", "m5.metal", "c6g.large", "t4g.medium",
                "dl1.24xlarge", "inf1.2xlarge", "trn1.2xlarge",
                "g4dn.8xlarge", "p3.8xlarge", "m6idn.32xlarge"} <= names
        assert len(specs) == 15

    def test_family_parsing(self):
        assert parse_family("m6idn") == ("m", 6)
        assert parse_family("trn1") == ("trn", 1)
        assert parse_family("g4dn") == ("g", 4)
        assert parse_family("c6g") == ("c", 6)

    def test_m5_large_facts(self, specs):
        m5 = next(s for s in specs if s.name == "m5.large")
        assert (m5.vcpus, m5.memory_mib) == (2, 8192)
        assert (m5.enis, m5.ipv4_per_eni) == (3, 10)
        assert m5.pod_eni_count == 9        # vpclimits BranchInterface
        assert m5.od_price == 0.096         # us-east-1 pricing table
        assert m5.arch == "amd64" and m5.cpu_manufacturer == "intel"
        assert m5.network_bandwidth_mbps == 750   # bandwidth table

    def test_graviton_facts(self, specs):
        c6g = next(s for s in specs if s.name == "c6g.large")
        assert c6g.arch == "arm64" and c6g.cpu_manufacturer == "aws"

    def test_metal_has_no_hypervisor(self, specs):
        metal = next(s for s in specs if s.name == "m5.metal")
        assert metal.hypervisor == ""
        assert metal.size == "metal"

    def test_accelerators(self, specs):
        by = {s.name: s for s in specs}
        assert by["dl1.24xlarge"].gpu_manufacturer == "habana"
        assert by["dl1.24xlarge"].gpu_count == 8
        assert by["p3.8xlarge"].gpu_manufacturer == "nvidia"
        assert by["inf1.6xlarge"].accelerator_count == 4
        # trn1 Neurons are the reference's hardcoded facts (types.go:283-291)
        assert by["trn1.2xlarge"].accelerator_name == "Trainium"
        assert by["trn1.2xlarge"].accelerator_count == 1


class TestLatticeFromRealData:
    def test_real_eni_pod_density(self, lattice):
        """ENI-limited pods = enis*(ipv4-1)+2 over the REAL vpclimits
        numbers — the eni-max-pods contract the synthetic catalog only
        mirrors in shape."""
        pods_ax = ax("pods")
        expect = {"m5.large": 29, "m5.xlarge": 58, "t3.large": 35,
                  "m5.metal": 737, "c6g.large": 29}
        for name, pods in expect.items():
            i = lattice.name_to_idx[name]
            assert lattice.capacity[i, pods_ax] == pods, name

    def test_gpu_resources_by_manufacturer(self, lattice):
        i = lattice.name_to_idx["dl1.24xlarge"]
        assert lattice.capacity[i, ax("habana.ai/gaudi")] == 8
        assert lattice.capacity[i, ax("nvidia.com/gpu")] == 0
        j = lattice.name_to_idx["p3.8xlarge"]
        assert lattice.capacity[j, ax("nvidia.com/gpu")] == 4
        k = lattice.name_to_idx["inf1.6xlarge"]
        assert lattice.capacity[k, ax("aws.amazon.com/neuron")] == 4
        t = lattice.name_to_idx["trn1.2xlarge"]
        assert lattice.capacity[t, ax("aws.amazon.com/neuron")] == 1

    def test_real_prices_reach_offerings(self, lattice):
        i = lattice.name_to_idx["m5.large"]
        # on-demand price in a plain AZ is the regional price
        zi, ci = 0, lattice.capacity_types.index("on-demand")
        assert abs(lattice.price[i, zi, ci] - 0.096) < 1e-9

    def test_solver_runs_on_real_lattice(self, lattice):
        from karpenter_provider_aws_tpu.apis import NodePool, Pod
        from karpenter_provider_aws_tpu.solver import Solver, build_problem
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"})
                for i in range(10)]
        pods.append(Pod(name="gpu0",
                        requests={"cpu": "4", "memory": "16Gi",
                                  "nvidia.com/gpu": 1}))
        plan = Solver(lattice).solve(build_problem(
            pods, [NodePool(name="default")], lattice))
        assert not plan.unschedulable
        gpu_nodes = [n for n in plan.new_nodes if "gpu0" in n.pods]
        assert gpu_nodes and gpu_nodes[0].instance_type in (
            "g4dn.8xlarge", "p3.8xlarge")

    def test_allocatable_matches_reference_formulas(self, lattice):
        """The overhead math (types.go:341-431) applied to REAL m5.large
        numbers: kube-reserved cpu for 2 vCPU = 70m (60+10), memory
        reserved = 11*pods + 255, eviction 100Mi."""
        i = lattice.name_to_idx["m5.large"]
        cap_cpu = lattice.capacity[i, ax("cpu")]
        alloc_cpu = lattice.alloc[i, ax("cpu")]
        assert cap_cpu == 2000.0
        assert alloc_cpu == 2000.0 - 70.0
        cap_mem = lattice.capacity[i, ax("memory")]
        alloc_mem = lattice.alloc[i, ax("memory")]
        reserved = 11 * 29 + 255
        assert abs((cap_mem - alloc_mem) - (reserved + 100)) < 1e-3


class TestImporterFreshness:
    @pytest.mark.skipif(not REFERENCE.exists(),
                        reason="reference checkout unavailable")
    def test_checked_in_catalog_is_current(self, tmp_path):
        out = tmp_path / "cat.json"
        r = subprocess.run(
            [sys.executable, str(REPO / "tools" / "import_reference_data.py"),
             "--out", str(out)],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert out.read_text() == DEFAULT_PATH.read_text()
