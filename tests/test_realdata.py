"""Real-data catalog: reference data tables → JSON → lattice.

The imported facts (tools/import_reference_data.py) must survive into
the lattice EXACTLY: the full-breadth per-type labels from the
reference's generated instance-types doc (website/content/en/preview/
reference/instance-types.md, 759 sections), ENI/pod density + trunking
from zz_generated.vpclimits.go (default-card inversion per
types.go:319-332), prices from zz_generated.pricing_aws.go (us-east-1),
bandwidth from zz_generated.bandwidth.go, and the trn1 Neuron hardcodes
(types.go:281-291). Spot prices are data-carried per-AZ (flagged
derived — the reference ships no static spot table, pricing.go:409-415).
"""

import json
import pathlib
import subprocess
import sys

import pytest

from karpenter_provider_aws_tpu.apis.resources import RESOURCE_AXES
from karpenter_provider_aws_tpu.lattice import build_lattice
from karpenter_provider_aws_tpu.lattice.realdata import (
    DEFAULT_PATH, load_catalog, parse_family,
)

REPO = pathlib.Path(__file__).resolve().parent.parent
REFERENCE = pathlib.Path("/root/reference")


def ax(name):
    return RESOURCE_AXES.index(name)


@pytest.fixture(scope="module")
def specs():
    return load_catalog()


@pytest.fixture(scope="module")
def lattice(specs):
    return build_lattice(specs)


@pytest.fixture(scope="module")
def raw_doc():
    return json.loads(DEFAULT_PATH.read_text())


class TestLoader:
    def test_full_breadth(self, specs):
        """The catalog is the reference's real ~750-type breadth, not a
        fixture subset."""
        names = {s.name for s in specs}
        assert len(specs) >= 700
        assert {"m5.large", "m5.metal", "c6g.large", "t4g.medium",
                "dl1.24xlarge", "inf1.2xlarge", "trn1.2xlarge",
                "g4dn.8xlarge", "p3.8xlarge", "m6idn.32xlarge",
                "p5.48xlarge", "u-24tb1.112xlarge", "hpc7g.16xlarge",
                "a1.medium", "c7gn.16xlarge"} <= names

    def test_all_types_priced(self, specs):
        assert all(s.od_price > 0 for s in specs)

    def test_family_parsing(self):
        assert parse_family("m6idn") == ("m", 6)
        assert parse_family("trn1") == ("trn", 1)
        assert parse_family("g4dn") == ("g", 4)
        assert parse_family("c6g") == ("c", 6)

    def test_m5_large_facts(self, specs):
        m5 = next(s for s in specs if s.name == "m5.large")
        assert (m5.vcpus, m5.memory_mib) == (2, 8192)
        assert (m5.enis, m5.ipv4_per_eni) == (3, 10)
        assert m5.pod_eni_count == 9        # vpclimits BranchInterface
        assert m5.od_price == 0.096         # us-east-1 pricing table
        assert m5.arch == "amd64" and m5.cpu_manufacturer == "intel"
        assert m5.network_bandwidth_mbps == 750

    def test_graviton_facts(self, specs):
        c6g = next(s for s in specs if s.name == "c6g.large")
        assert c6g.arch == "arm64" and c6g.cpu_manufacturer == "aws"

    def test_metal_has_no_hypervisor(self, specs):
        metal = next(s for s in specs if s.name == "m5.metal")
        assert metal.hypervisor == ""
        assert metal.size == "metal"

    def test_accelerators(self, specs):
        by = {s.name: s for s in specs}
        assert by["dl1.24xlarge"].gpu_manufacturer == "habana"
        assert by["dl1.24xlarge"].gpu_count == 8
        assert by["p3.8xlarge"].gpu_manufacturer == "nvidia"
        assert by["inf1.6xlarge"].accelerator_count == 4
        # trn1 Neurons are the reference's hardcoded facts (types.go:283-291)
        assert by["trn1.2xlarge"].accelerator_name == "Trainium"
        assert by["trn1.2xlarge"].accelerator_count == 1
        assert by["p5.48xlarge"].gpu_count == 8     # H100s
        assert by["p5.48xlarge"].gpu_memory_mib == 81920

    def test_multi_network_card_default_card_enis(self, specs):
        """vpclimits counts ENIs across all cards, but the VPC CNI only
        uses the default card (types.go:319-332); the importer inverts
        the doc's published pods to recover the default-card count."""
        by = {s.name: s for s in specs}
        assert by["trn1n.32xlarge"].enis == 5       # not the 80 total
        assert by["p5.48xlarge"].enis == 2          # not the 64 total
        assert by["c6in.32xlarge"].enis == 7        # not the 14 total

    def test_efa_from_doc_resources(self, specs):
        by = {s.name: s for s in specs}
        assert by["p4d.24xlarge"].efa_count == 4
        assert by["trn1n.32xlarge"].efa_count == 16
        assert by["m5.large"].efa_count == 0

    def test_spot_prices_are_data_carried(self, specs, raw_doc):
        """Spot prices ride the JSON (per-AZ), flagged derived."""
        assert "derived" in raw_doc["spotSource"]
        m5 = next(s for s in specs if s.name == "m5.large")
        assert m5.spot_prices, "real catalog must carry spot prices"
        zones = [z for z, _ in m5.spot_prices]
        assert "us-west-2a" in zones
        for _, p in m5.spot_prices:
            assert 0 < p < m5.od_price


class TestLatticeFromRealData:
    def test_real_eni_pod_density(self, lattice):
        """ENI-limited pods = enis*(ipv4-1)+2 over the REAL vpclimits
        numbers — the eni-max-pods contract the synthetic catalog only
        mirrors in shape."""
        pods_ax = ax("pods")
        expect = {"m5.large": 29, "m5.xlarge": 58, "t3.large": 35,
                  "m5.metal": 737, "c6g.large": 29,
                  "trn1n.32xlarge": 247, "p5.48xlarge": 100,
                  "hpc7g.16xlarge": 198}
        for name, pods in expect.items():
            i = lattice.name_to_idx[name]
            assert lattice.capacity[i, pods_ax] == pods, name

    def test_gpu_resources_by_manufacturer(self, lattice):
        i = lattice.name_to_idx["dl1.24xlarge"]
        assert lattice.capacity[i, ax("habana.ai/gaudi")] == 8
        assert lattice.capacity[i, ax("nvidia.com/gpu")] == 0
        j = lattice.name_to_idx["p3.8xlarge"]
        assert lattice.capacity[j, ax("nvidia.com/gpu")] == 4
        k = lattice.name_to_idx["inf1.6xlarge"]
        assert lattice.capacity[k, ax("aws.amazon.com/neuron")] == 4
        t = lattice.name_to_idx["trn1.2xlarge"]
        assert lattice.capacity[t, ax("aws.amazon.com/neuron")] == 1

    def test_real_prices_reach_offerings(self, lattice):
        i = lattice.name_to_idx["m5.large"]
        # on-demand price in a plain AZ is the regional price
        zi, ci = 0, lattice.capacity_types.index("on-demand")
        assert abs(lattice.price[i, zi, ci] - 0.096) < 1e-9

    def test_spot_prices_from_data_not_synthetic(self, lattice, specs):
        """The lattice's spot axis equals the JSON's numbers (data
        path), for every available spot offering."""
        import numpy as np
        ci = lattice.capacity_types.index("spot")
        by = {s.name: s for s in specs}
        checked = 0
        for i, name in enumerate(lattice.names[:50]):
            s = by[name]
            for zi, z in enumerate(lattice.zones):
                if not lattice.available[i, zi, ci]:
                    continue
                sp = s.spot_price_in(z)
                assert sp is not None, (name, z)
                assert abs(lattice.price[i, zi, ci] - sp) < 1e-6
                checked += 1
        assert checked > 50

    def test_solver_runs_on_real_lattice(self, lattice):
        from karpenter_provider_aws_tpu.apis import NodePool, Pod
        from karpenter_provider_aws_tpu.solver import Solver, build_problem
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"})
                for i in range(10)]
        pods.append(Pod(name="gpu0",
                        requests={"cpu": "4", "memory": "16Gi",
                                  "nvidia.com/gpu": 1}))
        plan = Solver(lattice).solve(build_problem(
            pods, [NodePool(name="default")], lattice))
        assert not plan.unschedulable
        gpu_nodes = [n for n in plan.new_nodes if "gpu0" in n.pods]
        assert gpu_nodes
        gi = lattice.name_to_idx[gpu_nodes[0].instance_type]
        assert lattice.capacity[gi, ax("nvidia.com/gpu")] >= 1

    def test_allocatable_matches_reference_formulas(self, lattice):
        """The overhead math (types.go:341-431) applied to REAL m5.large
        numbers: kube-reserved cpu for 2 vCPU = 70m (60+10), memory
        reserved = 11*pods + 255, eviction 100Mi."""
        i = lattice.name_to_idx["m5.large"]
        cap_cpu = lattice.capacity[i, ax("cpu")]
        alloc_cpu = lattice.alloc[i, ax("cpu")]
        assert cap_cpu == 2000.0
        assert alloc_cpu == 2000.0 - 70.0
        cap_mem = lattice.capacity[i, ax("memory")]
        alloc_mem = lattice.alloc[i, ax("memory")]
        reserved = 11 * 29 + 255
        assert abs((cap_mem - alloc_mem) - (reserved + 100)) < 1e-3

    def test_allocatable_matches_reference_published(self, lattice,
                                                     raw_doc):
        """Our predicted allocatable equals the reference's OWN published
        numbers (the instance-types doc's Resources table, preserved per
        type as refAllocatable) across the ENTIRE catalog — cpu exact,
        memory within 2 MiB (one rounding divergence on the 24 TiB
        type)."""
        cpu_ax, mem_ax, pods_ax = ax("cpu"), ax("memory"), ax("pods")
        checked = 0
        for t in raw_doc["types"]:
            ra = t.get("refAllocatable")
            if not ra or not ra.get("cpuMilli"):
                continue
            i = lattice.name_to_idx[t["name"]]
            assert lattice.alloc[i, cpu_ax] == ra["cpuMilli"], t["name"]
            assert abs(lattice.alloc[i, mem_ax] - ra["memoryMi"]) <= 2, \
                t["name"]
            assert lattice.alloc[i, pods_ax] == ra["pods"], t["name"]
            checked += 1
        assert checked >= 700


class TestImporterFreshness:
    @pytest.mark.skipif(not REFERENCE.exists(),
                        reason="reference checkout unavailable")
    def test_checked_in_catalog_is_current(self, tmp_path):
        out = tmp_path / "cat.json"
        r = subprocess.run(
            [sys.executable, str(REPO / "tools" / "import_reference_data.py"),
             "--out", str(out)],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert out.read_text() == DEFAULT_PATH.read_text()
