"""The device-resident reconcile microloop (ISSUE 14 /
docs/reference/microloop.md):

- plan parity: solve_delta (the microloop) is byte-identical to a
  full-staging solve of the same problem, across churn, on one device
  and on the forced 8-way virtual mesh;
- the changed-plan fingerprint: an unchanged problem skips the plan
  fetch (and, on a mesh, the tail-bin merge) while still re-decoding
  correctly; link legs per steady pass stay within the bound;
- donation safety: a device fault mid-microloop rebuilds donated state
  (resident invalidation) instead of re-dispatching against a consumed
  buffer, and recovery restores parity AND re-engages the microloop;
- mesh-shape invalidation resets the retained microloop state;
- the admission-overlap seam runs exactly once per solve_delta call,
  fallback included;
- stats() reports every microloop counter without touching the solve
  lock (the stats-never-blocks pin extended to the new surface);
- the journal → device-block coalescer: contiguous drains merge, a
  mismatched anchor falls back to a direct journal read, and batched
  ticks surface in DirtySet.ticks.
"""

import json
import threading

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Pod, serde
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.parallel import plan_mesh
from karpenter_provider_aws_tpu.solver import Solver, build_problem
from karpenter_provider_aws_tpu.solver.faults import FaultInjector


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog()
                          if s.family in ("m5", "c5")])


def _canon(plan) -> str:
    return json.dumps(serde.plan_semantic_dict(plan), sort_keys=True)


def _pods(n_sigs=10, per=5):
    return [Pod(name=f"p{s}-{i}",
                requests={"cpu": f"{100 + s * 25}m", "memory": "1Gi"})
            for s in range(n_sigs) for i in range(per)]


class TestMicroloopSingleDevice:
    def test_parity_across_churn(self, lattice):
        """Byte-identical to a full-staging solve of the SAME problem
        at every step — the delta is in bytes moved, never the answer."""
        solver = Solver(lattice)
        referee = Solver(lattice)
        pools = [NodePool(name="default")]
        pods = _pods()
        for cut in (0, 3, 7, 1):
            pods = pods[cut:]
            problem = build_problem(pods, pools, lattice)
            got = solver.solve_delta(problem)
            assert _canon(got) == _canon(referee.solve(problem))
            assert got.pipelined and got.solver_path == "device"
        st = solver.stats()
        assert st["micro_solves"] == 4
        assert st["micro_aborts"] == 0

    def test_fingerprint_skips_unchanged_plan(self, lattice):
        """An unchanged problem pays ZERO data legs: no dirty blocks to
        upload, and the fingerprint suppresses the plan fetch."""
        solver = Solver(lattice)
        problem = build_problem(_pods(), [NodePool(name="default")],
                                lattice)
        p1 = solver.solve_delta(problem)
        legs0 = (solver.link_stats["upload_legs"]
                 + solver.link_stats["fetch_legs"])
        p2 = solver.solve_delta(problem)
        st = solver.stats()
        assert st["micro_skipped_syncs"] == 1
        assert st["micro_tiny_syncs"] >= 2
        assert (solver.link_stats["upload_legs"]
                + solver.link_stats["fetch_legs"]) == legs0
        assert st["micro_last_legs"] == 0
        assert _canon(p1) == _canon(p2)

    def test_steady_churn_pays_at_most_two_legs(self, lattice):
        solver = Solver(lattice)
        pools = [NodePool(name="default")]
        pods = _pods(n_sigs=40)   # multi-block fused buffer
        solver.solve_delta(build_problem(pods, pools, lattice))
        for cut in (3, 2, 4):
            pods = pods[cut:]
            solver.solve_delta(build_problem(pods, pools, lattice))
            assert solver.pipeline_stats["micro_last_legs"] <= 2

    def test_skipped_sync_redecodes_with_current_names(self, lattice):
        """Pod NAMES churn even when the packing doesn't: the retained
        result bytes must decode against the current problem's names."""
        solver = Solver(lattice)
        pools = [NodePool(name="default")]
        a = build_problem(_pods(), pools, lattice)
        solver.solve_delta(a)
        renamed = [Pod(name=f"r{s}-{i}",
                       requests={"cpu": f"{100 + s * 25}m",
                                 "memory": "1Gi"})
                   for s in range(10) for i in range(5)]
        b = build_problem(renamed, pools, lattice)
        plan = solver.solve_delta(b)
        # identical packing → fetch skipped, but the plan names the NEW pods
        assert solver.stats()["micro_skipped_syncs"] == 1
        placed = {p for n in plan.new_nodes for p in n.pods} | {
            p for v in plan.existing_assignments.values() for p in v}
        assert placed == {p.name for p in renamed} - set(plan.unschedulable)

    def test_overlap_runs_exactly_once(self, lattice):
        solver = Solver(lattice)
        problem = build_problem(_pods(), [NodePool(name="default")],
                                lattice)
        calls = []
        solver.solve_delta(problem, overlap=lambda: calls.append(1))
        assert calls == [1]
        assert solver.stats()["overlapped_admission"] == 1
        # fallback path (wave-scale G is ineligible) still runs it once
        fi = FaultInjector(g_limit=2)
        solver.inject_faults(fi)
        pods = [Pod(name=f"w{s}", requests={"cpu": f"{100 + s}m"})
                for s in range(8)]
        wave = build_problem(pods, [NodePool(name="default")], lattice)
        calls.clear()
        plan = solver.solve_delta(wave, overlap=lambda: calls.append(1))
        solver.inject_faults(None)
        assert calls == [1]
        assert plan.solver_path == "wave-split"
        assert solver.stats()["micro_aborts"] == 1


class TestDonationSafety:
    def test_fault_mid_microloop_rebuilds_donated_state(self, lattice):
        """The donation-safety pin: a device fault mid-microloop must
        invalidate the resident (donated) state so recovery re-uploads
        fresh — never re-dispatches a consumed buffer — and the faulted
        pass still returns a parity plan via the ladder."""
        solver = Solver(lattice)
        referee = Solver(lattice)
        pools = [NodePool(name="default")]
        problem = build_problem(_pods(), pools, lattice)
        solver.solve_delta(problem)
        misses0 = solver._resident.misses
        solver.inject_faults(FaultInjector(device_errors=1))
        faulted = solver.solve_delta(problem)
        solver.inject_faults(None)
        ref = referee.solve(problem)
        assert _canon(faulted) == _canon(ref)
        # the recovery re-uploaded (resident state was dropped, not reused)
        assert solver._resident.misses > misses0
        assert solver.stats()["micro_aborts"] == 1
        assert solver.stats()["micro_engaged"] is False
        # and the NEXT pass re-engages the microloop with parity intact
        again = solver.solve_delta(problem)
        assert _canon(again) == _canon(ref)
        assert solver.stats()["micro_solves"] == 2
        assert solver.stats()["micro_engaged"] is True

    def test_donated_entry_replaced_never_reread(self, lattice):
        """After a donated delta scatter the cache entry holds the
        scatter OUTPUT; the consumed base is unreachable. The returned
        views across passes are distinct live arrays."""
        from karpenter_provider_aws_tpu.solver.pipeline import (
            ResidentInputCache)
        cache = ResidentInputCache(block=64)
        a = np.arange(1024, dtype=np.uint8)
        d1 = cache.upload(("k",), a, donate=True)
        b = a.copy()
        b[3] ^= 0xFF
        d2 = cache.upload(("k",), b, donate=True)
        assert cache.hits == 1 and cache.blocks_shipped >= 1
        assert np.asarray(d2)[3] == b[3]
        # a third no-op upload serves from the (replaced) entry
        d3 = cache.upload(("k",), b, donate=True)
        assert np.array_equal(np.asarray(d3), b)


class TestMicroloopOnMesh:
    def test_mesh_micro_parity_and_merge_reuse(self, lattice):
        solver = Solver(lattice, mesh=plan_mesh("8").mesh)
        referee = Solver(lattice)
        pools = [NodePool(name="default")]
        problem = build_problem(_pods(n_sigs=16, per=8), pools, lattice)
        p1 = solver.solve_delta(problem)
        assert p1.mesh_devices == 8
        assert _canon(p1) == _canon(referee.solve(problem))
        merge_ran = solver.pipeline_stats["micro_merge_solves"]
        p2 = solver.solve_delta(problem)
        st = solver.stats()
        assert st["micro_skipped_syncs"] == 1
        assert st["micro_last_legs"] == 0
        if merge_ran:
            # identical shard results reuse the retained merge bytes
            assert st["micro_merge_skips"] == 1
            assert st["micro_merge_solves"] == merge_ran
        assert _canon(p2) == _canon(p1)

    def test_mesh_shape_change_resets_micro_state(self, lattice):
        solver = Solver(lattice, mesh=plan_mesh("8").mesh)
        problem = build_problem(_pods(), [NodePool(name="default")],
                                lattice)
        solver.solve_delta(problem)
        assert solver.stats()["micro_engaged"] is True
        solver.set_mesh(plan_mesh("4").mesh)
        assert solver.stats()["micro_engaged"] is False
        plan = solver.solve_delta(problem)
        assert plan.mesh_devices == 4
        # cold under the new mesh: a full fetch, never a stale skip
        assert solver.stats()["micro_skipped_syncs"] == 0

    def test_pinned_groups_abort_to_standard_planner(self, lattice):
        """single_bin (co-location) groups need the host split planner:
        the microloop must abort, and the ladder must still deliver."""
        from karpenter_provider_aws_tpu.apis import wellknown as wk
        from karpenter_provider_aws_tpu.apis.objects import PodAffinityTerm
        solver = Solver(lattice, mesh=plan_mesh("8").mesh)
        pods = [Pod(name=f"aff{i}",
                    requests={"cpu": "500m", "memory": "512Mi"},
                    pod_affinity=[PodAffinityTerm(
                        topology_key=wk.LABEL_HOSTNAME, anti=False,
                        label_selector=(("app", "aff"),))],
                    labels={"app": "aff"}) for i in range(6)]
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        if not problem.single_bin.any():
            pytest.skip("lattice/problem shape did not produce "
                        "single-bin groups")
        plan = solver.solve_delta(problem)
        assert solver.stats()["micro_aborts"] == 1
        placed = sum(len(n.pods) for n in plan.new_nodes) + sum(
            len(v) for v in plan.existing_assignments.values())
        assert placed + len(plan.unschedulable) == len(pods)


class TestStatsNeverBlocks:
    def test_stats_while_solve_lock_held(self, lattice):
        """The PR 5 pin extended to the microloop counters: stats()
        must return while another thread holds the solve lock."""
        solver = Solver(lattice)
        solver.solve_delta(build_problem(_pods(),
                                         [NodePool(name="default")],
                                         lattice))
        hold = threading.Event()
        release = threading.Event()

        def holder():
            with solver._solve_lock:
                hold.set()
                release.wait(5.0)

        t = threading.Thread(target=holder, daemon=True)
        t.start()
        assert hold.wait(5.0)
        try:
            done = threading.Event()
            out = {}

            def snap():
                out["st"] = solver.stats()
                done.set()

            threading.Thread(target=snap, daemon=True).start()
            assert done.wait(2.0), "stats() blocked on the solve lock"
            for key in ("micro_solves", "micro_last_legs",
                        "micro_skipped_syncs", "link_upload_legs",
                        "link_fetch_bytes", "micro_engaged"):
                assert key in out["st"]
        finally:
            release.set()
            t.join(5.0)


class TestJournalCoalescer:
    def test_contiguous_ticks_merge(self):
        from karpenter_provider_aws_tpu.state.cluster import (
            ClusterState, DirtyJournalCoalescer)
        cs = ClusterState()
        co = DirtyJournalCoalescer(cs)
        base = cs.state_rev
        cs.add_pod(Pod(name="a", requests={"cpu": "1"}))
        co.tick(base)
        cs.add_pod(Pod(name="b", requests={"cpu": "1"}))
        co.tick(base)
        cs.touch_capacity()
        d = co.take(base)
        assert d.since == base and d.rev == cs.state_rev
        assert {"a", "b"} <= d.pods and d.bins
        assert not d.full
        # matches what one direct walk would have answered
        direct = cs.dirty_since(base)
        assert d.pods == direct.pods and d.bins == direct.bins

    def test_anchor_mismatch_falls_back(self):
        from karpenter_provider_aws_tpu.state.cluster import (
            ClusterState, DirtyJournalCoalescer)
        cs = ClusterState()
        co = DirtyJournalCoalescer(cs)
        cs.add_pod(Pod(name="x", requests={"cpu": "1"}))
        mid = cs.state_rev
        co.tick(0)                       # pending set anchored at 0
        cs.add_pod(Pod(name="y", requests={"cpu": "1"}))
        d = co.take(mid)                 # builder rebuilt at `mid`
        assert co.fallbacks == 1
        assert "y" in d.pods and "x" not in d.pods
        assert d.since == mid

    def test_ticks_counted(self):
        from karpenter_provider_aws_tpu.state.cluster import (
            ClusterState, DirtyJournalCoalescer)
        cs = ClusterState()
        co = DirtyJournalCoalescer(cs)
        base = cs.state_rev
        for i in range(3):
            cs.add_pod(Pod(name=f"t{i}", requests={"cpu": "1"}))
            co.tick(base)
        d = co.take(base)
        assert d.ticks >= 3


class TestLinkAccounting:
    def test_full_solve_counts_legs_both_directions(self, lattice):
        solver = Solver(lattice)
        solver.solve(build_problem(_pods(), [NodePool(name="default")],
                                   lattice))
        ls = solver.link_stats
        assert ls["upload_legs"] >= 1 and ls["upload_bytes"] > 0
        assert ls["fetch_legs"] >= 1 and ls["fetch_bytes"] > 0

    def test_metrics_mirror(self, lattice):
        """The provisioner mirrors solver link counters into the
        karpenter_solver_link_* families by per-pass delta."""
        from karpenter_provider_aws_tpu.metrics import (Registry,
                                                        wire_core_metrics)
        reg = Registry()
        m = wire_core_metrics(reg)
        assert "solver_link_legs" in m and "solver_link_bytes" in m
        text = reg.render()
        assert "karpenter_solver_link_legs_total" in text
        assert "karpenter_solver_link_bytes_total" in text
