"""PodDisruptionBudget + do-not-disrupt semantics.

Behavioral spec: reference website concepts/disruption.md —
:33  the terminator evicts via the Eviction API to respect PDBs and waits
     for a full drain before terminating,
:112 a zero-allowance pdb renders a node Unconsolidatable,
:253/:282/:294 the `karpenter.sh/do-not-disrupt` annotation on a pod,
     node, or NodePool template blocks voluntary disruption candidacy.
"""

import pytest

from karpenter_provider_aws_tpu.apis import (
    NodePool, Operator as ReqOp, Pod, PodDisruptionBudget, Requirement,
)
from karpenter_provider_aws_tpu.apis.objects import NodePoolDisruption, PodAffinityTerm
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.utils.clock import FakeClock

_FAMILIES = ("m5", "c5", "t3")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog() if s.family in _FAMILIES])


def make_env(lattice, pools=None):
    clock = FakeClock()
    pools = pools or [NodePool(
        name="default",
        requirements=[Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("on-demand",))],
        disruption=NodePoolDisruption(consolidate_after=5.0))]
    return Operator(options=Options(registration_delay=1.0), lattice=lattice,
                    cloud=FakeCloud(clock), clock=clock, node_pools=pools)


def spread_pods(n, prefix="app", labels=None, **kw):
    """n pods, one per node (hostname anti-affinity within the group)."""
    anti = [PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                            label_selector=(("grp", prefix),), anti=True)]
    return [Pod(name=f"{prefix}-{i}", labels={"grp": prefix, **(labels or {})},
                requests={"cpu": "500m", "memory": "1Gi"},
                pod_affinity=list(anti), **kw) for i in range(n)]


class TestPdbAllowance:
    def test_max_unavailable_math(self, lattice):
        env = make_env(lattice)
        for p in spread_pods(3, "web"):
            env.cluster.add_pod(p)
        env.settle()
        pdb = PodDisruptionBudget(name="web-pdb", label_selector={"grp": "web"},
                                  max_unavailable=1)
        env.cluster.add_pdb(pdb)
        assert env.cluster._pdb_allowance(pdb) == 1
        # one pod unbound -> unavailable consumes the whole budget
        evicted = env.cluster.unbind_pods_on(
            next(iter(env.cluster.nodes)))
        assert len(evicted) == 1
        assert env.cluster._pdb_allowance(pdb) == 0

    def test_min_available_math(self, lattice):
        env = make_env(lattice)
        for p in spread_pods(3, "db"):
            env.cluster.add_pod(p)
        env.settle()
        pdb = PodDisruptionBudget(name="db-pdb", label_selector={"grp": "db"},
                                  min_available=2)
        env.cluster.add_pdb(pdb)
        assert env.cluster._pdb_allowance(pdb) == 1


class TestPdbDrain:
    def test_drain_paced_by_budget_then_completes(self, lattice):
        """Terminating a node whose pods share a maxUnavailable=1 budget
        drains one pod per pass; each evicted pod reschedules and turns
        healthy again, restoring allowance for the next eviction. The node
        and instance are deleted only after the LAST pod left
        (disruption.md:33)."""
        env = make_env(lattice)
        # 4 pods forced onto ONE node via a node-count-limiting selector:
        # bind them by scheduling once, then terminate that node
        for i in range(4):
            env.cluster.add_pod(Pod(name=f"svc-{i}", labels={"app": "svc"},
                                    requests={"cpu": "250m", "memory": "512Mi"}))
        env.settle()
        assert len(env.cluster.nodes) == 1
        victim_claim = next(iter(env.cluster.claims.values()))
        env.cluster.add_pdb(PodDisruptionBudget(
            name="svc-pdb", label_selector={"app": "svc"}, max_unavailable=1))

        env.termination.delete_claim(victim_claim.name)
        env.termination.reconcile()
        # first pass: exactly one pod evicted, node still present
        bound = [p for p in env.cluster.pods.values() if p.node_name]
        assert len(bound) == 3
        assert victim_claim.name in env.cluster.claims
        assert any(e.reason == "DrainBlocked" for e in env.recorder.events())

        # let the control plane reschedule the evicted pod to a NEW node
        # (the victim is cordoned), then keep reconciling: the drain
        # completes one pod per healthy-again cycle
        for _ in range(30):
            env.run_once(force_provision=bool(env.cluster.pending_pods()))
            env.clock.step(2)
            if victim_claim.name not in env.cluster.claims:
                break
        assert victim_claim.name not in env.cluster.claims
        # every pod survived (bound somewhere else once the last evictee
        # reschedules)
        env.settle()
        assert sum(1 for p in env.cluster.pods.values()
                   if p.node_name is not None) == 4

    def test_daemonsets_exempt_from_budget(self, lattice):
        env = make_env(lattice)
        for p in spread_pods(2, "logging"):
            env.cluster.add_pod(p)
        env.settle()
        node = next(iter(env.cluster.nodes))
        env.cluster.add_pod(Pod(name="ds-agent", labels={"grp": "logging"},
                                is_daemonset=True, node_name=node,
                                requests={"cpu": "100m"}))
        env.cluster.add_pdb(PodDisruptionBudget(
            name="log-pdb", label_selector={"grp": "logging"},
            max_unavailable=1))
        evicted, blocked = env.cluster.drain_node(node)
        # the daemonset pod neither evicts nor blocks
        assert all(not p.is_daemonset for p in evicted + blocked)
        # and it is DELETED with its node, not orphaned into phantom
        # daemonset overhead for future node sizing
        claim_name = env.cluster.nodes[node].node_claim
        env.termination.delete_claim(claim_name)
        for _ in range(5):
            env.termination.reconcile()
            if node not in env.cluster.nodes:
                break
        assert "ds-agent" not in env.cluster.pods


class TestDoNotDisrupt:
    def _consolidatable_env(self, lattice, pod_kw=None, pool_kw=None):
        """One node sized for 4 pods, then 3 deleted: the survivor leaves
        the node under-utilized, so single-node consolidation would
        replace it with a cheaper shape — unless something blocks it."""
        pools = [NodePool(
            name="default",
            requirements=[Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN,
                                      ("on-demand",))],
            disruption=NodePoolDisruption(consolidate_after=5.0),
            **(pool_kw or {}))]
        env = make_env(lattice, pools=pools)
        for i in range(4):
            env.cluster.add_pod(Pod(
                name=f"tiny-{i}", labels={"grp": "tiny"},
                requests={"cpu": "800m", "memory": "1536Mi"},
                **(pod_kw or {})))
        env.settle()
        assert len(env.cluster.claims) == 1
        for i in range(1, 4):
            env.cluster.delete_pod(f"tiny-{i}")
        return env

    def _run_disruption(self, env, rounds=10):
        env.clock.step(6)
        for _ in range(rounds):
            env.run_once(force_provision=bool(env.cluster.pending_pods()))
            env.clock.step(3)

    def test_pod_annotation_blocks_candidacy(self, lattice):
        env = self._consolidatable_env(
            lattice,
            pod_kw={"annotations": {wk.ANNOTATION_DO_NOT_DISRUPT: "true"}})
        before = set(env.cluster.claims)
        self._run_disruption(env)
        assert set(env.cluster.claims) == before, \
            "do-not-disrupt pods must pin their nodes"

    def test_nodepool_annotation_propagates_and_blocks(self, lattice):
        env = self._consolidatable_env(
            lattice,
            pool_kw={"annotations": {wk.ANNOTATION_DO_NOT_DISRUPT: "true"}})
        for c in env.cluster.claims.values():
            assert c.annotations.get(wk.ANNOTATION_DO_NOT_DISRUPT) == "true"
        before = set(env.cluster.claims)
        self._run_disruption(env)
        assert set(env.cluster.claims) == before

    def test_node_annotation_blocks_candidacy(self, lattice):
        env = self._consolidatable_env(lattice)
        for node in env.cluster.nodes.values():
            node.annotations[wk.ANNOTATION_DO_NOT_DISRUPT] = "true"
        before = set(env.cluster.claims)
        self._run_disruption(env)
        assert set(env.cluster.claims) == before

    def test_zero_allowance_pdb_blocks_candidacy(self, lattice):
        env = self._consolidatable_env(lattice)
        env.cluster.add_pdb(PodDisruptionBudget(
            name="tiny-pdb", label_selector={"grp": "tiny"},
            max_unavailable=0))
        before = set(env.cluster.claims)
        self._run_disruption(env)
        assert set(env.cluster.claims) == before
        events = env.recorder.events(reason="Unconsolidatable")
        assert events
        # published once per (node, pdb) blockage episode — not once per
        # reconcile pass per disruption method (the recorder must not
        # flood while a pdb pins a node for days)
        assert len(events) <= len(before)

    def test_without_blockers_consolidation_proceeds(self, lattice):
        """Control: the same shape WITHOUT annotations/PDBs consolidates,
        so the blocked tests above prove causation."""
        env = self._consolidatable_env(lattice)
        before = set(env.cluster.claims)
        self._run_disruption(env, rounds=20)
        assert set(env.cluster.claims) != before


class TestForceDrainBackstop:
    def test_grace_period_unblocks_stuck_termination(self, lattice):
        """A zero-allowance budget cannot bill an instance forever when
        termination_grace_period is set: after the grace the drain
        forces through and the claim terminates."""
        clock = FakeClock()
        env = Operator(options=Options(registration_delay=1.0,
                                       termination_grace_period=60.0),
                       lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                       node_pools=[NodePool(
                           name="default",
                           requirements=[Requirement(wk.LABEL_CAPACITY_TYPE,
                                                     ReqOp.IN, ("on-demand",))])])
        for i in range(2):
            env.cluster.add_pod(Pod(name=f"p-{i}", labels={"app": "stuck"},
                                    requests={"cpu": "500m", "memory": "1Gi"}))
        env.settle()
        env.cluster.add_pdb(PodDisruptionBudget(
            name="frozen", label_selector={"app": "stuck"}, max_unavailable=0))
        victim = next(iter(env.cluster.claims.values()))
        node = env.cluster.node_for_claim(victim.name).name
        env.cluster.add_pod(Pod(name="ds-on-stuck", is_daemonset=True,
                                node_name=node, requests={"cpu": "100m"}))
        env.termination.delete_claim(victim.name)
        env.termination.reconcile()
        assert victim.name in env.cluster.claims  # blocked, still alive
        clock.step(61)
        env.termination.reconcile()
        assert victim.name not in env.cluster.claims
        assert env.recorder.events(reason="ForceDrained")
        # the daemonset pod died with the force-drained node (no phantom)
        assert "ds-on-stuck" not in env.cluster.pods

    def test_drain_blocked_event_published_once_per_episode(self, lattice):
        env = make_env(lattice)
        for i in range(2):
            env.cluster.add_pod(Pod(name=f"p-{i}", labels={"app": "stuck"},
                                    requests={"cpu": "500m", "memory": "1Gi"}))
        env.settle()
        env.cluster.add_pdb(PodDisruptionBudget(
            name="frozen", label_selector={"app": "stuck"}, max_unavailable=0))
        victim = next(iter(env.cluster.claims.values()))
        env.termination.delete_claim(victim.name)
        for _ in range(20):
            env.termination.reconcile()
        assert len(env.recorder.events(reason="DrainBlocked")) == 1

    def test_daemonset_do_not_disrupt_pins_node(self, lattice):
        """A do-not-disrupt DAEMONSET pod blocks candidacy too (the
        candidate check must see the unfiltered pod list)."""
        pools = [NodePool(
            name="default",
            requirements=[Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN,
                                      ("on-demand",))],
            disruption=NodePoolDisruption(consolidate_after=5.0))]
        env = make_env(lattice, pools=pools)
        for i in range(4):
            env.cluster.add_pod(Pod(name=f"tiny-{i}", labels={"grp": "tiny"},
                                    requests={"cpu": "800m", "memory": "1536Mi"}))
        env.settle()
        assert len(env.cluster.claims) == 1
        node = next(iter(env.cluster.nodes))
        env.cluster.add_pod(Pod(
            name="ds-pinned", is_daemonset=True, node_name=node,
            annotations={wk.ANNOTATION_DO_NOT_DISRUPT: "true"},
            requests={"cpu": "100m"}))
        for i in range(1, 4):
            env.cluster.delete_pod(f"tiny-{i}")
        before = set(env.cluster.claims)
        env.clock.step(6)
        for _ in range(10):
            env.run_once(force_provision=bool(env.cluster.pending_pods()))
            env.clock.step(3)
        assert set(env.cluster.claims) == before
