"""End-to-end control plane driven ENTIRELY through the fake apiserver.

The reference's envtest stratum: scenario code speaks only the API
protocol (create/delete/list through the typed client); controllers
observe through informer-fed ClusterState and write through the
ApiWriter; ZERO direct FakeCloud/ClusterState mutation happens here
(reference pkg/test/environment.go:83-162, cmd/controller/main.go:47-53).

Covered flow: provision (pods → claims → instances → nodes → binds) →
watch-driven config (a NodePool created through the API) → disruption
(consolidation drains through the PDB-enforced eviction subresource) →
termination (finalizer-gated NodeClaim removal).
"""

import pytest

from karpenter_provider_aws_tpu.apis import (
    NodePool, Pod, PodDisruptionBudget, Requirement,
)
from karpenter_provider_aws_tpu.apis import Operator as ReqOp
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.apis.objects import NodeClaimPhase
from karpenter_provider_aws_tpu.kube import FakeAPIServer, KubeClient
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.utils.clock import FakeClock


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog()
                          if s.family in ("m5", "c5", "t3")])


def make_env(lattice, **operator_kw):
    clock = FakeClock()
    server = FakeAPIServer(clock=clock)
    op = Operator(options=Options(registration_delay=1.0),
                  lattice=lattice, clock=clock, api_server=server,
                  **operator_kw)
    return clock, server, KubeClient(server), op


def run_pod(name, cpu="1", **kw):
    return Pod(name=name, requests={"cpu": cpu, "memory": "2Gi"}, **kw)


class TestProvisionThroughAPI:
    def test_pods_via_api_get_nodes_and_bind(self, lattice):
        clock, server, client, op = make_env(lattice)
        for i in range(5):
            client.create_pod(run_pod(f"p{i}"))
        op.settle()
        # server truth: every pod bound, nodes + claims materialized
        pods = client.list_pods()
        assert all(p.node_name for p in pods)
        nodes = client.list_nodes()
        assert nodes, "no nodes registered through the API"
        claims = client.list_nodeclaims()
        assert claims and all(c.phase == NodeClaimPhase.INITIALIZED
                              for c in claims)
        assert all(c.provider_id for c in claims)
        # the mirror agrees with the server (informer-fed)
        assert {n.name for n in nodes} == set(op.cluster.nodes)
        assert {p.name for p in pods} == set(op.cluster.pods)

    def test_cluster_state_synced_metric_set(self, lattice):
        clock, server, client, op = make_env(lattice)
        assert op.sync.has_synced
        assert op.metrics.gauge(
            "karpenter_cluster_state_synced").value() == 1.0

    def test_nodepool_created_through_api_is_used(self, lattice):
        """Watch-driven config: a pool that exists ONLY as an API object
        serves pods — the provisioner discovered it via the informer."""
        clock, server, client, op = make_env(lattice)
        client.create_nodepool(NodePool(
            name="team-a",
            labels={"team": "a"},
            requirements=[Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN,
                                      ("on-demand",))]))
        client.create_pod(run_pod("w0", node_selector={"team": "a"}))
        op.settle()
        pods = client.list_pods()
        assert pods[0].node_name
        node = client.get_node(pods[0].node_name)
        assert node.node_pool == "team-a"
        assert node.labels.get("team") == "a"

    def test_invalid_nodepool_rejected_by_admission(self, lattice):
        from karpenter_provider_aws_tpu.kube import InvalidObjectError
        clock, server, client, op = make_env(lattice)
        with pytest.raises(InvalidObjectError):
            client.create_nodepool(NodePool(
                name="bad", requirements=[
                    Requirement(wk.LABEL_OS, ReqOp.IN,
                                ("linux", "windows"))]))

    def test_pod_created_mid_flight_joins_next_batch(self, lattice):
        clock, server, client, op = make_env(lattice)
        client.create_pod(run_pod("first"))
        op.settle()
        n_nodes = len(client.list_nodes())
        client.create_pod(Pod(name="second",
                              requests={"cpu": "500m", "memory": "512Mi"}))
        op.settle()
        pods = {p.name: p for p in client.list_pods()}
        assert pods["second"].node_name
        # small second pod joins existing capacity, no second node
        assert len(client.list_nodes()) == n_nodes


class TestDisruptionThroughAPI:
    def test_emptied_nodes_consolidate_and_claims_finalize(self, lattice):
        clock, server, client, op = make_env(lattice)
        for i in range(6):
            client.create_pod(run_pod(f"p{i}"))
        op.settle()
        assert client.list_nodes()
        # workload shrinks: pods deleted THROUGH the API
        for i in range(6):
            client.delete_pod(f"p{i}")
        # consolidation needs its stabilization window
        for _ in range(40):
            op.run_once()
            clock.step(30.0)
        assert client.list_nodes() == []
        assert client.list_nodeclaims() == []
        # instances actually terminated (observed via the provider surface)
        assert all(i.state == "terminated"
                   for i in op.cloud_provider.list_instances())

    def test_pdb_blocks_drain_until_replacement_healthy(self, lattice):
        """The drain path goes through the server-side Eviction API: a
        zero-allowance PDB blocks it, and the DrainBlocked event
        surfaces."""
        clock, server, client, op = make_env(lattice)
        client.create_pdb(PodDisruptionBudget(
            name="db-pdb", label_selector={"app": "db"}, max_unavailable=0))
        client.create_pod(run_pod("db-0", labels={"app": "db"}))
        op.settle()
        pods = client.list_pods()
        assert pods[0].node_name
        claim = client.list_nodeclaims()[0]
        # deleting the claim through the API starts the finalizer flow
        client.delete_nodeclaim(claim.name, now=clock.now())
        for _ in range(5):
            op.run_once()
            clock.step(1.0)
        # still blocked: node object remains, pod still bound, claim
        # deleting but not gone
        assert client.list_nodes()
        assert client.list_pods()[0].node_name
        assert client.list_nodeclaims()[0].deletion_timestamp
        assert op.recorder.events(reason="DrainBlocked")
        # budget released through the API → drain completes → the old
        # claim finalizes; the evicted pod reschedules onto a FRESH node
        # (eviction = unbind; the workload controller re-creates it)
        old_node = pods[0].node_name
        client.delete_pdb("db-pdb")
        for _ in range(25):
            op.run_once()
            clock.step(2.0)
        assert claim.name not in {c.name for c in client.list_nodeclaims()}
        pod_now = client.list_pods()[0]
        assert pod_now.node_name and pod_now.node_name != old_node
        assert old_node not in {n.name for n in client.list_nodes()}


class TestScenarioIsolation:
    def test_no_direct_mutation_needed_for_full_lifecycle(self, lattice):
        """The complete provision→disrupt→terminate lifecycle with the
        scenario touching ONLY the client: the VERDICT r3 'done' bar."""
        clock, server, client, op = make_env(lattice)
        # provision
        for i in range(4):
            client.create_pod(run_pod(f"a{i}"))
        op.settle()
        assert all(p.node_name for p in client.list_pods())
        # disrupt (shrink workload, consolidation empties nodes)
        for i in range(4):
            client.delete_pod(f"a{i}")
        for _ in range(40):
            op.run_once()
            clock.step(30.0)
        # terminate: everything gone, server-side and mirror-side
        assert client.list_nodes() == []
        assert client.list_nodeclaims() == []
        assert op.cluster.nodes == {} and op.cluster.claims == {}


class TestWatchDrivenConfigGuard:
    def test_cross_object_invalid_pool_not_installed(self, lattice):
        """Per-object admission can't see across objects: a linux-os pool
        referencing a Windows NodeClass passes the webhook but must be
        rejected by the cross-object guard when it arrives via watch."""
        from karpenter_provider_aws_tpu.apis import NodeClass
        clock, server, client, op = make_env(lattice)
        client.create_nodeclass(NodeClass(name="win", ami_family="Windows", role="r"))
        # webhook defaulting pins os=linux on an os-less pool
        client.create_nodepool(NodePool(name="broken", node_class_ref="win"))
        op.sync_once()
        assert "broken" not in op.node_pools
        assert op.recorder.events(reason="InvalidConfig")
        # a valid pool arriving the same way still installs
        client.create_nodepool(NodePool(name="ok"))
        op.sync_once()
        assert "ok" in op.node_pools

    def test_nodeclass_change_revalidates_referencing_pools(self, lattice):
        """Deleting/replacing a NodeClass re-runs the guard over pools
        referencing it — a cure installs the pool, a break evicts it."""
        from karpenter_provider_aws_tpu.apis import NodeClass, Requirement
        from karpenter_provider_aws_tpu.apis import Operator as ROp
        clock, server, client, op = make_env(lattice)
        client.create_nodepool(NodePool(
            name="winpool", node_class_ref="family",
            requirements=[Requirement(wk.LABEL_OS, ROp.IN, ("windows",))]))
        op.sync_once()
        assert "winpool" in op.node_pools   # class unknown: tolerated
        client.create_nodeclass(NodeClass(name="family", ami_family="AL2023", role="r"))
        op.sync_once()
        # now the pair contradicts (windows pool, linux family): evicted
        assert "winpool" not in op.node_pools
        assert op.recorder.events(reason="InvalidConfig")


class TestInterruptionThroughAPI:
    def test_spot_interruption_drains_and_replaces_via_api(self, lattice):
        """The interruption flow in API mode: a spot message cordons and
        drains through the ApiWriter (eviction subresource, finalizer
        removal), the pod reschedules, and the doomed node disappears
        server-side."""
        from karpenter_provider_aws_tpu.interruption import (
            FakeQueue, spot_interruption,
        )
        from karpenter_provider_aws_tpu.cloud.fake import parse_instance_id
        clock = FakeClock()
        server = FakeAPIServer(clock=clock)
        queue = FakeQueue("e2e-int")
        # note: API-mode admission DEFAULTS an os/capacity-less pool to
        # on-demand; the spot→ICE path needs an explicitly spot pool
        spot_pool = NodePool(name="default", requirements=[
            Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("spot",))])
        op = Operator(options=Options(registration_delay=1.0,
                                      interruption_queue="e2e-int"),
                      lattice=lattice, clock=clock, api_server=server,
                      node_pools=[spot_pool],
                      interruption_queue=queue)
        client = KubeClient(server)
        client.create_pod(run_pod("w0"))
        op.settle()
        assert client.list_nodeclaims()[0].capacity_type == "spot"
        claim = client.list_nodeclaims()[0]
        old_node = client.list_pods()[0].node_name
        queue.send(spot_interruption(parse_instance_id(claim.provider_id)))
        op.settle(max_rounds=60)
        # old claim finalized through the API; the pod rides a new node
        assert claim.name not in {c.name for c in client.list_nodeclaims()}
        pod = client.list_pods()[0]
        assert pod.node_name and pod.node_name != old_node
        assert old_node not in {n.name for n in client.list_nodes()}
        # the interrupted offering went into the ICE mask
        assert any(True for _ in op.unavailable.entries())


class TestNodePoolDeletionCascadeAPI:
    def test_pool_deleted_over_api_drains_nodes(self, lattice):
        """The cascade in API mode keys off the nodepools INFORMER
        store: deleting the pool at the server drains its claims."""
        clock, server, client, op = make_env(lattice)
        client.create_nodepool(NodePool(name="team-b", weight=90))
        op.sync_once()
        for i in range(3):
            client.create_pod(run_pod(f"cb{i}"))
        op.settle()
        mine = [c for c in client.list_nodeclaims()
                if c.node_pool == "team-b"]
        assert mine, "pods landed on the default pool, scenario vacuous"
        client.delete_nodepool("team-b")
        # settle() exits on no-pending; give the drain full rounds
        for _ in range(6):
            op.settle()
            clock.step(5.0)
        left = [c for c in client.list_nodeclaims()
                if c.node_pool == "team-b" and not c.deletion_timestamp]
        assert not left, left
        # the displaced pods rebound onto surviving capacity
        assert all(p.node_name for p in client.list_pods())

    def test_invalid_config_pool_does_not_cascade(self, lattice):
        """A pool the cross-object config guard rejects leaves the
        ACTIVE dict but still exists at the server — its nodes must
        survive the config hiccup (the cascade consults the informer
        store, not the guarded dict)."""
        clock, server, client, op = make_env(lattice)
        for i in range(2):
            client.create_pod(run_pod(f"cg{i}"))
        op.settle()
        assert client.list_nodeclaims()
        # break the default pool's config: os the amiFamily can't serve
        bad = next(p for p in client.list_nodepools()
                   if p.name == "default")
        bad.requirements = [Requirement(
            wk.LABEL_OS, ReqOp.IN, ("windows",))]
        client.update_nodepool(bad)
        op.settle()
        # guard rejected it from the active dict...
        assert "default" not in op.node_pools
        # ...but no claim drains: the pool still exists at the server
        assert all(not c.deletion_timestamp
                   for c in client.list_nodeclaims())

    def test_cascade_publishes_one_event_per_claim(self, lattice):
        """The mirror's deletion_timestamp lags the server write by one
        informer pump; GC ticks inside that window must not re-publish
        NodePoolDeleted for the same claim."""
        clock, server, client, op = make_env(lattice)
        client.create_nodepool(NodePool(name="team-c", weight=90))
        op.sync_once()
        for i in range(3):
            client.create_pod(run_pod(f"cc{i}"))
        op.settle()
        n_claims = len([c for c in client.list_nodeclaims()
                        if c.node_pool == "team-c"])
        assert n_claims
        client.delete_nodepool("team-c")
        op.sync_once()           # pool deletion reaches the informer store
        op.gc.reconcile()        # cascades; mirror claims not yet updated
        op.gc.reconcile()        # second tick inside the lag window
        evs = op.recorder.events(reason="NodePoolDeleted")
        assert len(evs) == n_claims, [e.object_name for e in evs]


class TestEventsThroughAPI:
    """Controller events are wire-visible objects (kind ``events``) —
    the `kubectl get events` debugging flow of the reference docs."""

    def test_lifecycle_events_mirror_into_apiserver(self, lattice):
        clock, server, client, op = make_env(lattice)
        client.create_pod(run_pod("evt-p0"))
        op.settle()
        objs, _ = server.list("events")
        reasons = [o["spec"]["reason"] for o in objs]
        for expected in ("Launched", "Registered", "Initialized"):
            assert expected in reasons, reasons
        # mirrored stream preserves publish order vs the in-memory ring
        assert reasons == [e.reason for e in op.recorder.events()][-len(reasons):]

    def test_kpctl_renders_events_table(self, lattice, capsys, monkeypatch):
        import pathlib
        monkeypatch.syspath_prepend(str(
            pathlib.Path(__file__).resolve().parent.parent / "tools"))
        import kpctl
        clock, server, client, op = make_env(lattice)
        client.create_pod(run_pod("evt-p1"))
        op.settle()
        objs, _ = server.list("events")
        kpctl.print_table("events", objs)
        out = capsys.readouterr().out
        assert "REASON" in out and "Launched" in out
        assert "NodeClaim/" in out


class TestNodePoolStatusResources:
    """Live pool usage surfaces as the wire object's controller-owned
    status sub-map (envelope status.resources — the reference NodePool's
    status.resources), OUTSIDE the user-owned spec."""

    def test_usage_patched_onto_pool_object(self, lattice):
        clock, server, client, op = make_env(lattice)
        for i in range(3):
            client.create_pod(run_pod(f"sr-{i}"))
        op.settle()
        obj = server.get("nodepools", "default")
        # the spec/status split: live usage never rides the user spec
        assert "statusResources" not in obj["spec"]
        sr = obj["status"]["resources"]
        assert sr.get("cpu", "").endswith("m")
        assert sr.get("memory", "").endswith("Mi")
        assert int(sr["pods"]) >= 3
        # quantity strings parse back to the mirror's usage vector
        from karpenter_provider_aws_tpu.apis.resources import (
            axis, resources_to_vec)
        vec = resources_to_vec(sr)
        assert vec[axis("cpu")] == op.cluster.pool_usage()["default"][
            axis("cpu")]

    def test_usage_clears_when_nodes_terminate(self, lattice):
        clock, server, client, op = make_env(lattice)
        client.create_pod(run_pod("sr-gone"))
        op.settle()
        client.delete_pod("sr-gone")
        # consolidation needs its stabilization window to empty the node
        for _ in range(40):
            op.run_once()
            clock.step(30.0)
        # the node is gone; usage axes drop out of the status (the
        # merge-patch carries explicit deletes for zeroed axes)
        assert client.list_nodes() == []
        sr = server.get("nodepools", "default")["status"]["resources"]
        assert not sr, sr

    def test_user_apply_preserves_status(self, lattice):
        """The spec/status split: a user apply (full-spec update) can
        never touch the controller-owned status — a `kpctl get -o yaml |
        kpctl apply` round-trip no longer re-submits stale usage (ADVICE
        r5), and a legacy spec carrying statusResources has it stripped
        by admission normalization."""
        from karpenter_provider_aws_tpu.apis import serde
        clock, server, client, op = make_env(lattice)
        client.create_pod(run_pod("sr-apply"))
        op.settle()
        before = server.get("nodepools", "default")["status"]["resources"]
        assert before
        # user-style apply: serde round-trip of a FRESH pool spec, like
        # kpctl apply -f would PUT — plus a stale legacy statusResources
        # key as an old exported YAML would carry
        spec = serde.nodepool_to_dict(NodePool(name="default", weight=7))
        spec["statusResources"] = {"cpu": "999"}
        import copy
        obj = copy.deepcopy(server.get("nodepools", "default"))
        obj["spec"] = spec
        server.update("nodepools", obj)
        after = server.get("nodepools", "default")
        assert after["status"]["resources"] == before
        assert after["spec"].get("weight") == 7
        # admission normalization strips the legacy in-spec status key
        assert "statusResources" not in after["spec"]
        op.run_once()
        sr = server.get("nodepools", "default")["status"]["resources"]
        assert sr.get("cpu", "").endswith("m"), sr

    def test_status_cache_pruned_on_pool_delete(self, lattice):
        """Deleted pools leave _pool_status_cache (review r5: unbounded
        growth under per-job pool churn)."""
        clock, server, client, op = make_env(lattice)
        client.create_nodepool(NodePool(name="job-1", weight=9))
        client.create_pod(run_pod("jp", node_selector={
            "karpenter.sh/nodepool": "job-1"}))
        op.settle()
        assert "job-1" in op._pool_status_cache
        client.delete_pod("jp")
        for _ in range(40):
            op.run_once()
            clock.step(30.0)
        client.delete_nodepool("job-1")
        for _ in range(10):
            op.run_once()
            clock.step(30.0)
        assert "job-1" not in op.node_pools
        assert "job-1" not in op._pool_status_cache
