"""Every shipped example applies cleanly — and does what it says.

The reference's examples/ gallery is untested YAML; ours is pinned:
each file round-trips kpctl's document loader and the apiserver's full
admission chain (schema + webhooks), and the scenario-bearing ones are
exercised against the solver so the example's *behavior* is true, not
just its syntax.
"""

import pathlib
import sys

import pytest

from karpenter_provider_aws_tpu.kube import FakeAPIServer, install_admission

REPO = pathlib.Path(__file__).resolve().parent.parent
EXAMPLES = sorted((REPO / "examples").rglob("*.yaml"))

sys.path.insert(0, str(REPO / "tools"))
import kpctl  # noqa: E402  (the SHIPPED loader — what apply -f runs)


def load_documents(path):
    return kpctl.load_documents(str(path))


@pytest.mark.parametrize("path", EXAMPLES, ids=lambda p: p.name)
def test_example_passes_admission(path):
    from karpenter_provider_aws_tpu.apis import serde
    from karpenter_provider_aws_tpu.apis.resources import resources_to_vec
    s = FakeAPIServer()
    install_admission(s)
    docs = load_documents(path)
    assert docs, f"{path} holds no documents"
    for d in docs:
        assert set(d) == {"kind", "spec"}, f"{path}: non-wire document"
        s.create(d["kind"], d["spec"])   # raises InvalidObjectError on drift
        assert s.get(d["kind"], d["spec"]["name"])
        if d["kind"] == "pods":
            # no admission hook is installed for pods — validate via the
            # typed round-trip instead, and require REAL resource demand
            # (a typo'd requests key would silently stop inflating)
            pod = serde.pod_from_dict(d["spec"])
            assert resources_to_vec(pod.requests).sum() > 0, d["spec"]


def test_readme_table_lists_every_file():
    readme = (REPO / "examples" / "README.md").read_text()
    for p in EXAMPLES:
        rel = p.relative_to(REPO / "examples")
        assert str(rel) in readme, f"examples/README.md misses {rel}"


def test_general_purpose_example_schedules_a_pod():
    """The flagship example provisions: its pool serves a generic pod
    with a current-generation m/c/r type."""
    from karpenter_provider_aws_tpu.apis import Pod, serde
    from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
    from karpenter_provider_aws_tpu.operator import Operator, Options
    from karpenter_provider_aws_tpu.utils.clock import FakeClock

    docs = load_documents(REPO / "examples" / "general-purpose.yaml")
    pools = [serde.nodepool_from_dict(d["spec"]) for d in docs
             if d["kind"] == "nodepools"]
    classes = {d["spec"]["name"]: serde.nodeclass_from_dict(d["spec"])
               for d in docs if d["kind"] == "nodeclasses"}
    lat = build_lattice([s for s in build_catalog()
                         if s.family in ("m5", "c5", "t3", "m6g")])
    op = Operator(options=Options(cluster_name="my-cluster",
                                  registration_delay=1.0),
                  lattice=lat, clock=FakeClock(),
                  node_pools=pools, node_classes=classes)
    op.cluster.add_pod(Pod(name="w0",
                           requests={"cpu": "1", "memory": "2Gi"}))
    op.settle()
    node = next(iter(op.cluster.nodes.values()))
    assert node.node_pool == "general-purpose"
    # the pool's requirements held — asserted on the node's own labels
    # so each requirement is checked directly, not via lattice contents
    assert node.labels["karpenter.k8s.aws/instance-category"] in (
        "c", "m", "r")
    assert int(node.labels["karpenter.k8s.aws/instance-generation"]) > 2


def test_spot_example_launches_spot():
    from karpenter_provider_aws_tpu.apis import Pod, serde
    from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
    from karpenter_provider_aws_tpu.operator import Operator, Options
    from karpenter_provider_aws_tpu.utils.clock import FakeClock

    docs = load_documents(REPO / "examples" / "spot.yaml")
    pools = [serde.nodepool_from_dict(d["spec"]) for d in docs
             if d["kind"] == "nodepools"]
    lat = build_lattice([s for s in build_catalog()
                         if s.family in ("m5", "c5", "r5")])
    op = Operator(options=Options(registration_delay=1.0), lattice=lat,
                  clock=FakeClock(), node_pools=pools)
    op.cluster.add_pod(Pod(name="w0",
                           requests={"cpu": "1", "memory": "2Gi"}))
    op.settle()
    claim = next(iter(op.cluster.claims.values()))
    assert claim.capacity_type == "spot"
