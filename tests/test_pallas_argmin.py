"""Pallas cheapest-offering kernel tests (ops/offering_argmin.py).

The kernel runs in interpreter mode on the CPU mesh (the compiled path is
probed and used on real TPU backends); every case is checked against the
XLA oracle form, including tie-breaking and all-infeasible bins."""

import numpy as np
import pytest
import jax.numpy as jnp

from karpenter_provider_aws_tpu.ops import binpack
from karpenter_provider_aws_tpu.ops.offering_argmin import (
    _ZCP, cheapest_offering_pallas, cheapest_offering_xla,
)


def random_case(rng, B=128, Tp=128, zc_live=8):
    tm = (rng.random((B, Tp)) < 0.4).astype(np.float32)
    zc = np.zeros((B, _ZCP), np.float32)
    zc[:, :zc_live] = (rng.random((B, zc_live)) < 0.6).astype(np.float32)
    pr = np.full((Tp, _ZCP), np.inf, np.float32)
    pr[:, :zc_live] = rng.random((Tp, zc_live)).astype(np.float32) + 0.01
    # some offerings unavailable
    pr[:, :zc_live][rng.random((Tp, zc_live)) < 0.2] = np.inf
    return jnp.asarray(tm), jnp.asarray(zc), jnp.asarray(pr)


class TestKernelParity:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    @pytest.mark.parametrize("B,Tp", [(128, 128), (256, 256), (128, 768)])
    def test_matches_xla_oracle(self, seed, B, Tp):
        rng = np.random.default_rng(seed)
        tm, zc, pr = random_case(rng, B=B, Tp=Tp)
        v_p, i_p = cheapest_offering_pallas(tm, zc, pr, interpret=True)
        v_x, i_x = cheapest_offering_xla(tm, zc, pr)
        np.testing.assert_array_equal(np.asarray(i_p), np.asarray(i_x))
        finite = np.isfinite(np.asarray(v_x))
        np.testing.assert_allclose(np.asarray(v_p)[finite],
                                   np.asarray(v_x)[finite])
        assert np.all(~np.isfinite(np.asarray(v_p)[~finite]))

    def test_ties_resolve_to_lowest_flat_index(self):
        tm = jnp.ones((128, 128), jnp.float32)
        zc = jnp.zeros((128, _ZCP), jnp.float32).at[:, :4].set(1.0)
        pr = jnp.full((128, _ZCP), jnp.inf, jnp.float32).at[:, :4].set(2.5)
        v, i = cheapest_offering_pallas(tm, zc, pr, interpret=True)
        assert np.all(np.asarray(i) == 0)       # first (t=0, zc=0) wins
        assert np.allclose(np.asarray(v), 2.5)

    def test_all_infeasible_bin_reports_inf(self):
        tm = jnp.zeros((128, 128), jnp.float32)
        zc = jnp.ones((128, _ZCP), jnp.float32)
        pr = jnp.ones((128, _ZCP), jnp.float32)
        v, i = cheapest_offering_pallas(tm, zc, pr, interpret=True)
        assert np.all(~np.isfinite(np.asarray(v)))
        assert np.all(np.asarray(i) == 0)


class TestPackIntegration:
    def test_pack_same_plan_with_pallas_finalization(self):
        """Full solve parity: the Pallas finalization (interpret mode)
        produces the identical NodePlan to the XLA finalization."""
        from karpenter_provider_aws_tpu.apis import NodePool, Pod
        from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
        from karpenter_provider_aws_tpu.solver import Solver, build_problem

        lattice = build_lattice([s for s in build_catalog()
                                 if s.family in ("m5", "c5", "t3")])
        pods = [Pod(name=f"p{i}", requests={"cpu": "500m", "memory": "1Gi"})
                for i in range(12)]
        pools = [NodePool(name="default")]

        binpack.disable_pallas_argmin()
        try:
            s1 = Solver(lattice)
            binpack.disable_pallas_argmin()  # Solver probe may not enable
            plan_xla = s1.solve(build_problem(pods, pools, lattice))

            # enable/disable invalidate the pack jit caches themselves
            assert binpack.enable_pallas_argmin(interpret=True)
            s2 = Solver(lattice)
            plan_pal = s2.solve(build_problem(pods, pools, lattice))
        finally:
            binpack.disable_pallas_argmin()

        assert plan_pal.new_node_cost == pytest.approx(plan_xla.new_node_cost)
        assert [(n.instance_type, n.zone, n.capacity_type, sorted(n.pods))
                for n in plan_pal.new_nodes] == \
            [(n.instance_type, n.zone, n.capacity_type, sorted(n.pods))
             for n in plan_xla.new_nodes]
