"""Tracing & flight recorder (trace/, docs/reference/tracing.md).

Covers the span library (contextvars propagation, W3C traceparent wire
format, the disabled fast path's zero-allocation contract), the flight
recorder's TAIL sampling (errored / degraded / over-budget traces pinned
past ring wrap-around — including a real injected-fault degraded device
solve), the Chrome trace-event export, cross-process span ingestion
(the sidecar ships its spans back in the Solve RPC response), and the
/debug/traces read surface.
"""

import json
import threading

import pytest

from karpenter_provider_aws_tpu import trace
from karpenter_provider_aws_tpu.trace import FlightRecorder
from karpenter_provider_aws_tpu.trace.span import NOOP_SPAN, Span
from karpenter_provider_aws_tpu.utils.clock import FakeClock


@pytest.fixture()
def recorder():
    """Tracing enabled with a tiny ring; always restored to disabled."""
    rec = FlightRecorder(ring=8, retained=4, latency_budget_ms=1000.0)
    trace.enable(rec)
    yield rec
    trace.disable()
    trace.get_tracer().recorder = None


@pytest.fixture()
def fake_clock():
    clk = FakeClock(start=1_000.0)
    rec = FlightRecorder(ring=8, retained=4, latency_budget_ms=1000.0)
    trace.enable(rec, clock=clk)
    yield clk, rec
    trace.disable()
    tr = trace.get_tracer()
    tr.recorder = None
    from karpenter_provider_aws_tpu.utils.clock import Clock
    tr.clock = Clock()


class TestTraceparent:
    def test_round_trip(self):
        tid, sid = "ab" * 16, "cd" * 8
        hdr = trace.format_traceparent(tid, sid)
        assert hdr == f"00-{tid}-{sid}-01"
        assert trace.parse_traceparent(hdr) == (tid, sid, True)

    def test_unsampled_flag(self):
        hdr = trace.format_traceparent("ab" * 16, "cd" * 8, sampled=False)
        assert trace.parse_traceparent(hdr) == ("ab" * 16, "cd" * 8, False)

    @pytest.mark.parametrize("bad", [
        None, "", "garbage", "00-short-cdcdcdcdcdcdcdcd-01",
        "00-" + "ab" * 16 + "-" + "cd" * 8,            # missing flags
        "zz-" + "ab" * 16 + "-" + "cd" * 8 + "-01",    # non-hex version
        "ff-" + "ab" * 16 + "-" + "cd" * 8 + "-01",    # forbidden version
        "00-" + "00" * 16 + "-" + "cd" * 8 + "-01",    # all-zero trace
        "00-" + "ab" * 16 + "-" + "00" * 8 + "-01",    # all-zero span
        "00-" + "xy" * 16 + "-" + "cd" * 8 + "-01",    # non-hex trace
    ])
    def test_malformed_headers_never_raise(self, bad):
        assert trace.parse_traceparent(bad) is None


class TestSpans:
    def test_nesting_via_contextvars(self, recorder):
        with trace.span("outer") as outer:
            assert trace.current() is outer
            with trace.span("inner") as inner:
                assert inner.trace_id == outer.trace_id
                assert inner.parent_id == outer.span_id
            assert trace.current() is outer
        assert trace.current() is None

    def test_remote_parent_from_header(self, recorder):
        hdr = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        with trace.span("child", parent=hdr) as sp:
            assert sp.trace_id == "ab" * 16
            assert sp.parent_id == "cd" * 8

    def test_parent_none_forces_new_root(self, recorder):
        with trace.span("outer") as outer:
            with trace.span("root2", parent=None) as sp:
                assert sp.trace_id != outer.trace_id
                assert sp.parent_id is None

    def test_links_accept_spans_headers_and_pairs(self, recorder):
        with trace.span("a") as a:
            pass
        hdr = trace.format_traceparent("ef" * 16, "ab" * 8)
        with trace.span("b", links=[a, hdr, ("12" * 16, "34" * 8)]) as b:
            assert (a.trace_id, a.span_id) in b.links
            assert ("ef" * 16, "ab" * 8) in b.links
            assert ("12" * 16, "34" * 8) in b.links

    def test_capture_and_annotate(self, recorder):
        assert trace.capture() is None
        with trace.span("op") as sp:
            hdr = trace.capture()
            assert hdr == sp.traceparent()
            trace.annotate(flavor="x")
        assert sp.attrs["flavor"] == "x"

    def test_exception_marks_error_status(self, recorder):
        with pytest.raises(ValueError):
            with trace.span("boom") as sp:
                raise ValueError("nope")
        assert sp.status == "error"
        assert "ValueError" in sp.attrs["error"]

    def test_thread_handoff_via_traceparent(self, recorder):
        """The batching seams' hand-off: capture() in the producer,
        parent= in the worker yields one connected trace."""
        out = {}

        def worker(ctx):
            with trace.span("worker", parent=ctx) as sp:
                out["span"] = sp

        with trace.span("producer") as prod:
            t = threading.Thread(target=worker, args=(trace.capture(),))
            t.start()
            t.join()
        assert out["span"].trace_id == prod.trace_id
        assert out["span"].parent_id == prod.span_id

    def test_fake_clock_durations_and_wall_anchor(self, fake_clock):
        clk, rec = fake_clock
        with trace.span("timed") as sp:
            clk.step(0.25)
        assert sp.duration == pytest.approx(0.25)
        assert sp.start == pytest.approx(1_000.0)


class TestDisabledFastPath:
    def test_span_is_shared_noop_singleton(self):
        assert not trace.enabled()
        assert trace.span("a") is NOOP_SPAN
        assert trace.span("b", parent=None, pods=9) is NOOP_SPAN
        with trace.span("c") as sp:
            assert sp is NOOP_SPAN
            assert sp.set(x=1) is NOOP_SPAN
            assert sp.traceparent() is None
        assert trace.current() is None
        assert trace.capture() is None
        trace.annotate(k="v")  # no ambient span: must be a no-op

    def test_no_span_objects_allocated_when_disabled(self):
        """The acceptance contract: tracing disabled, call sites allocate
        NO Span objects (one attribute read + the shared singleton)."""
        import gc
        assert not trace.enabled()
        gc.collect()
        before = len([o for o in gc.get_objects() if isinstance(o, Span)])
        for _ in range(100):
            with trace.span("hot.path", pods=3):
                trace.annotate(deep=True)
        gc.collect()
        after = len([o for o in gc.get_objects() if isinstance(o, Span)])
        assert after == before

    def test_contextvar_untouched_when_disabled(self):
        with trace.span("noop"):
            assert trace.current() is None


class TestTailSampling:
    def _trace(self, name="op", **attrs):
        with trace.span(name, **attrs):
            pass

    def test_boring_traces_evicted_on_ring_wrap(self, recorder):
        for i in range(20):
            self._trace(f"boring{i}")
        assert len(recorder.summaries()) <= recorder.ring_size
        assert recorder.stats["completed"] == 20

    def test_degraded_trace_survives_ring_wrap(self, recorder):
        with trace.span("solve") as sp:
            sp.set(degraded=True)
        pinned = sp.trace_id
        for i in range(3 * recorder.ring_size):
            self._trace(f"boring{i}")
        assert recorder.get(pinned) is not None
        summary = [t for t in recorder.summaries()
                   if t["traceId"] == pinned]
        assert summary and summary[0]["retained"] == "degraded"

    def test_errored_trace_retained(self, recorder):
        with pytest.raises(RuntimeError):
            with trace.span("fail") as sp:
                raise RuntimeError("x")
        for i in range(2 * recorder.ring_size):
            self._trace(f"boring{i}")
        got = [t for t in recorder.summaries()
               if t["traceId"] == sp.trace_id]
        assert got and got[0]["retained"] == "error"

    def test_error_outranks_degraded(self, recorder):
        with pytest.raises(RuntimeError):
            with trace.span("both") as sp:
                sp.set(degraded=True)
                raise RuntimeError("x")
        got = [t for t in recorder.summaries()
               if t["traceId"] == sp.trace_id]
        assert got[0]["retained"] == "error"

    def test_slow_trace_retained_by_latency_budget(self, fake_clock):
        clk, rec = fake_clock
        with trace.span("slowpoke") as sp:
            clk.step(1.5)   # budget is 1000 ms
        got = [t for t in rec.summaries() if t["traceId"] == sp.trace_id]
        assert got and got[0]["retained"] == "slow"
        with trace.span("fast") as sp2:
            clk.step(0.01)
        got2 = [t for t in rec.summaries() if t["traceId"] == sp2.trace_id]
        assert got2 and got2[0]["retained"] is None

    def test_discard_root_drops_trace(self, recorder):
        """An idle reconcile (disruption found nothing) must not churn
        the ring: its root marks discard and the trace vanishes."""
        with trace.span("idle.reconcile") as sp:
            sp.set(discard=True)
        assert recorder.get(sp.trace_id) is None
        assert recorder.stats["discarded"] == 1

    def test_retained_set_bounded(self, recorder):
        """Evidence is bounded: after the ring wraps with fresh traffic,
        only the NEWEST retained_size incidents stay pinned."""
        for i in range(3 * recorder.retained_size):
            with trace.span(f"bad{i}") as sp:
                sp.set(degraded=True)
        for i in range(2 * recorder.ring_size):
            self._trace(f"boring{i}")
        retained = [t for t in recorder.summaries() if t["retained"]]
        assert len(retained) == recorder.retained_size
        newest = {f"bad{i}" for i in range(2 * recorder.retained_size,
                                           3 * recorder.retained_size)}
        assert {t["root"] for t in retained} == newest

    def test_degraded_device_solve_trace_retained_after_wrap(self, recorder):
        """The acceptance scenario end-to-end at the solver layer: an
        INJECTED-FAULT degraded solve's trace survives ring wrap."""
        from karpenter_provider_aws_tpu.apis import NodePool, Pod
        from karpenter_provider_aws_tpu.lattice import (build_catalog,
                                                        build_lattice)
        from karpenter_provider_aws_tpu.solver import (FaultInjector,
                                                       Solver,
                                                       build_problem)
        lattice = build_lattice(
            [s for s in build_catalog() if s.family in ("m5", "c5")])
        solver = Solver(lattice)
        solver.inject_faults(FaultInjector(device_errors=8))
        pods = [Pod(name=f"p{i}", requests={"cpu": "1", "memory": "2Gi"})
                for i in range(8)]
        with trace.span("provision.pass") as root:
            plan = solver.solve(build_problem(
                pods, [NodePool(name="default")], lattice))
        assert plan.degraded and plan.solver_path == "host-ffd"
        for i in range(3 * recorder.ring_size):
            with trace.span(f"boring{i}"):
                pass
        spans = recorder.get(root.trace_id)
        assert spans is not None, "degraded solve trace fell out of the ring"
        names = {s.name for s in spans}
        assert "solver.host_ffd" in names
        got = [t for t in recorder.summaries()
               if t["traceId"] == root.trace_id]
        assert got[0]["retained"] == "degraded"


class TestChromeExport:
    def test_export_shape_and_process_rows(self, recorder):
        with trace.span("root", pods=4) as root:
            with trace.span("child"):
                pass
            with trace.span("remote", svc="sidecar"):
                pass
        doc = recorder.to_chrome(root.trace_id)
        assert doc["displayTimeUnit"] == "ms"
        events = doc["traceEvents"]
        xs = [e for e in events if e["ph"] == "X"]
        assert len(xs) == 3
        for e in xs:
            assert {"name", "ph", "cat", "ts", "dur", "pid", "tid",
                    "args"} <= set(e)
            assert e["args"]["traceId"] == root.trace_id
        # one process_name metadata row per service
        metas = {e["args"]["name"] for e in events if e["ph"] == "M"}
        assert metas == {"operator", "sidecar"}
        # valid JSON end to end
        assert json.loads(json.dumps(doc)) == doc

    def test_export_unknown_trace_is_none(self, recorder):
        assert recorder.to_chrome("ff" * 16) is None

    def test_links_and_scalar_attrs_exported(self, recorder):
        with trace.span("a") as a:
            pass
        with trace.span("b", links=[a], n=3, deep=True,
                        blob={"not": "scalar"}) as b:
            pass
        doc = recorder.to_chrome(b.trace_id)
        (ev,) = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert ev["args"]["n"] == 3 and ev["args"]["deep"] is True
        assert "blob" not in ev["args"]          # non-scalar dropped
        assert ev["args"]["links"] == [f"{a.trace_id}:{a.span_id}"]


class TestIngest:
    def _wire_span(self, trace_id, span_id, parent_id=None, name="remote",
                   **attrs):
        return {"name": name, "traceId": trace_id, "spanId": span_id,
                "parentId": parent_id, "svc": "sidecar", "thread": 7,
                "start": 1000.0, "durationMs": 12.5, "status": "ok",
                "attrs": attrs, "links": []}

    def test_ingest_joins_open_trace(self, recorder):
        with trace.span("local.root") as root:
            n = recorder.ingest([self._wire_span(
                root.trace_id, "aa" * 8, parent_id=root.span_id)])
            assert n == 1
        spans = recorder.get(root.trace_id)
        assert {s.svc for s in spans} == {"operator", "sidecar"}
        remote = [s for s in spans if s.svc == "sidecar"][0]
        assert remote.parent_id == root.span_id
        assert remote.duration == pytest.approx(0.0125)

    def test_ingest_dedupes_by_span_id(self, recorder):
        """The in-process sidecar shares the recorder: its spans arrive
        once locally and once over the wire — they must not double."""
        with trace.span("local.root") as root:
            w = self._wire_span(root.trace_id, "aa" * 8)
            assert recorder.ingest([w, w]) == 1
            assert recorder.ingest([w]) == 0
        assert len(recorder.get(root.trace_id)) == 2

    def test_remote_degraded_span_pins_trace(self, recorder):
        """Tail sampling sees the ingested subtree: a solve that degraded
        only in the SIDECAR still pins the whole trace."""
        with trace.span("local.root") as root:
            recorder.ingest([self._wire_span(
                root.trace_id, "aa" * 8, degraded=True)])
        for i in range(3 * recorder.ring_size):
            with trace.span(f"boring{i}"):
                pass
        got = [t for t in recorder.summaries()
               if t["traceId"] == root.trace_id]
        assert got and got[0]["retained"] == "degraded"

    def test_ingest_standalone_trace_finalizes(self, recorder):
        n = recorder.ingest([self._wire_span("ab" * 16, "aa" * 8)])
        assert n == 1
        assert recorder.get("ab" * 16) is not None

    def test_imported_span_round_trips(self):
        from karpenter_provider_aws_tpu.trace import ImportedSpan
        d = self._wire_span("ab" * 16, "aa" * 8, parent_id="cd" * 8, n=3)
        assert ImportedSpan(d).to_dict() == d


class TestDebugDoc:
    def test_list_and_get_routes(self, recorder):
        with trace.span("served") as sp:
            pass
        doc = recorder.debug_doc("/debug/traces", {})
        assert doc["ring"] == recorder.ring_size
        assert any(t["traceId"] == sp.trace_id for t in doc["traces"])
        one = recorder.debug_doc(f"/debug/traces/{sp.trace_id}", {})
        assert one["traceId"] == sp.trace_id
        assert one["spans"][0]["name"] == "served"

    def test_chrome_format_and_misses(self, recorder):
        with trace.span("served") as sp:
            pass
        chrome = recorder.debug_doc(f"/debug/traces/{sp.trace_id}",
                                    {"format": ["chrome"]})
        assert "traceEvents" in chrome
        assert recorder.debug_doc("/debug/traces/" + "ff" * 16, {}) is None
        assert recorder.debug_doc("/debug/other", {}) is None

    def test_failed_write_is_recorded_as_error(self, recorder):
        """A failed POST's span must finish status=error (the 3 a.m.
        evidence): the handler's except runs OUTSIDE the span, so the
        exception is seen at span exit before the error response."""
        import urllib.error
        import urllib.request

        from karpenter_provider_aws_tpu.kube.apiserver import FakeAPIServer
        from karpenter_provider_aws_tpu.kube.httpserver import serve

        httpd = serve(FakeAPIServer(), port=0)
        try:
            port = httpd.server_address[1]
            tid = "ab" * 16
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/apis/pods", method="POST",
                data=b'{"no": "name"}',
                headers={"Content-Type": "application/json",
                         "traceparent": f"00-{tid}-{'cd' * 8}-01"})
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(req)
        finally:
            httpd.shutdown()
        spans = recorder.get(tid)
        assert spans and spans[0].status == "error"
        got = [t for t in recorder.summaries() if t["traceId"] == tid]
        assert got and got[0]["retained"] == "error"

    def test_exemplar_renders_as_scrape_safe_comment(self, recorder):
        """Classic text-format scrapes must survive exemplars: the trace
        id rides a COMMENT line, never the sample line itself."""
        from karpenter_provider_aws_tpu.metrics import Histogram
        h = Histogram("t_hist", "h", buckets=(1.0,), labelnames=("stage",))
        h.observe(0.5, exemplar="ab" * 16, stage="compute")
        lines = h._render()
        samples = [l for l in lines if not l.startswith("#")]
        assert all("#" not in l for l in samples), samples
        comments = [l for l in lines if l.startswith("# exemplar")]
        assert len(comments) == 1 and "ab" * 16 in comments[0]
        assert h.exemplar(stage="compute") == ("ab" * 16, 0.5)
        # no exemplar observed → byte-identical classic rendering
        h2 = Histogram("t_hist2", "h", buckets=(1.0,))
        h2.observe(0.5)
        assert not [l for l in h2._render() if l.startswith("# exemplar")]

    def test_served_over_http(self, recorder):
        """The kube httpserver mounts the same doc at /debug/traces."""
        import urllib.request

        from karpenter_provider_aws_tpu.kube.apiserver import FakeAPIServer
        from karpenter_provider_aws_tpu.kube.httpserver import serve

        with trace.span("wire.visible") as sp:
            pass
        httpd = serve(FakeAPIServer(), port=0)
        try:
            port = httpd.server_address[1]
            base = f"http://127.0.0.1:{port}"
            with urllib.request.urlopen(f"{base}/debug/traces") as r:
                listing = json.loads(r.read())
            assert any(t["traceId"] == sp.trace_id
                       for t in listing["traces"])
            url = f"{base}/debug/traces/{sp.trace_id}?format=chrome"
            with urllib.request.urlopen(url) as r:
                chrome = json.loads(r.read())
            assert chrome["traceEvents"]
        finally:
            httpd.shutdown()
