"""Saturation observatory tests (docs/reference/headroom.md).

FakeClock-driven forecaster math (EWMA fill/drain convergence, the
linear-fill time-to-exhaustion check, drain-beats-fill = infinite
headroom), probe-error isolation, drop-counter parity, the monotonic
high-water pin (registry AND the apiserver's watch_max_depth), the
once-per-episode high-water capture, ring-kind exclusion from ranking
and capture, and the operator wiring (>= 12 probes, the `headroom`
provider, /debug/headroom, the registry-read folds).
"""

import json

import pytest

from karpenter_provider_aws_tpu import introspect
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.introspect.headroom import (
    DEFAULT_HIGH_WATER_FRACTION, HeadroomRegistry)
from karpenter_provider_aws_tpu.kube.apiserver import FakeAPIServer
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.utils.clock import FakeClock


class ScriptedQueue:
    """A probe whose depth/drops follow a script the test controls."""

    def __init__(self, capacity=1000.0, kind="queue"):
        self.depth = 0.0
        self.capacity = capacity
        self.drops = 0.0
        self.kind = kind

    def probe(self):
        return {"depth": self.depth, "capacity": self.capacity,
                "drops": self.drops, "kind": self.kind}


class CaptureSpy:
    def __init__(self):
        self.calls = []

    def capture(self, reason, **evidence):
        self.calls.append((reason, evidence))


def registry(clock=None, **kw):
    return HeadroomRegistry(clock or FakeClock(), **kw)


class TestForecasterMath:
    def test_ewma_fill_rate_converges_on_linear_fill(self):
        clock = FakeClock()
        hr = registry(clock)
        q = ScriptedQueue(capacity=100_000.0)
        hr.register_probe("q", q.probe)
        # 5 items/s for 300 s >> tau=30 s: EWMA must converge to 5
        for _ in range(300):
            hr.observe()
            q.depth += 5.0
            clock.step(1.0)
        row = hr.read("q")
        assert row["fill_rate"] == pytest.approx(5.0, rel=0.01)
        assert row["drain_rate"] == pytest.approx(0.0, abs=1e-9)

    def test_tte_matches_linear_fill(self):
        clock = FakeClock()
        hr = registry(clock)
        q = ScriptedQueue(capacity=10_000.0)
        hr.register_probe("q", q.probe)
        for _ in range(300):
            hr.observe()
            q.depth += 4.0
            clock.step(1.0)
        row = hr.read("q")
        expect = (10_000.0 - row["depth"]) / 4.0
        assert row["seconds_to_exhaustion"] == pytest.approx(expect,
                                                             rel=0.02)
        st = hr.stats()
        assert st["first_to_break"] == "q"
        assert st["min_tte_seconds"] == pytest.approx(expect, rel=0.02)

    def test_drain_faster_than_fill_is_infinite_headroom(self):
        clock = FakeClock()
        hr = registry(clock)
        q = ScriptedQueue(capacity=100.0)
        q.depth = 80.0
        hr.register_probe("q", q.probe)
        for _ in range(120):
            hr.observe()
            q.depth = max(q.depth - 0.5, 0.0)   # draining
            clock.step(1.0)
        row = hr.read("q")
        assert row["seconds_to_exhaustion"] is None
        assert row["drain_rate"] > 0.0
        st = hr.stats()
        assert st["min_tte_seconds"] == -1.0 and st["first_to_break"] == ""

    def test_flat_queue_never_forecasts(self):
        clock = FakeClock()
        hr = registry(clock)
        q = ScriptedQueue(capacity=100.0)
        q.depth = 50.0
        hr.register_probe("q", q.probe)
        for _ in range(10):
            hr.observe()
            clock.step(1.0)
        assert hr.read("q")["seconds_to_exhaustion"] is None

    def test_unbounded_resource_never_forecasts(self):
        clock = FakeClock()
        hr = registry(clock)
        q = ScriptedQueue(capacity=0.0)
        hr.register_probe("q", q.probe)
        for _ in range(60):
            hr.observe()
            q.depth += 10.0
            clock.step(1.0)
        assert hr.read("q")["seconds_to_exhaustion"] is None

    def test_drops_count_as_fill_pressure(self):
        """A queue pinned at its bound while dropping is still FILLING
        at the drop rate — the depth delta alone would read 0."""
        clock = FakeClock()
        hr = registry(clock)
        q = ScriptedQueue(capacity=100.0)
        q.depth = 100.0
        hr.register_probe("q", q.probe)
        for _ in range(300):
            hr.observe()
            q.drops += 3.0          # depth stays pinned at the bound
            clock.step(1.0)
        hr.observe()
        row = hr.read("q")
        assert row["fill_rate"] == pytest.approx(3.0, rel=0.01)
        assert row["drops"] == q.drops   # drop-counter parity: the row
        # re-reports the structure's own cumulative counter verbatim

    def test_zero_dt_observation_skips_rate_update(self):
        clock = FakeClock()
        hr = registry(clock)
        q = ScriptedQueue()
        hr.register_probe("q", q.probe)
        hr.observe()
        q.depth += 50.0
        hr.observe()               # same clock reading: no dt
        assert hr.read("q")["fill_rate"] == 0.0

    def test_ranking_tte_then_occupancy_then_name(self):
        clock = FakeClock()
        hr = registry(clock)
        soon = ScriptedQueue(capacity=100.0)
        late = ScriptedQueue(capacity=100_000.0)
        idle_b = ScriptedQueue(capacity=100.0)
        idle_a = ScriptedQueue(capacity=100.0)
        idle_b.depth = 60.0
        hr.register_probe("soon", soon.probe)
        hr.register_probe("late", late.probe)
        hr.register_probe("idle_b", idle_b.probe)
        hr.register_probe("idle_a", idle_a.probe)
        for _ in range(120):
            hr.observe()
            soon.depth = min(soon.depth + 0.5, 95.0)
            late.depth += 0.5
            clock.step(1.0)
        # keep 'soon' filling on the final reads (it plateaus at 95)
        order = [r["resource"] for r in hr.table()]
        assert order[0] == "soon" or order[0] == "late"
        # finite-TTE rows lead; among no-forecast rows occupancy ranks
        assert order.index("idle_b") < order.index("idle_a")


class TestProbeIsolation:
    def test_broken_probe_marks_its_row_only(self):
        clock = FakeClock()
        hr = registry(clock)
        ok = ScriptedQueue(capacity=10.0)
        hr.register_probe("ok", ok.probe)
        hr.register_probe("bad", lambda: 1 / 0)
        for _ in range(3):
            hr.observe()
            clock.step(1.0)
        rows = {r["resource"]: r for r in hr.table()}
        assert "error" in rows["bad"] and "ZeroDivisionError" in \
            rows["bad"]["error"]
        assert "error" not in rows["ok"]
        # one error TRANSITION = one count, not one per sweep
        assert hr.stats()["probe_errors"] == 1.0

    def test_probe_recovery_clears_error(self):
        clock = FakeClock()
        hr = registry(clock)
        state = {"boom": True}

        def flaky():
            if state["boom"]:
                raise RuntimeError("x")
            return {"depth": 1.0, "capacity": 10.0}

        hr.register_probe("flaky", flaky.__call__)
        hr.observe()
        clock.step(1.0)
        state["boom"] = False
        hr.observe()
        assert "error" not in hr.read("flaky")

    def test_missing_depth_is_an_error_not_a_crash(self):
        hr = registry()
        hr.register_probe("bad", lambda: {"capacity": 5.0})
        hr.observe()
        assert "error" in hr.read("bad")

    def test_read_unknown_resource_is_empty(self):
        assert registry().read("nope") == {}

    def test_register_replaces_by_name(self):
        hr = registry()
        hr.register_probe("q", lambda: {"depth": 1.0})
        hr.register_probe("q", lambda: {"depth": 7.0})
        hr.observe()
        assert hr.read("q")["depth"] == 7.0
        hr.unregister_probe("q")
        assert hr.names() == []


class TestMonotonicHighWater:
    def test_registry_high_water_never_resets(self):
        clock = FakeClock()
        hr = registry(clock)
        q = ScriptedQueue(capacity=100.0)
        hr.register_probe("q", q.probe)
        for depth in (10.0, 90.0, 5.0, 40.0):
            q.depth = depth
            hr.observe()
            clock.step(1.0)
        assert hr.read("q")["highwater"] == 90.0

    def test_probe_supplied_high_water_folds_in(self):
        hr = registry()
        hr.register_probe("q", lambda: {"depth": 1.0, "capacity": 10.0,
                                        "highwater": 8.0})
        hr.observe()
        assert hr.read("q")["highwater"] == 8.0

    def test_apiserver_watch_high_water_survives_dropped_watcher(self):
        """The satellite-6 pin: FakeAPIServer.stats()['watch_max_depth']
        was live-watchers-only and RESET when the deep watcher went away
        — it must be monotonic per process."""
        clock = FakeClock()
        api = FakeAPIServer(clock=clock, watch_queue_bound=64)
        w = api.watch("pods")
        for i in range(8):
            api.create("pods", {"name": f"p-{i}"})
        assert api.stats()["watch_max_depth"] >= 8.0
        api.stop_watch(w)
        st = api.stats()
        assert st["watch_max_depth"] >= 8.0, \
            "high water must not reset when the deep watcher is dropped"
        assert st["watch_deepest"] == 0.0   # the LIVE readout may drop
        probe = api.headroom_probe()
        assert probe["highwater"] >= 8.0


class TestEpisodeCapture:
    def test_capture_fires_once_per_episode_and_rearms(self):
        clock = FakeClock()
        hr = registry(clock)
        spy = CaptureSpy()
        hr.attach_capture(spy)
        q = ScriptedQueue(capacity=100.0)
        hr.register_probe("q", q.probe)

        def tick(depth):
            q.depth = depth
            hr.observe()
            clock.step(1.0)

        tick(50.0)
        tick(95.0)        # crosses 0.9: fire
        tick(99.0)        # still above: no second fire
        tick(100.0)
        assert len(spy.calls) == 1
        reason, evidence = spy.calls[0]
        assert reason == "headroom-q"
        assert evidence["resource"] == "q"
        assert evidence["occupancy"] >= DEFAULT_HIGH_WATER_FRACTION
        tick(10.0)        # recovery re-arms
        tick(95.0)        # second episode
        assert len(spy.calls) == 2
        assert hr.read("q")["episodes"] == 2

    def test_ring_kind_never_fires_or_ranks(self):
        clock = FakeClock()
        hr = registry(clock)
        spy = CaptureSpy()
        hr.attach_capture(spy)
        ring = ScriptedQueue(capacity=10.0, kind="ring")
        ring.depth = 10.0   # full by design
        hr.register_probe("ring", ring.probe)
        for _ in range(60):
            hr.observe()
            clock.step(1.0)
        assert spy.calls == []
        row = hr.read("ring")
        assert row["seconds_to_exhaustion"] is None
        assert row["burn"] == 0.0
        assert hr.stats()["saturated"] == 0.0

    def test_capture_failure_does_not_fail_the_sweep(self):
        clock = FakeClock()
        hr = registry(clock)

        class Broken:
            def capture(self, reason, **kw):
                raise RuntimeError("disk full")

        hr.attach_capture(Broken())
        q = ScriptedQueue(capacity=10.0)
        q.depth = 10.0
        hr.register_probe("q", q.probe)
        hr.observe()
        clock.step(1.0)
        hr.observe()
        assert hr.read("q")["episodes"] == 1


_FAMILIES = ("m5", "c5")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog()
                          if s.family in _FAMILIES])


@pytest.fixture()
def op(lattice):
    clock = FakeClock()
    return Operator(options=Options(registration_delay=1.0),
                    lattice=lattice, cloud=FakeCloud(clock), clock=clock)


class TestOperatorWiring:
    def test_at_least_twelve_probes_in_direct_mode(self, op):
        hr = introspect.headroom_registry()
        assert hr is op.headroom
        assert len(hr.names()) >= 12
        for expect in ("journal_ring", "journal_coalescer", "events_ring",
                       "decision_audit_ring", "slo_rings", "burn_captures",
                       "sampler_rings", "cloud_launch_batcher",
                       "cloud_terminate_batcher", "solver_resident_cache",
                       "consolidation_probe_cache", "profiler_stacks"):
            assert expect in hr.names(), expect

    def test_headroom_provider_and_debug_doc(self, op):
        op.emit_gauges()
        snap = introspect.registry().collect()
        assert "headroom" in snap
        assert snap["headroom"]["resources"] >= 12.0
        body, ctype = introspect.debug_doc("/debug/headroom", {})
        assert ctype == "application/json"
        doc = json.loads(body)
        assert doc["enabled"] is True
        assert len(doc["resources"]) >= 12
        for row in doc["resources"]:
            assert {"resource", "kind", "depth", "capacity", "highwater",
                    "drops", "occupancy"} <= set(row)

    def test_gauge_families_emitted_per_resource(self, op):
        op.emit_gauges()
        text = op.metrics.render()
        assert 'karpenter_headroom_depth{resource="journal_ring"}' in text
        assert 'karpenter_headroom_capacity{resource="events_ring"}' in text
        assert "karpenter_headroom_seconds_to_exhaustion" in text

    def test_interruption_gauge_folds_from_registry(self, lattice):
        clock = FakeClock()
        op = Operator(options=Options(registration_delay=1.0,
                                      interruption_queue="q"),
                      lattice=lattice, cloud=FakeCloud(clock), clock=clock)
        assert "interruption_queue" in op.headroom.names()
        op.emit_gauges()
        text = op.metrics.render()
        assert "karpenter_interruption_queue_depth 0" in text

    def test_high_water_fraction_option_reaches_registry(self, lattice):
        clock = FakeClock()
        op = Operator(options=Options(registration_delay=1.0,
                                      headroom_high_water_fraction=0.5),
                      lattice=lattice, cloud=FakeCloud(clock), clock=clock)
        assert op.headroom.high_water_fraction == 0.5

    def test_debug_doc_without_registry_is_error_shaped(self):
        saved = introspect.headroom_registry()
        try:
            introspect.set_headroom(None)
            body, _ = introspect.debug_doc("/debug/headroom", {})
            doc = json.loads(body)
            assert doc["enabled"] is False and "message" in doc
        finally:
            introspect.set_headroom(saved)
