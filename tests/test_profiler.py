"""Continuous-profiling layer tests (docs/reference/profiling.md).

Covers the tentpole contracts of introspect/profiler.py,
introspect/contention.py, and solver/costmodel.py:

- sampling profiler: folded-stack capture of live threads, bounded
  store, Chrome export, FakeClock stamping, the disabled path (nothing
  constructed, nothing allocated, endpoints report the marker),
- contention accounting: uncontended fast path records NO samples,
  contended acquires record wait + owner-at-contention tag, re-entrant
  hold spans, condition queue-wait kept apart from lock-wait, the
  karpenter_lock_wait_seconds histogram, the set_enabled(False)
  pass-through,
- device cost model: compile-time analysis capture (both jax return
  shapes), measured-vs-modeled attribution, bounded shape set,
- burn-triggered capture lifecycle (FakeClock, no sleeps): sustained
  burn -> exactly one retained capture per episode, re-armed on
  recovery, bounded retention under repeated episodes; the slow-pass
  trigger's arm/cooldown; warmup-window passes never trigger,
- operator wiring + both HTTP mounts (/debug/pprof/*), the gzip
  negotiation satellite, log-line trace correlation, and the kpctl
  profile/top surfaces.
"""

import gzip
import json
import logging
import threading
import time
import urllib.request

import pytest

from karpenter_provider_aws_tpu import introspect, trace
from karpenter_provider_aws_tpu.apis import Pod
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.introspect import (BurnCapture,
                                                   SamplingProfiler,
                                                   SloTracker, contention)
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.solver.costmodel import (DeviceCostModel,
                                                         shape_key)
from karpenter_provider_aws_tpu.utils.clock import FakeClock

_FAMILIES = ("m5", "c5")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog()
                          if s.family in _FAMILIES])


@pytest.fixture()
def env(lattice):
    clock = FakeClock()
    return Operator(options=Options(registration_delay=1.0),
                    lattice=lattice, cloud=FakeCloud(clock), clock=clock)


def _parked_thread(name="parked-worker"):
    """A thread parked in a recognizably-named function, for sampling."""
    ev = threading.Event()

    def distinctive_parking_spot():
        ev.wait(10.0)

    t = threading.Thread(target=distinctive_parking_spot, name=name,
                         daemon=True)
    t.start()
    time.sleep(0.02)   # let it reach the wait
    return t, ev


class TestSamplingProfiler:
    def test_folded_capture_of_live_threads(self):
        prof = SamplingProfiler(hz=100)
        t, ev = _parked_thread()
        try:
            for _ in range(3):
                prof.sample_once()
        finally:
            ev.set()
            t.join()
        folded = prof.folded()
        assert "distinctive_parking_spot" in folded
        # thread prefix, root-first order, trailing count
        line = next(ln for ln in folded.splitlines()
                    if "distinctive_parking_spot" in ln)
        assert line.startswith("parked-worker;")
        stack, _, count = line.rpartition(" ")
        assert int(count) >= 3
        frames = stack.split(";")
        # the leaf is the innermost wait, the named fn sits above it
        assert frames.index(next(
            f for f in frames if "distinctive_parking_spot" in f)) \
            < len(frames) - 1

    def test_thread_name_cardinality_normalized(self):
        prof = SamplingProfiler()
        t, ev = _parked_thread(name="Thread-123 (run)")
        try:
            prof.sample_once()
        finally:
            ev.set()
            t.join()
        assert any(k.startswith("Thread-NNN (run);")
                   for k in prof.folded().splitlines())

    def test_bounded_store_drops_overflow(self):
        prof = SamplingProfiler(max_stacks=2)
        with prof._lock:
            prof._counts = {"a;b 1": 1, "c;d 1": 1}
        t, ev = _parked_thread(name="overflow-w")
        try:
            prof.sample_once()
        finally:
            ev.set()
            t.join()
        assert prof.dropped_stacks >= 1
        assert len(prof._counts) == 2

    def test_top_inclusive_and_self(self):
        prof = SamplingProfiler()
        with prof._lock:
            prof._counts = {"t;a;b": 3, "t;a;c": 2, "t;a": 1}
        top = {d["frame"]: d for d in prof.top(10)}
        assert top["a"]["inclusive"] == 6
        assert top["a"]["self"] == 1
        assert top["b"]["inclusive"] == 3 and top["b"]["self"] == 3

    def test_chrome_export_merges_consecutive_samples(self):
        prof = SamplingProfiler(hz=10)
        with prof._lock:
            prof._raw.extend([
                (1.0, "w", ("a", "b")),
                (1.1, "w", ("a", "b")),
                (1.2, "w", ("a", "c")),
            ])
        doc = prof.to_chrome()
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        by_name = {}
        for e in xs:
            by_name.setdefault(e["name"], []).append(e)
        # 'a' spans all three samples as ONE merged event
        assert len(by_name["a"]) == 1
        assert by_name["a"][0]["dur"] >= 0.2 * 1e6
        # 'b' closed when the stack diverged; 'c' opened after
        assert len(by_name["b"]) == 1 and len(by_name["c"]) == 1
        metas = [e for e in doc["traceEvents"] if e["ph"] == "M"]
        assert any(e["name"] == "thread_name" for e in metas)

    def test_fakeclock_stamps_sample_times(self):
        clock = FakeClock(start=500.0)
        prof = SamplingProfiler(hz=10, clock=clock)
        t, ev = _parked_thread(name="clocked-w")
        try:
            prof.sample_once()
            clock.step(5.0)
            prof.sample_once()
        finally:
            ev.set()
            t.join()
        with prof._lock:
            times = sorted({t for t, _, _ in prof._raw})
        assert times == [500.0, 505.0]

    def test_daemon_lifecycle_and_self_measured_overhead(self):
        prof = SamplingProfiler(hz=200).start()
        try:
            deadline = time.monotonic() + 5.0
            while prof.samples < 5 and time.monotonic() < deadline:
                time.sleep(0.01)
        finally:
            prof.stop()
        assert prof.samples >= 5
        stats = prof.stats()
        assert stats["avg_sample_ms"] > 0
        assert stats["running"] == 0.0   # stopped

    def test_disabled_path_allocates_nothing(self):
        """The zero-overhead-when-disabled pin: no published profiler,
        no sampler thread, the provider reports the marker, and the
        endpoint serves the disabled body."""
        assert introspect.profiler_instance() is None
        assert not any(t.name == "sampling-profiler"
                       for t in threading.enumerate())
        assert introspect.profiler_stats() == {"enabled": 0.0}
        body, ctype = introspect.debug_doc("/debug/pprof/profile", {})
        assert b"disabled" in body
        doc = json.loads(introspect.debug_doc(
            "/debug/pprof/profile", {"format": ["json"]})[0])
        assert doc == {"enabled": False}

    def test_reset(self):
        prof = SamplingProfiler()
        with prof._lock:
            prof._counts["x;y"] = 1
        prof.samples = 3
        prof.reset()
        assert prof.folded() == "" and prof.samples == 0


class TestContention:
    def test_uncontended_fast_path_records_no_waits(self):
        lk = contention.lock("t_uncontended")
        for _ in range(5):
            with lk:
                pass
        st = lk.stats
        assert st.acquisitions == 5
        assert st.contended == 0
        assert st.wait_total_s == 0.0
        assert st.owner_tags == {}

    def test_contended_acquire_records_wait_and_owner_tag(self):
        lk = contention.lock("t_contended")
        entered = threading.Event()
        release = threading.Event()

        def holder():
            with lk:
                entered.set()
                release.wait(5.0)

        t = threading.Thread(target=holder, daemon=True)
        t.start()
        assert entered.wait(5.0)
        waited = threading.Event()

        def waiter():
            with lk:
                waited.set()

        w = threading.Thread(target=waiter, daemon=True)
        w.start()
        time.sleep(0.05)   # let the waiter actually block
        release.set()
        assert waited.wait(5.0)
        t.join(5.0)
        w.join(5.0)
        st = lk.stats
        assert st.contended >= 1
        assert st.wait_total_s > 0
        assert st.max_wait_s > 0
        # the waiter resolved the holder's frame at contention time
        assert st.owner_tags
        assert any(":" in tag for tag in st.owner_tags)
        # the holder's hold time (covering the blocked window) recorded
        assert st.max_hold_s > 0

    def test_reentrant_hold_is_one_span(self):
        lk = contention.rlock("t_reentrant")
        with lk:
            with lk:
                pass
        st = lk.stats
        assert st.acquisitions == 2
        assert st.holds == 1   # first-acquire -> last-release

    def test_condition_queue_wait_separate_from_lock_wait(self):
        cond = contention.condition("t_cond")
        got = []

        def consumer():
            with cond:
                while not got:
                    cond.wait(5.0)

        t = threading.Thread(target=consumer, daemon=True)
        t.start()
        time.sleep(0.05)
        with cond:
            got.append(1)
            cond.notify_all()
        t.join(5.0)
        st = contention._stats_for("t_cond")
        assert st.qwaits >= 1
        assert st.qwait_total_s > 0
        # parked wait() time is NOT lock contention
        flat = st.flat()
        assert flat["t_cond_qwait_total_ms"] > 0

    def test_set_enabled_false_is_pass_through(self):
        lk = contention.lock("t_disabled")
        contention.set_enabled(False)
        try:
            with lk:
                pass
            assert lk.stats.acquisitions == 0
        finally:
            contention.set_enabled(True)
        with lk:
            pass
        assert lk.stats.acquisitions == 1

    def test_nonblocking_probe_and_is_owned(self):
        lk = contention.lock("t_probe")
        assert lk.acquire(blocking=False)
        assert lk._is_owned()
        assert not lk.acquire(blocking=False)   # held; probe fails clean
        lk.release()
        assert not lk._is_owned()

    def test_metric_histogram_observes_on_contention(self):
        from karpenter_provider_aws_tpu.metrics import (Registry,
                                                        lint_exposition,
                                                        wire_core_metrics)
        reg = Registry()
        wire_core_metrics(reg)
        hist = reg.get("karpenter_lock_wait_seconds")
        contention.attach_metrics(hist)
        try:
            lk = contention.lock("t_metric")
            entered, release = threading.Event(), threading.Event()

            def holder():
                with lk:
                    entered.set()
                    release.wait(5.0)

            t = threading.Thread(target=holder, daemon=True)
            t.start()
            assert entered.wait(5.0)
            w = threading.Thread(target=lambda: lk.acquire() and
                                 lk.release(), daemon=True)
            w.start()
            time.sleep(0.05)
            release.set()
            t.join(5.0)
            w.join(5.0)
            assert hist.count(lock="t_metric") >= 1
            assert not lint_exposition(reg.render())
        finally:
            contention.attach_metrics(None)

    def test_stats_flat_and_top_waits(self):
        lk = contention.lock("t_flat")
        with lk:
            pass
        flat = contention.stats()
        assert flat["t_flat_acquisitions"] >= 1
        assert "t_flat_wait_p99_ms" in flat
        doc = contention.detail()
        assert "t_flat" in doc["locks"]
        assert doc["locks"]["t_flat"]["acquisitions"] >= 1
        # top_waits only ranks locks that actually contended
        assert all(n != "t_flat" for n, _, _ in contention.top_waits(50)) \
            or contention._stats_for("t_flat").contended > 0


class TestDeviceCostModel:
    def test_observe_solve_calibrates_best(self):
        m = DeviceCostModel()
        key = shape_key(64, 512)
        m.observe_solve(key, 10.0)
        m.observe_solve(key, 4.0)
        m.observe_solve(key, 8.0)
        s = m.stats()
        assert s["last_compute_ms"] == 8.0
        assert s["last_model_ms"] == 4.0
        assert s["last_vs_model"] == 2.0
        assert m.summary()["shapes"][key]["solves"] == 3

    def test_record_compiled_handles_both_jax_shapes(self):
        class CompiledDict:
            def cost_analysis(self):
                return {"flops": 100.0, "bytes accessed": 200.0}

            def memory_analysis(self):
                class MA:
                    temp_size_in_bytes = 10
                    output_size_in_bytes = 20
                    argument_size_in_bytes = 30
                return MA()

        class CompiledList(CompiledDict):
            def cost_analysis(self):
                return [{"flops": 7.0, "bytes accessed": 9.0}]

        m = DeviceCostModel()
        assert m.record_compiled("k1", CompiledDict())
        assert m.record_compiled("k2", CompiledList())
        s = m.summary()["shapes"]
        assert s["k1"]["flops"] == 100.0
        assert s["k1"]["peak_bytes"] == 60.0
        assert s["k2"]["flops"] == 7.0

    def test_analysis_failure_is_contained(self):
        class Broken:
            def cost_analysis(self):
                raise RuntimeError("backend says no")

            def memory_analysis(self):
                raise RuntimeError("no")

        m = DeviceCostModel()
        assert not m.record_compiled("k", Broken())
        assert m.capture_errors == 1
        assert m.stats()["shapes"] == 0

    def test_shape_set_bounded(self):
        import karpenter_provider_aws_tpu.solver.costmodel as cm
        m = DeviceCostModel()
        for i in range(cm._MAX_SHAPES + 10):
            m.observe_solve(f"G{i}_B1", 1.0)
        assert len(m._shapes) == cm._MAX_SHAPES

    def test_solver_lowering_capture_fills_model(self, env):
        """capture_cost_model lowers (no compile, no execute) one warm
        shape and records XLA's real analysis."""
        from karpenter_provider_aws_tpu.solver import costmodel
        costmodel.model().reset()
        n = env.solver.capture_cost_model(g_buckets=(16,), b_buckets=(32,))
        assert n == 1
        rec = costmodel.model().summary()["shapes"][shape_key(16, 32)]
        assert rec["flops"] > 0 or rec["bytes_accessed"] > 0


class TestBurnCaptureLifecycle:
    def _rig(self, retain=8):
        clock = FakeClock()
        slo = SloTracker(clock)
        bc = BurnCapture(clock, retain=retain,
                         latency_budget_seconds=slo.latency_budget_seconds)
        slo.attach_capture(bc)
        return clock, slo, bc

    def _burn_episode(self, clock, slo):
        """Drive one sustained latency-burn episode to its firing edge."""
        for _ in range(8):
            slo.record_latency(1.0)   # 5x the 200 ms budget, under the
            clock.step(1.0)           # slow-pass threshold (2 s)
        slo.update()                  # episode opens
        clock.step(slo.sustain_seconds + 1.0)
        for _ in range(3):
            slo.record_latency(1.0)   # keep the window hot
        slo.update()                  # sustained -> fires

    def _recover(self, clock, slo):
        clock.step(slo.window_seconds + 1.0)   # window empties
        slo.update()                           # burn 0 -> re-arm

    def test_one_capture_per_episode_rearmed_on_recovery(self):
        clock, slo, bc = self._rig()
        self._burn_episode(clock, slo)
        assert bc.capture_count == 1
        assert bc.captures[0]["reason"] == "slo-latency-burn"
        assert bc.captures[0]["burn"] > 1.0
        # still burning: the episode must not fire again
        for _ in range(5):
            slo.record_latency(1.0)
            clock.step(1.0)
            slo.update()
        assert bc.capture_count == 1
        # recovery re-arms; the next episode captures again
        self._recover(clock, slo)
        self._burn_episode(clock, slo)
        assert bc.capture_count == 2

    def test_bounded_retention_under_repeated_episodes(self):
        clock, slo, bc = self._rig(retain=3)
        for _ in range(7):
            self._burn_episode(clock, slo)
            self._recover(clock, slo)
        assert bc.capture_count == 7
        assert len(bc.captures) == 3           # flight-recorder bound
        episodes = [c["episode"] for c in bc.captures]
        assert episodes == [5, 6, 7]           # newest retained

    def test_slow_pass_trigger_arm_and_cooldown(self):
        clock, slo, bc = self._rig()
        slo.record_latency(3.0)        # grossly over (10x budget = 2 s)
        assert bc.capture_count == 1
        assert bc.captures[-1]["reason"] == "slow-pass"
        slo.record_latency(3.0)        # disarmed: no capture storm
        assert bc.capture_count == 1
        slo.record_latency(0.05)       # within budget, but cooldown holds
        slo.record_latency(3.0)
        assert bc.capture_count == 1
        clock.step(bc.cooldown_seconds + 1.0)
        slo.record_latency(0.05)       # within budget AFTER cooldown
        slo.record_latency(3.0)        # re-armed
        assert bc.capture_count == 2

    def test_warmup_passes_never_trigger(self):
        clock, slo, bc = self._rig()
        slo.begin_warmup()
        slo.record_latency(30.0)       # cold compile
        assert bc.capture_count == 0
        assert slo.warmup_dropped == 1

    def test_capture_embeds_profile_contention_device_evidence(self):
        clock, _, bc = self._rig()
        prof = SamplingProfiler(hz=100)
        t, ev = _parked_thread(name="evidence-w")
        try:
            prof.sample_once()
        finally:
            ev.set()
            t.join()
        introspect.set_profiler(prof)
        try:
            lk = contention.lock("t_evidence")
            with lk:
                pass
            snap = bc.capture("manual")
        finally:
            introspect.set_profiler(None)
        assert snap["profile"]["samples"] == 1
        assert any("distinctive_parking_spot" in d["frame"]
                   for d in snap["profile"]["top"])
        assert "contention" in snap and "device" in snap
        assert snap["episode"] == 1

    def test_capture_bug_never_breaks_burn_tracking(self):
        clock, slo, _ = self._rig()

        class Exploding:
            def on_sustained_burn(self, *a):
                raise RuntimeError("boom")

            def note_latency(self, *a):
                raise RuntimeError("boom")

        slo.attach_capture(Exploding())
        self._burn_episode(clock, slo)   # must not raise
        assert slo.update()["latency_burn"] > 1.0


class TestOperatorWiringAndHttp:
    def test_providers_registered_and_capture_attached(self, env):
        names = introspect.registry().names()
        for n in ("contention", "profiler", "device", "burn_captures"):
            assert n in names
        assert env.slo._capture is env.burn_capture
        assert env.slo.on_sustained == env.burn_capture.on_sustained_burn
        assert introspect.burn_capture() is env.burn_capture
        # hot locks report from the first mirror mutation
        env.cluster.add_pod(Pod(name="wire-0",
                                requests={"cpu": "100m", "memory": "1Gi"}))
        flat = contention.stats()
        assert flat["cluster_state_acquisitions"] > 0

    def test_solve_observes_cost_model(self, env):
        from karpenter_provider_aws_tpu.solver import costmodel
        before = dict(costmodel.model()._shapes)
        for i in range(3):
            env.cluster.add_pod(Pod(name=f"cm-{i}",
                                    requests={"cpu": "500m",
                                              "memory": "1Gi"}))
        env.settle(max_rounds=20)
        stats = costmodel.model().stats()
        assert stats["shapes"] >= max(len(before), 1)
        assert stats.get("last_compute_ms", 0) > 0

    @pytest.fixture()
    def served(self, env):
        from karpenter_provider_aws_tpu.cli import start_server
        prof = introspect.enable_profiling(hz=100)
        server = start_server(env, 0)
        yield env, f"http://127.0.0.1:{server.server_address[1]}"
        server.shutdown()
        prof.stop()
        introspect.set_profiler(None)

    def test_pprof_routes_on_metrics_server(self, served):
        env, base = served
        deadline = time.monotonic() + 5.0
        prof = introspect.profiler_instance()
        while prof.samples < 3 and time.monotonic() < deadline:
            time.sleep(0.02)
        folded = urllib.request.urlopen(
            base + "/debug/pprof/profile", timeout=10).read().decode()
        assert folded.strip()
        cont = json.loads(urllib.request.urlopen(
            base + "/debug/pprof/contention", timeout=10).read())
        assert "cluster_state" in cont["locks"]
        dev = json.loads(urllib.request.urlopen(
            base + "/debug/pprof/device", timeout=10).read())
        assert "shapes" in dev
        caps = json.loads(urllib.request.urlopen(
            base + "/debug/pprof/captures", timeout=10).read())
        assert "captures" in caps

    def test_pprof_routes_on_rest_apiserver(self, lattice):
        from karpenter_provider_aws_tpu.kube import FakeAPIServer
        from karpenter_provider_aws_tpu.kube.httpserver import serve
        clock = FakeClock()
        api = FakeAPIServer()
        Operator(options=Options(registration_delay=1.0), lattice=lattice,
                 cloud=FakeCloud(clock), clock=clock, api_server=api)
        httpd = serve(api, 0)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            cont = json.loads(urllib.request.urlopen(
                base + "/debug/pprof/contention", timeout=10).read())
            assert "api_server" in cont["locks"]
            # the PR 2 invariant: the new mounts carry X-Server-Time too
            resp = urllib.request.urlopen(
                base + "/debug/pprof/device", timeout=10)
            assert float(resp.headers["X-Server-Time"]) > 0
        finally:
            httpd.shutdown()

    def test_gzip_negotiation_on_vars_and_metrics(self, served):
        env, base = served
        env.sampler.sample_once()
        for path, parse in (("/debug/vars?series=1", json.loads),
                            ("/metrics", lambda b: b)):
            req = urllib.request.Request(
                base + path, headers={"Accept-Encoding": "gzip"})
            resp = urllib.request.urlopen(req, timeout=10)
            assert resp.headers.get("Content-Encoding") == "gzip", path
            parse(gzip.decompress(resp.read()))
            # a client that did NOT opt in gets identity, untouched
            plain = urllib.request.urlopen(base + path, timeout=10)
            assert plain.headers.get("Content-Encoding") is None
            parse(plain.read())

    def test_gzip_on_rest_apiserver_vars(self, lattice):
        from karpenter_provider_aws_tpu.kube import FakeAPIServer
        from karpenter_provider_aws_tpu.kube.httpserver import serve
        clock = FakeClock()
        op = Operator(options=Options(registration_delay=1.0),
                      lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                      api_server=FakeAPIServer())
        op.sampler.sample_once()
        httpd = serve(op.api_server, 0)
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{httpd.server_address[1]}"
                "/debug/vars?series=1",
                headers={"Accept-Encoding": "gzip"})
            resp = urllib.request.urlopen(req, timeout=10)
            assert resp.headers.get("Content-Encoding") == "gzip"
            json.loads(gzip.decompress(resp.read()))
        finally:
            httpd.shutdown()

    def test_tiny_bodies_skip_gzip(self):
        from karpenter_provider_aws_tpu.kube.httpserver import maybe_gzip
        body, enc = maybe_gzip(b"ok", "gzip")
        assert body == b"ok" and enc is None
        big = b"x" * 4096
        zipped, enc = maybe_gzip(big, "gzip, deflate")
        assert enc == "gzip" and gzip.decompress(zipped) == big
        assert maybe_gzip(big, None) == (big, None)


class TestLogTraceCorrelation:
    def _capture_logs(self, fn):
        from karpenter_provider_aws_tpu.utils.logging import (_KVFormatter,
                                                              get_logger)
        log = get_logger("test_profiler")
        records = []
        h = logging.Handler()
        h.emit = records.append
        h.setFormatter(_KVFormatter())
        log._logger.addHandler(h)
        log._logger.setLevel(logging.INFO)
        log._logger.propagate = False
        try:
            fn(log)
        finally:
            log._logger.removeHandler(h)
        return [_KVFormatter().format(r) for r in records]

    def test_log_inside_span_carries_trace_id(self):
        from karpenter_provider_aws_tpu.trace import FlightRecorder
        trace.enable(FlightRecorder())
        try:
            out = {}

            def go(log):
                with trace.span("corr.test") as sp:
                    out["tid"] = sp.trace_id
                    log.info("inside", k=1)
                log.info("outside")

            lines = self._capture_logs(go)
        finally:
            trace.disable()
        assert f"trace={out['tid']}" in lines[0]
        assert "k=1" in lines[0]
        assert "trace=" not in lines[1]

    def test_log_without_tracing_unchanged(self):
        lines = self._capture_logs(lambda log: log.info("plain", a=2))
        assert "trace=" not in lines[0]
        assert "a=2" in lines[0]

    def test_explicit_trace_kv_wins(self):
        from karpenter_provider_aws_tpu.trace import FlightRecorder
        trace.enable(FlightRecorder())
        try:
            lines = self._capture_logs(
                lambda log: log.info("x", trace="mine"))
        finally:
            trace.disable()
        assert "trace=mine" in lines[0]


class TestKpctlSurfaces:
    @pytest.fixture()
    def kpctl(self, monkeypatch):
        import pathlib
        monkeypatch.syspath_prepend(str(
            pathlib.Path(__file__).resolve().parent.parent / "tools"))
        import kpctl
        return kpctl

    def test_top_renders_contention_device_profiler_rows(self, kpctl):
        doc = {"providers": {
            "contention": {"locks": 3,
                           "api_server_wait_p99_ms": 12.0,
                           "api_server_contended": 40,
                           "cluster_state_wait_p99_ms": 5.0,
                           "cluster_state_contended": 10,
                           "writer_wait_p99_ms": 0.0,
                           "writer_contended": 0},
            "device": {"last_compute_ms": 12.5, "last_model_ms": 10.0,
                       "last_vs_model": 1.25, "shapes": 4,
                       "bytes_in_use": 0},
            "profiler": {"enabled": 1.0, "samples": 500, "hz": 50,
                         "unique_stacks": 42, "overhead_pct": 1.2},
            "burn_captures": {"retained": 2, "total": 5},
        }}
        lines = kpctl._render_top(doc, "srv")
        cont = next(l for l in lines if l.startswith("CONTENTION"))
        assert "api_server p99 12.0ms (40x)" in cont
        # ranked by p99, zero-wait locks dropped
        assert cont.index("api_server") < cont.index("cluster_state")
        assert "writer" not in cont
        dev = next(l for l in lines if l.startswith("DEVICE"))
        assert "1.25x" in dev
        prof = next(l for l in lines if l.startswith("PROFILER"))
        assert "overhead 1.2%" in prof
        slo = next(l for l in lines if l.startswith("SLO"))
        assert "captures 2" in slo

    def test_profile_top_and_capture_live(self, kpctl, env, capsys,
                                          tmp_path):
        from karpenter_provider_aws_tpu.cli import start_server
        prof = introspect.enable_profiling(hz=200)
        server = start_server(env, 0)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            deadline = time.monotonic() + 5.0
            while prof.samples < 5 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert kpctl.main(["--server", base, "profile", "top"]) == 0
            out = capsys.readouterr().out
            assert "samples" in out and "FRAME" in out
            dest = tmp_path / "prof.folded"
            assert kpctl.main(["--server", base, "profile", "capture",
                               "-o", str(dest)]) == 0
            assert dest.read_text().strip()
        finally:
            server.shutdown()
            prof.stop()
            introspect.set_profiler(None)

    def test_profile_capture_reports_disabled(self, kpctl, env, capsys,
                                              tmp_path):
        from karpenter_provider_aws_tpu.cli import start_server
        assert introspect.profiler_instance() is None
        server = start_server(env, 0)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            assert kpctl.main(["--server", base, "profile",
                               "capture"]) == 1
            assert "not running" in capsys.readouterr().err
            # every FORMAT detects the disabled marker — a chrome
            # capture must never write a useless {"enabled": false} stub
            # and exit 0 (regression)
            dest = tmp_path / "stub.json"
            assert kpctl.main(["--server", base, "profile", "capture",
                               "--format", "chrome",
                               "-o", str(dest)]) == 1
            assert "not running" in capsys.readouterr().err
            assert not dest.exists()
        finally:
            server.shutdown()

    def test_profile_diff(self, kpctl, tmp_path, capsys):
        a = tmp_path / "a.folded"
        b = tmp_path / "b.folded"
        a.write_text("t;main;slow_fn 10\nt;main;ok_fn 5\n")
        b.write_text("t;main;slow_fn 2\nt;main;ok_fn 5\n")
        assert kpctl.main(["profile", "diff", str(a), str(b)]) == 0
        out = capsys.readouterr().out
        assert "slow_fn" in out and "-8" in out
        assert "ok_fn" not in out   # unchanged frames dropped

    def test_soak_summary_prints_peak_lock_wait(self, kpctl, tmp_path,
                                                capsys):
        art = tmp_path / "soak.json"
        art.write_text(json.dumps({
            "samples": [{"t": 1.0, "nodes": 1, "pending_pods": 0,
                         "cost_per_hour": 0.1, "subsystems": {}}],
            "summary": {"wall_seconds": 60, "peak_nodes": 5,
                        "peak_pending_pods": 2, "peak_cost_per_hour": 1.0,
                        "peak_latency_burn": 0.5, "peak_cost_burn": 0.0,
                        "peak_lock_wait_ms": 42.5,
                        "peak_lock_wait_lock": "api_server",
                        "final": {"subsystems": {"burn_captures": {
                            "total": 3, "retained": 2,
                            "last_reason": "slo-latency-burn"}}}},
        }))
        assert kpctl.main(["soak", str(art)]) == 0
        out = capsys.readouterr().out
        assert "peak lock wait 42.5ms (api_server)" in out
        assert "burn captures 3" in out

    def test_monitor_summary_computes_lock_peak(self, env):
        from karpenter_provider_aws_tpu.debug import Monitor
        mon = Monitor(env)
        mon.samples = [
            {"t": 1.0, "nodes": 0, "pending_pods": 0, "cost_per_hour": 0,
             "subsystems": {"contention": {"a_max_wait_ms": 5.0}}},
            {"t": 2.0, "nodes": 0, "pending_pods": 0, "cost_per_hour": 0,
             "subsystems": {"contention": {"a_max_wait_ms": 9.0,
                                           "b_max_wait_ms": 3.0}}},
        ]
        summ = mon.summary()
        assert summ["peak_lock_wait_ms"] == 9.0
        assert summ["peak_lock_wait_lock"] == "a"
