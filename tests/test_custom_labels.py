"""Custom-key label assignment (workload segregation) semantics.

Behavioral spec: reference website concepts/scheduling.md:534-556 — a
NodePool requirement on a user-defined key with the `Exists` operator (or
`In` over several values) leaves the node's label value free; workloads
pin it via nodeSelector, Karpenter labels the launched nodes accordingly
(separating conflicting workloads), and generates a random label when a
matching workload names none.
"""

import pytest

from karpenter_provider_aws_tpu.apis import (
    NodePool, Operator as ReqOp, Pod, Requirement,
)
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.solver import Solver, build_problem
from karpenter_provider_aws_tpu.utils.clock import FakeClock

TEAM = "company.com/team"
_FAMILIES = ("m5", "c5", "t3")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog() if s.family in _FAMILIES])


@pytest.fixture(scope="module")
def solver(lattice):
    return Solver(lattice)


def team_pool(**kw):
    return NodePool(name=kw.pop("name", "default"), requirements=[
        Requirement(TEAM, ReqOp.EXISTS, ()),
        Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("on-demand",))], **kw)


def team_pods(team, n=3, prefix=None):
    prefix = prefix or team
    return [Pod(name=f"{prefix}-{i}", requests={"cpu": "500m", "memory": "1Gi"},
                node_selector={TEAM: team}) for i in range(n)]


class TestExistsSegregation:
    def test_conflicting_teams_never_share_a_node(self, solver, lattice):
        problem = build_problem(team_pods("team-a") + team_pods("team-b"),
                                [team_pool()], lattice)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        for n in plan.new_nodes:
            teams = {p.split("-")[1] for p in n.pods}
            assert len(teams) == 1
            assert n.extra_labels[TEAM] == f"team-{teams.pop()}"
            assert n.node_pool == "default"

    def test_multi_value_selector_matches_either(self, solver, lattice):
        pod = Pod(name="flex", requests={"cpu": "500m"},
                  required_affinity=[Requirement(TEAM, ReqOp.IN,
                                                 ("team-a", "team-b"))])
        plan = solver.solve(build_problem([pod], [team_pool()], lattice))
        assert not plan.unschedulable
        (n,) = plan.new_nodes
        assert n.extra_labels[TEAM] in ("team-a", "team-b")

    def test_unconstrained_pods_prefer_the_base_pool(self, solver, lattice):
        plan = solver.solve(build_problem(
            [Pod(name="plain", requests={"cpu": "500m"})],
            [team_pool()], lattice))
        (n,) = plan.new_nodes
        assert n.extra_labels == {}  # base pool; label generated at claim time

    def test_exists_only_demand_gets_generated_value(self, solver, lattice):
        pod = Pod(name="anyteam", requests={"cpu": "500m"},
                  required_affinity=[Requirement(TEAM, ReqOp.EXISTS, ())])
        plan = solver.solve(build_problem([pod], [team_pool()], lattice))
        assert not plan.unschedulable
        (n,) = plan.new_nodes
        assert n.extra_labels[TEAM].startswith("kpat-")

    def test_in_valued_offer_restricts_values(self, solver, lattice):
        pool = NodePool(name="spread", requirements=[
            Requirement("capacity-spread", ReqOp.IN, ("1", "2"))])
        ok = Pod(name="ok", requests={"cpu": "500m"},
                 node_selector={"capacity-spread": "2"})
        bad = Pod(name="bad", requests={"cpu": "500m"},
                  node_selector={"capacity-spread": "9"})
        plan = solver.solve(build_problem([ok, bad], [pool], lattice))
        assert "bad" in plan.unschedulable and "ok" not in plan.unschedulable
        (n,) = [n for n in plan.new_nodes if n.pods]
        assert n.extra_labels == {"capacity-spread": "2"}

    def test_template_label_still_binds_exactly(self, solver, lattice):
        """A pool with a fixed template LABEL is not value-free."""
        pool = NodePool(name="fixed", labels={TEAM: "team-x"})
        plan = solver.solve(build_problem(
            team_pods("team-a", n=1) + team_pods("team-x", n=1),
            [pool], lattice))
        assert "team-a-0" in plan.unschedulable
        (n,) = [n for n in plan.new_nodes if n.pods]
        assert n.pods == ["team-x-0"] and n.extra_labels == {}


class TestEndToEnd:
    def _env(self, lattice):
        clock = FakeClock()
        return Operator(options=Options(registration_delay=1.0),
                        lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                        node_pools=[team_pool()])

    def test_claims_and_nodes_carry_the_label(self, lattice):
        env = self._env(lattice)
        for p in team_pods("team-a", 2) + team_pods("team-b", 2):
            env.cluster.add_pod(p)
        env.settle()
        assert all(p.node_name for p in env.cluster.pods.values())
        by_team = {}
        for claim in env.cluster.claims.values():
            assert claim.node_pool == "default"  # budgets/limits roll up
            team = claim.labels.get(TEAM)
            assert team in ("team-a", "team-b")
            by_team.setdefault(team, []).append(claim)
            node = env.cluster.node_for_claim(claim.name)
            assert node is not None and node.labels.get(TEAM) == team
        assert set(by_team) == {"team-a", "team-b"}

    def test_second_wave_joins_matching_existing_node_only(self, lattice):
        env = self._env(lattice)
        for p in team_pods("team-a", 1):
            env.cluster.add_pod(p)
        env.settle()
        assert len(env.cluster.nodes) == 1
        # wave 2: one more team-a pod (tiny) must join the existing team-a
        # node; a team-b pod must get a NEW node
        env.cluster.add_pod(Pod(name="team-a-more", requests={"cpu": "100m"},
                                node_selector={TEAM: "team-a"}))
        env.cluster.add_pod(Pod(name="team-b-new", requests={"cpu": "100m"},
                                node_selector={TEAM: "team-b"}))
        env.settle()
        pods_by_node = env.cluster.pods_by_node()
        assert len(env.cluster.nodes) == 2
        for node_name, pods in pods_by_node.items():
            teams = {env.cluster.nodes[node_name].labels.get(TEAM)}
            for p in pods:
                assert p.node_selector.get(TEAM) in teams

    def test_unconstrained_pod_node_gets_random_label(self, lattice):
        """scheduling.md:554: a workload that matches the pool without
        naming a value still yields a labeled node."""
        env = self._env(lattice)
        env.cluster.add_pod(Pod(name="plain", requests={"cpu": "500m"}))
        env.settle()
        (claim,) = env.cluster.claims.values()
        assert claim.labels.get(TEAM, "").startswith("kpat-")


class TestCustomKeySpread:
    """Topology spread over user-defined labels — the reference's 'virtual
    domains' technique (scheduling.md:558-614): domains discovered from
    NodePool requirement values, spread balanced by water-fill, each slice
    pinned to its domain's labeled pool variant."""

    def _ratio_pools(self):
        return [
            NodePool(name="spot", requirements=[
                Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("spot",)),
                Requirement("capacity-spread", ReqOp.IN, ("2", "3", "4", "5"))]),
            NodePool(name="on-demand", requirements=[
                Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("on-demand",)),
                Requirement("capacity-spread", ReqOp.IN, ("1",))]),
        ]

    def _spread_pods(self, n, anyway=False):
        from karpenter_provider_aws_tpu.apis.objects import TopologySpreadConstraint
        return [Pod(name=f"w{i}", labels={"app": "web"},
                    requests={"cpu": "1", "memory": "2Gi"},
                    topology_spread=[TopologySpreadConstraint(
                        max_skew=1, topology_key="capacity-spread",
                        when_unsatisfiable=("ScheduleAnyway" if anyway
                                            else "DoNotSchedule"),
                        label_selector=(("app", "web"),))])
                for i in range(n)]

    def test_four_to_one_spot_ratio(self, solver, lattice):
        plan = solver.solve(build_problem(self._spread_pods(10),
                                          self._ratio_pools(), lattice))
        assert not plan.unschedulable
        per_cap = {"spot": 0, "on-demand": 0}
        per_domain = {}
        for n in plan.new_nodes:
            d = n.extra_labels["capacity-spread"]
            per_domain[d] = per_domain.get(d, 0) + len(n.pods)
            per_cap[n.capacity_type] += len(n.pods)
        assert per_cap == {"spot": 8, "on-demand": 2}
        assert all(v == 2 for v in per_domain.values())

    def test_schedule_anyway_spread_is_advisory(self, solver, lattice):
        plan = solver.solve(build_problem(self._spread_pods(10, anyway=True),
                                          self._ratio_pools(), lattice))
        assert not plan.unschedulable
        assert not any("capacity-spread" in w for w in plan.warnings)

    def test_undiscoverable_domains_warn(self, solver, lattice):
        plan = solver.solve(build_problem(
            self._spread_pods(4), [NodePool(name="plain")], lattice))
        assert any("no discoverable domains" in w for w in plan.warnings)

    def test_bound_pods_count_into_domains(self, lattice):
        """Existing matching pods on labeled nodes shift the water-fill:
        a domain already holding pods receives fewer new ones."""
        clock = FakeClock()
        env = Operator(options=Options(registration_delay=1.0),
                       lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                       node_pools=self._ratio_pools())
        for p in self._spread_pods(5):
            env.cluster.add_pod(p)
        env.settle()
        by_domain = {}
        for node_name, pods in env.cluster.pods_by_node().items():
            d = env.cluster.nodes[node_name].labels.get("capacity-spread")
            by_domain[d] = by_domain.get(d, 0) + len(pods)
        assert set(by_domain) == {"1", "2", "3", "4", "5"}
        # second wave of 5: counts must stay balanced at exactly 2 each
        for p in self._spread_pods(5, anyway=False):
            env.cluster.add_pod(Pod(
                name=f"w2-{p.name}", labels=p.labels, requests=p.requests,
                topology_spread=list(p.topology_spread)))
        env.settle()
        by_domain = {}
        for node_name, pods in env.cluster.pods_by_node().items():
            d = env.cluster.nodes[node_name].labels.get("capacity-spread")
            by_domain[d] = by_domain.get(d, 0) + len(pods)
        assert all(v == 2 for v in by_domain.values()), by_domain


class TestReviewRegressions:
    def test_demand_plus_spread_composes(self, solver, lattice):
        """A group pinning one custom key AND spreading over another gets
        composed pool variants (team=a x rack=r1/r2), not unschedulable."""
        from karpenter_provider_aws_tpu.apis.objects import TopologySpreadConstraint
        pool = NodePool(name="default", requirements=[
            Requirement(TEAM, ReqOp.EXISTS, ()),
            Requirement("rack", ReqOp.IN, ("r1", "r2"))])
        pods = [Pod(name=f"p{i}", labels={"app": "db"},
                    requests={"cpu": "1", "memory": "2Gi"},
                    node_selector={TEAM: "team-a"},
                    topology_spread=[TopologySpreadConstraint(
                        max_skew=1, topology_key="rack",
                        label_selector=(("app", "db"),))])
                for i in range(4)]
        plan = solver.solve(build_problem(pods, [pool], lattice))
        assert not plan.unschedulable, plan.unschedulable
        racks = {}
        for n in plan.new_nodes:
            assert n.extra_labels[TEAM] == "team-a"
            racks[n.extra_labels["rack"]] = racks.get(n.extra_labels["rack"], 0) + len(n.pods)
        assert racks == {"r1": 2, "r2": 2}

    def test_generated_value_is_stable_across_passes(self, solver, lattice):
        """Exists-only demands reuse the node the first pass labeled (the
        generated value derives from the group content, not batch order)."""
        def demand(name):
            return Pod(name=name, requests={"cpu": "100m"},
                       required_affinity=[Requirement(TEAM, ReqOp.EXISTS, ())])
        p1 = solver.solve(build_problem([demand("w1")], [team_pool()], lattice))
        # a different batch composition around the same workload shape
        p2 = solver.solve(build_problem(
            [Pod(name="other", requests={"cpu": "2"}), demand("w2")],
            [team_pool()], lattice))
        v1 = p1.new_nodes[0].extra_labels[TEAM]
        (n2,) = [n for n in p2.new_nodes if "w2" in n.pods]
        assert v1 == n2.extra_labels[TEAM]

    def test_in_valued_pool_labels_unconstrained_claims(self, lattice):
        """scheduling.md template contract: a node of a pool requiring
        team In (a,b) always carries one of those values."""
        clock = FakeClock()
        env = Operator(options=Options(registration_delay=1.0),
                       lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                       node_pools=[NodePool(name="default", requirements=[
                           Requirement(TEAM, ReqOp.IN, ("team-a", "team-b"))])])
        env.cluster.add_pod(Pod(name="plain", requests={"cpu": "500m"}))
        env.settle()
        (claim,) = env.cluster.claims.values()
        assert claim.labels.get(TEAM) in ("team-a", "team-b")

    def test_sidecar_preserves_custom_label_state(self, lattice):
        """ExistingBin.labels and BoundPod.node_labels survive the wire:
        a remote solve joins the labeled existing node instead of
        launching a duplicate."""
        import numpy as np
        from karpenter_provider_aws_tpu.apis import serde
        from karpenter_provider_aws_tpu.solver.problem import ExistingBin
        ti = lattice.name_to_idx["m5.xlarge"]
        b = ExistingBin(name="n1", node_pool="default",
                        instance_type="m5.xlarge", zone=lattice.zones[0],
                        capacity_type="on-demand",
                        used=np.zeros_like(lattice.alloc[ti]),
                        labels={TEAM: "team-a"})
        rt = serde.existing_bin_from_dict(serde.existing_bin_to_dict(b))
        assert rt.labels == {TEAM: "team-a"}
        problem = build_problem(team_pods("team-a", 1), [team_pool()],
                                lattice, existing=[rt])
        solver = Solver(lattice)
        plan = solver.solve(problem)
        assert plan.existing_assignments.get("n1") == ["team-a-0"]
        assert not plan.new_nodes
