"""Cross-process end-to-end: a SPAWNED control plane driven over the wire.

The reference's e2e stratum operates across a real network boundary
(test/suites/* drive remote clusters through
test/pkg/environment/common/environment.go); this does the same to the
served control plane: spawn ``python -m karpenter_provider_aws_tpu
--api-port N`` as a subprocess, then — purely over HTTP REST, with
tools/kpctl.py as the client — apply a NodePool, create pods, watch
nodes appear, inject a spot interruption through the queue's wire route
(POST /queue/messages, the SQS-over-HTTP analog), and assert the
cordon→drain→replace convergence from REST reads alone.

One subprocess serves the whole module (startup pays the JAX import +
first-solve compile once); individual asserts poll with deadlines.
"""

import json
import os
import pathlib
import socket
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

import kpctl  # noqa: E402

STARTUP_TIMEOUT = 120.0
CONVERGE_TIMEOUT = 90.0


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def control_plane():
    """The served control plane as a separate OS process."""
    port = _free_port()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        CLUSTER_NAME="xproc-e2e",
    )
    # log to a FILE, not a pipe: an undrained pipe backs up after ~64KB
    # of chaos-path logging and deadlocks the control plane mid-test
    import tempfile
    log = tempfile.NamedTemporaryFile(
        mode="w+", prefix="xproc-e2e-", suffix=".log", delete=False)
    proc = subprocess.Popen(
        [sys.executable, "-m", "karpenter_provider_aws_tpu",
         "--api-port", str(port),
         "--interruption-queue", "xproc-q",
         "--metrics-port", "0",
         "--step", "0.2",
         "--log-level", "WARNING"],
        cwd=str(REPO), env=env,
        stdout=log, stderr=subprocess.STDOUT, text=True)

    def _tail():
        with open(log.name) as f:
            return f.read()[-4000:]

    base = f"http://127.0.0.1:{port}"
    client = kpctl.Client(base)
    deadline = time.monotonic() + STARTUP_TIMEOUT
    last_err = None
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"control plane exited rc={proc.returncode}:\n{_tail()}")
        try:
            client.request("GET", "/apis/nodepools")
            break
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            last_err = e
            time.sleep(0.5)
    else:
        proc.kill()
        raise RuntimeError(f"REST surface never came up: {last_err}")
    yield client, base
    proc.terminate()
    try:
        proc.wait(15)
    except subprocess.TimeoutExpired:
        proc.kill()


def poll(fn, timeout=CONVERGE_TIMEOUT, every=0.5, desc=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = fn()
        if got:
            return got
        time.sleep(every)
    raise AssertionError(f"timed out waiting for {desc or fn}")


def kpctl_cli(base, *argv):
    """Drive the SHIPPED CLI (not the library) across the wire."""
    r = subprocess.run(
        [sys.executable, str(REPO / "tools" / "kpctl.py"),
         "--server", base, *argv],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    return r.stdout


@pytest.mark.slow
def test_provision_interrupt_converge_over_the_wire(control_plane,
                                                    tmp_path):
    client, base = control_plane

    # ---- provision: apply a pool + pods via the kpctl CLI ------------
    docs = [{"kind": "nodepools",
             "spec": {"name": "wire-pool", "weight": 50}}]
    docs += [{"kind": "pods",
              "spec": {"name": f"wp-{i}",
                       "requests": {"cpu": "1", "memory": "2Gi"}}}
             for i in range(6)]
    f = tmp_path / "apply.json"
    f.write_text(json.dumps(docs))
    out = kpctl_cli(base, "apply", "-f", str(f))
    assert "nodepools/wire-pool created" in out
    assert "pods/wp-5 created" in out

    # nodes appear and every pod binds — REST reads only
    def all_bound():
        pods = client.request("GET", "/apis/pods")["items"]
        mine = [p for p in pods
                if p["metadata"]["name"].startswith("wp-")]
        if mine and all(p["spec"].get("nodeName") for p in mine):
            return mine
        return None

    bound = poll(all_bound, desc="all pods bound")
    nodes = client.request("GET", "/apis/nodes")["items"]
    assert nodes, "no nodes visible over REST"
    # the kpctl table shows them too
    table = kpctl_cli(base, "get", "nodes")
    assert "NAME" in table and nodes[0]["metadata"]["name"] in table

    # ---- interrupt: spot warning through the queue's wire route ------
    claims = client.request("GET", "/apis/nodeclaims")["items"]
    live = [c for c in claims if c["spec"].get("providerID")
            and not c["metadata"].get("deletionTimestamp")]
    assert live, "expected at least one launched claim"
    # prefer a spot victim (exercises the spot->ICE feedback too), but a
    # spot warning resolves to ANY claim by instance id (controller
    # _ACTIONABLE), so fall back to whatever launched — the fake cloud's
    # ICE pools can push the first wave onto on-demand
    spot = [c for c in live if c["spec"].get("capacityType") == "spot"]
    victim = (spot or live)[0]
    instance_id = victim["spec"]["providerID"].rsplit("/", 1)[-1]
    node_of_victim = victim["metadata"]["name"]
    doomed = {p["metadata"]["name"] for p in bound
              if p["spec"]["nodeName"] == node_of_victim}
    resp = client.request("POST", "/queue/messages", {
        "version": "0", "source": "aws.ec2",
        "detail-type": "EC2 Spot Instance Interruption Warning",
        "detail": {"instance-id": instance_id,
                   "instance-action": "terminate"},
    })
    assert resp["messageId"]

    # ---- converge: victim drains, its pods land elsewhere ------------
    def victim_replaced():
        nodes = {n["metadata"]["name"]
                 for n in client.request("GET", "/apis/nodes")["items"]}
        if node_of_victim in nodes:
            return None
        pods = client.request("GET", "/apis/pods")["items"]
        mine = {p["metadata"]["name"]: p["spec"].get("nodeName")
                for p in pods if p["metadata"]["name"].startswith("wp-")}
        # every pod (incl. the doomed ones) bound somewhere that exists
        if all(nn and nn != node_of_victim for nn in mine.values()):
            return mine
        return None

    rebound = poll(victim_replaced, desc="interrupted node replaced")
    assert doomed, "victim node hosted no pods? scenario is vacuous"
    for name in doomed:
        assert rebound[name] != node_of_victim

    # the spot→ICE feedback is visible in the replacement: the new home
    # of a doomed pod is a different node object
    assert set(rebound.values()), rebound

    # ---- events: the kubectl-get-events flow, over the wire ----------
    table = kpctl_cli(base, "get", "events")
    assert "REASON" in table and "Launched" in table
    assert "Cordoned" in table   # the interruption drain left its trace

    # describe stitches an object to its events, kubectl-style
    claims = client.request("GET", "/apis/nodeclaims")["items"]
    some = claims[0]["metadata"]["name"]
    desc = kpctl_cli(base, "describe", "nodeclaims", some)
    assert f"Name:             {some}" in desc
    assert "Spec:" in desc and "Events:" in desc
    assert "Launched" in desc


@pytest.fixture(scope="module")
def traced_control_plane(tmp_path_factory):
    """TWO spawned processes — a standalone solver sidecar and an operator
    whose provisioning solves ship to it (--solver-address) — both with
    tracing on. The deployment shape the tracing acceptance names: one
    connected span tree crossing the REST boundary (client → apiserver)
    AND the gRPC boundary (operator → sidecar device solve)."""
    tmp = tmp_path_factory.mktemp("traced")
    sock = f"unix:{tmp}/solver.sock"
    port = _free_port()
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        CLUSTER_NAME="traced-e2e",
    )
    side_log = open(tmp / "sidecar.log", "w")
    side = subprocess.Popen(
        [sys.executable, "-m",
         "karpenter_provider_aws_tpu.parallel.sidecar",
         "--address", sock, "--synthetic-catalog", "--trace"],
        cwd=str(REPO), env=env, stdout=side_log,
        stderr=subprocess.STDOUT, text=True)
    op_log = open(tmp / "operator.log", "w")
    proc = subprocess.Popen(
        [sys.executable, "-m", "karpenter_provider_aws_tpu",
         "--api-port", str(port), "--metrics-port", "0",
         "--step", "0.2", "--trace", "--solver-address", sock,
         "--log-level", "WARNING"],
        cwd=str(REPO), env=env, stdout=op_log,
        stderr=subprocess.STDOUT, text=True)
    base = f"http://127.0.0.1:{port}"
    client = kpctl.Client(base)
    deadline = time.monotonic() + STARTUP_TIMEOUT
    while time.monotonic() < deadline:
        if side.poll() is not None or proc.poll() is not None:
            side.kill(), proc.kill()
            raise RuntimeError(
                f"spawn failed: sidecar rc={side.poll()} "
                f"operator rc={proc.poll()}\n"
                + open(tmp / "sidecar.log").read()[-2000:]
                + open(tmp / "operator.log").read()[-2000:])
        try:
            client.request("GET", "/apis/nodepools")
            break
        except (urllib.error.URLError, ConnectionError, OSError):
            time.sleep(0.5)
    else:
        side.kill(), proc.kill()
        raise RuntimeError("traced REST surface never came up")
    yield client, base
    for p in (proc, side):
        p.terminate()
    for p in (proc, side):
        try:
            p.wait(15)
        except subprocess.TimeoutExpired:
            p.kill()


@pytest.mark.slow
def test_one_connected_trace_across_both_process_boundaries(
        traced_control_plane, tmp_path):
    """REST admission → informer → batch → REMOTE device solve (gRPC
    sidecar process) → CreateFleet → NodeClaim registration, all under
    ONE trace id, exported as valid Chrome trace-event JSON by kpctl."""
    client, base = traced_control_plane
    trace_id = os.urandom(16).hex()
    traceparent = f"00-{trace_id}-{os.urandom(8).hex()}-01"

    client.request("POST", "/apis/nodepools",
                   {"name": "traced-pool", "weight": 50})
    for i in range(4):
        r = urllib.request.Request(
            f"{base}/apis/pods", method="POST",
            data=json.dumps({"name": f"tr-{i}",
                             "requests": {"cpu": "1",
                                          "memory": "2Gi"}}).encode(),
            headers={"Content-Type": "application/json",
                     "traceparent": traceparent})
        urllib.request.urlopen(r)

    def all_bound():
        pods = [p for p in client.request("GET", "/apis/pods")["items"]
                if p["metadata"]["name"].startswith("tr-")]
        return pods if pods and all(p["spec"].get("nodeName")
                                    for p in pods) else None

    poll(all_bound, desc="traced pods bound")

    # ---- the span tree: one trace, two services, fully connected ------
    def full_tree():
        try:
            doc = client.request("GET", f"/debug/traces/{trace_id}")
        except urllib.error.HTTPError:
            return None
        spans = doc["spans"]
        svcs = {s["svc"] for s in spans}
        names = {s["name"] for s in spans}
        # registration happens a few steps after binding; poll until the
        # whole causal chain is in the tree
        if "sidecar" not in svcs or "nodeclaim.register" not in names:
            return None
        return spans

    spans = poll(full_tree, desc="operator+sidecar spans in one trace")
    assert all(s["traceId"] == trace_id for s in spans)
    names = {s["name"] for s in spans}
    # the causal chain, stratum by stratum
    for expected in ("http POST /apis/pods", "provisioner.provision",
                     "solver.remote", "sidecar.solve",
                     "solver.solve_relaxed", "stage.compute",
                     "kube.create_nodeclaim", "nodeclaim.register"):
        assert expected in names, f"missing span {expected}: {sorted(names)}"
    # the device solve ran in the SIDECAR process
    by_svc = {}
    for s in spans:
        by_svc.setdefault(s["svc"], set()).add(s["name"])
    assert "sidecar.solve" in by_svc["sidecar"]
    assert "stage.compute" in by_svc["sidecar"]
    assert "provisioner.provision" in by_svc["operator"]
    # connectivity: every span's parent resolves inside the trace or to
    # the client's (remote) root — no orphaned subtrees
    ids = {s["spanId"] for s in spans}
    client_root = traceparent.split("-")[2]
    for s in spans:
        assert s["parentId"] is None or s["parentId"] in ids \
            or s["parentId"] == client_root, s

    # ---- kpctl trace: list names it, export is valid Chrome JSON ------
    out = kpctl_cli(base, "trace", "list")
    assert trace_id in out
    chrome_path = tmp_path / "trace.json"
    kpctl_cli(base, "trace", "export", trace_id, "-o", str(chrome_path))
    doc = json.loads(chrome_path.read_text())
    events = doc["traceEvents"]
    assert events and doc["displayTimeUnit"] == "ms"
    for e in events:
        assert {"name", "ph", "pid", "tid"} <= set(e)
        if e["ph"] == "X":
            assert e["dur"] >= 0 and {"ts", "args"} <= set(e)
    # two process rows: operator + sidecar
    metas = {e["args"]["name"] for e in events if e["ph"] == "M"}
    assert metas == {"operator", "sidecar"}
    show = kpctl_cli(base, "trace", "show", trace_id)
    assert "sidecar.solve" in show and "[sidecar]" in show

    # ---- solver provenance on the claim, rendered by describe ---------
    claims = client.request("GET", "/apis/nodeclaims")["items"]
    mine = [c for c in claims
            if c["spec"].get("annotations", {}).get(
                "karpenter.sh/traceparent", "").find(trace_id) >= 0]
    assert mine, "no claim carries the pass's traceparent annotation"
    desc = kpctl_cli(base, "describe", "nodeclaims",
                     mine[0]["metadata"]["name"])
    assert "Solver:" in desc
    assert "Path:" in desc and "Stages:" in desc
    assert trace_id in desc


@pytest.mark.slow
def test_kpctl_watch_and_delete_over_the_wire(control_plane, tmp_path):
    client, base = control_plane
    f = tmp_path / "one-pod.json"
    f.write_text(json.dumps(
        {"kind": "pods",
         "spec": {"name": "watchme",
                  "requests": {"cpu": "250m", "memory": "256Mi"}}}))
    # start a watch just before creating; --once exits on first event
    rv = client.request("GET", "/apis/pods")["resourceVersion"]
    w = subprocess.Popen(
        [sys.executable, str(REPO / "tools" / "kpctl.py"),
         "--server", base, "watch", "pods",
         "--resource-version", str(rv), "--once"],
        stdout=subprocess.PIPE, text=True)
    time.sleep(0.3)
    kpctl_cli(base, "apply", "-f", str(f))
    out, _ = w.communicate(timeout=30)
    assert "ADDED\tpods/watchme" in out
    # apply twice = configured, then delete
    out = kpctl_cli(base, "apply", "-f", str(f))
    assert "configured" in out
    out = kpctl_cli(base, "delete", "pods", "watchme", "--force")
    assert "deleted" in out
    pods = client.request("GET", "/apis/pods")["items"]
    assert "watchme" not in {p["metadata"]["name"] for p in pods}
