"""Lattice tests: catalog shape, overhead math oracle checks, mask compiler."""

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis import Operator, Requirement, Requirements
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.apis.resources import R, axis
from karpenter_provider_aws_tpu.lattice import (
    build_catalog,
    build_lattice,
    eni_limited_pods,
    KubeletConfiguration,
)
from karpenter_provider_aws_tpu.lattice.overhead import (
    _stepwise_cpu_reserved_millis,
    kube_reserved,
    eviction_threshold,
    vm_usable_memory_mib,
)
from karpenter_provider_aws_tpu.ops import compile_masks


@pytest.fixture(scope="module")
def lattice():
    return build_lattice()


class TestCatalog:
    def test_catalog_scale(self):
        catalog = build_catalog()
        # the reference works against a ~700+-type EC2 catalog
        assert len(catalog) >= 700
        assert len({t.name for t in catalog}) == len(catalog)

    def test_families_present(self):
        names = {t.name for t in build_catalog()}
        for expected in ("m5.large", "c6g.2xlarge", "r6i.metal", "t3.medium",
                         "p4d.24xlarge", "g5.xlarge", "inf1.6xlarge", "trn1.32xlarge"):
            assert expected in names, expected

    def test_deterministic(self):
        a, b = build_catalog(), build_catalog()
        assert [(t.name, t.od_price) for t in a] == [(t.name, t.od_price) for t in b]


class TestOverheadMath:
    """Values checked against the reference formulas (types.go:319-431)."""

    def test_eni_limited_pods_m5_large(self):
        # m5.large: 3 ENIs x 10 IPs -> 3*(10-1)+2 = 29 (the canonical value)
        assert eni_limited_pods(3, 10) == 29

    def test_eni_limited_pods_m5_4xlarge(self):
        # 8 ENIs x 30 IPs -> 8*29+2 = 234
        assert eni_limited_pods(8, 30) == 234

    def test_reserved_enis(self):
        assert eni_limited_pods(3, 10, reserved_enis=1) == 2 * 9 + 2
        assert eni_limited_pods(3, 10, reserved_enis=3) == 0

    def test_stepwise_cpu(self):
        # 2 vCPU (2000m): 6% of 1000 + 1% of 1000 = 70m
        assert _stepwise_cpu_reserved_millis(2000) == 70
        # 4 vCPU: 60 + 10 + 0.5% of 2000 = 80m
        assert _stepwise_cpu_reserved_millis(4000) == 80
        # 96 vCPU: 60+10+10 + 0.25% of 92000 = 310m
        assert _stepwise_cpu_reserved_millis(96000) == 310

    def test_kube_reserved_memory(self):
        vec = kube_reserved(2000, 29)
        assert vec[axis("memory")] == 11 * 29 + 255
        assert vec[axis("ephemeral-storage")] == 1024

    def test_kube_reserved_override(self):
        kc = KubeletConfiguration(kube_reserved={"cpu": "100m", "memory": "1Gi"})
        vec = kube_reserved(2000, 29, kc)
        assert vec[axis("cpu")] == 100
        assert vec[axis("memory")] == 1024

    def test_eviction_threshold_default(self):
        vec = eviction_threshold(8192, 20 * 1024)
        assert vec[axis("memory")] == 100
        assert vec[axis("ephemeral-storage")] == 2048  # 10% of 20Gi

    def test_eviction_signal_percentage(self):
        kc = KubeletConfiguration(eviction_hard={"memory.available": "5%"})
        vec = eviction_threshold(8000, 20 * 1024, kc)
        assert vec[axis("memory")] == pytest.approx(400)

    def test_vm_memory_overhead(self):
        # 8GiB amd64: 8192 - ceil(8192*0.075) = 8192 - 615 = 7577
        assert vm_usable_memory_mib(8192, "amd64") == 7577
        # arm64 loses 64MiB CMA first
        assert vm_usable_memory_mib(8192, "arm64") == 8128 - int(np.ceil(8128 * 0.075))


class TestLatticeTensors:
    def test_shapes(self, lattice):
        T, Z, C = lattice.T, lattice.Z, lattice.C
        assert T >= 700 and Z == 5 and C == 2
        assert lattice.alloc.shape == (T, R)
        assert lattice.price.shape == (T, Z, C)
        assert lattice.available.shape == (T, Z, C)

    def test_alloc_less_than_capacity(self, lattice):
        cpu_ax, mem_ax = axis("cpu"), axis("memory")
        assert (lattice.alloc[:, cpu_ax] < lattice.capacity[:, cpu_ax]).all()
        assert (lattice.alloc[:, mem_ax] < lattice.capacity[:, mem_ax]).all()
        assert (lattice.alloc >= 0).all()

    def test_price_inf_iff_unavailable(self, lattice):
        assert np.isinf(lattice.price[~lattice.available]).all()
        assert np.isfinite(lattice.price[lattice.available]).all()

    def test_spot_cheaper_than_od(self, lattice):
        od = lattice.price[:, :, 0]
        spot = lattice.price[:, :, 1]
        both = lattice.available[:, :, 0] & lattice.available[:, :, 1]
        assert (spot[both] < od[both]).all()

    def test_gpu_capacity(self, lattice):
        i = lattice.name_to_idx["p4d.24xlarge"]
        assert lattice.capacity[i, axis("nvidia.com/gpu")] == 8
        assert lattice.labels[i][wk.LABEL_INSTANCE_GPU_NAME] == "a100"


class TestMaskedViewVersioned:
    """masked_view_versioned must hand back the SAME view object while
    (price_version, ICE seq_num) is unchanged — the solver's
    identity-keyed narrowing cache only hits across controller passes if
    the view survives — and mint a fresh one the moment either moves."""

    def test_reuse_and_invalidation(self, lattice):
        from karpenter_provider_aws_tpu.cache.unavailable import UnavailableOfferings
        from karpenter_provider_aws_tpu.lattice.tensors import masked_view_versioned
        from karpenter_provider_aws_tpu.utils.clock import FakeClock

        clock = FakeClock()
        u = UnavailableOfferings(clock)
        v1 = masked_view_versioned(lattice, u)
        assert masked_view_versioned(lattice, u) is v1

        t, z = lattice.names[0], lattice.zones[0]
        u.mark_unavailable("ice", "on-demand", t, z)
        v2 = masked_view_versioned(lattice, u)
        assert v2 is not v1
        ti = lattice.name_to_idx[t]
        ci = lattice.capacity_types.index("on-demand")
        assert not v2.available[ti, 0, ci]
        assert masked_view_versioned(lattice, u) is v2

        # TTL expiry re-enters the market at the cleanup tick (seq bump)
        clock.step(10_000.0)
        u.cleanup()
        v3 = masked_view_versioned(lattice, u)
        assert v3 is not v2
        assert bool(v3.available[ti, 0, ci]) == bool(lattice.available[ti, 0, ci])

        lattice.price_version += 1
        try:
            assert masked_view_versioned(lattice, u) is not v3
        finally:
            lattice.price_version -= 1

    def test_two_ice_caches_sharing_one_base_never_alias(self, lattice):
        """Two operators over one injected base lattice each own an
        UnavailableOfferings instance; seq numbers are only comparable
        WITHIN an instance, so equal (price_version, seq) pairs from
        different caches must not serve each other's views."""
        from karpenter_provider_aws_tpu.cache.unavailable import UnavailableOfferings
        from karpenter_provider_aws_tpu.lattice.tensors import masked_view_versioned
        from karpenter_provider_aws_tpu.utils.clock import FakeClock

        clock = FakeClock()
        a, b = UnavailableOfferings(clock), UnavailableOfferings(clock)
        ta, tb = lattice.names[0], lattice.names[1]
        z = lattice.zones[0]
        a.mark_unavailable("ice", "on-demand", ta, z)   # a.seq == 1
        b.mark_unavailable("ice", "on-demand", tb, z)   # b.seq == 1
        va = masked_view_versioned(lattice, a)
        vb = masked_view_versioned(lattice, b)
        assert va is not vb
        ia, ib = lattice.name_to_idx[ta], lattice.name_to_idx[tb]
        ci = lattice.capacity_types.index("on-demand")
        assert not va.available[ia, 0, ci]
        assert bool(va.available[ib, 0, ci]) == bool(lattice.available[ib, 0, ci])
        assert not vb.available[ib, 0, ci]
        assert bool(vb.available[ia, 0, ci]) == bool(lattice.available[ia, 0, ci])


class TestMaskCompiler:
    def _names(self, lattice, mask):
        return {lattice.names[i] for i in np.nonzero(mask)[0]}

    def test_instance_family_in(self, lattice):
        reqs = Requirements([Requirement(wk.LABEL_INSTANCE_FAMILY, Operator.IN, ("m5", "c5"))])
        m = compile_masks(reqs, lattice)
        names = self._names(lattice, m.type_mask)
        assert names and all(n.startswith(("m5.", "c5.")) for n in names)

    def test_numeric_gt(self, lattice):
        reqs = Requirements([Requirement(wk.LABEL_INSTANCE_CPU, Operator.GT, ("64",))])
        m = compile_masks(reqs, lattice)
        for i in np.nonzero(m.type_mask)[0]:
            assert lattice.specs[i].vcpus > 64

    def test_gpu_exists(self, lattice):
        reqs = Requirements([Requirement(wk.LABEL_INSTANCE_GPU_NAME, Operator.EXISTS)])
        m = compile_masks(reqs, lattice)
        assert all(lattice.specs[i].gpu_count > 0 for i in np.nonzero(m.type_mask)[0])
        assert m.type_mask.sum() > 0

    def test_zone_and_capacity_axes(self, lattice):
        reqs = Requirements([
            Requirement(wk.LABEL_ZONE, Operator.IN, ("us-west-2a",)),
            Requirement(wk.LABEL_CAPACITY_TYPE, Operator.IN, ("spot",)),
        ])
        m = compile_masks(reqs, lattice)
        assert list(m.zone_mask) == [True, False, False, False, False]
        assert list(m.cap_mask) == [False, True]

    def test_extra_labels(self, lattice):
        reqs = Requirements([Requirement("example.com/team", Operator.IN, ("ml",))])
        assert not compile_masks(reqs, lattice).type_mask.any()
        assert compile_masks(reqs, lattice, extra_labels={"example.com/team": "ml"}).type_mask.all()
        assert not compile_masks(reqs, lattice, extra_labels={"example.com/team": "web"}).type_mask.any()

    def test_oracle_cross_check(self, lattice):
        """Mask compiler must agree with host-side satisfied_by on every type."""
        reqs = Requirements([
            Requirement(wk.LABEL_INSTANCE_CATEGORY, Operator.IN, ("c", "m")),
            Requirement(wk.LABEL_ARCH, Operator.IN, ("arm64",)),
            Requirement(wk.LABEL_INSTANCE_CPU, Operator.LT, ("33",)),
            Requirement(wk.LABEL_INSTANCE_SIZE, Operator.NOT_IN, ("metal",)),
        ])
        m = compile_masks(reqs, lattice)
        for i, lab in enumerate(lattice.labels):
            assert m.type_mask[i] == reqs.satisfied_by(lab), lattice.names[i]


class TestReviewRegressions:
    def test_extra_labels_cannot_shadow_lattice_keys(self, lattice):
        reqs = Requirements([Requirement(wk.LABEL_ARCH, Operator.IN, ("arm64",))])
        m = compile_masks(reqs, lattice, extra_labels={wk.LABEL_ARCH: "arm64"})
        for i in np.nonzero(m.type_mask)[0]:
            assert lattice.specs[i].arch == "arm64"

    def test_kube_reserved_explicit_zero(self):
        kc = KubeletConfiguration(kube_reserved={"memory": "0"})
        vec = kube_reserved(2000, 29, kc)
        assert vec[axis("memory")] == 0

    def test_gt_requires_integer(self):
        with pytest.raises(ValueError):
            Requirement("cpu", Operator.GT, ("4.2",))


class TestEphemeralStorage:
    """ephemeralStorage() resolution order (reference types.go:210-240):
    RAID0 local store > root-volume BDM > family-device BDM (last BDM for
    Custom AMIs) > the 20Gi default."""

    def _spec(self, nvme_gb=0):
        from karpenter_provider_aws_tpu.lattice import build_catalog
        name = "m5d.4xlarge" if nvme_gb else "m5.4xlarge"
        spec = next(s for s in build_catalog() if s.name == name)
        return spec

    def test_default_is_20gi_even_with_nvme(self):
        from karpenter_provider_aws_tpu.lattice.tensors import (
            DEFAULT_EBS_ROOT_MIB, ephemeral_storage_mib)
        spec = self._spec(nvme_gb=1)
        assert spec.local_nvme_gb > 0
        # default instanceStorePolicy leaves instance-store disks unused
        assert ephemeral_storage_mib(spec) == DEFAULT_EBS_ROOT_MIB

    def test_raid0_uses_local_store_total(self):
        from karpenter_provider_aws_tpu.lattice.tensors import (
            StorageConfig, ephemeral_storage_mib)
        spec = self._spec(nvme_gb=1)
        got = ephemeral_storage_mib(
            spec, StorageConfig(instance_store_policy="RAID0"))
        assert got == pytest.approx(spec.local_nvme_gb * 1000.0 / 1.048576)

    def test_raid0_without_local_store_falls_through(self):
        from karpenter_provider_aws_tpu.lattice.tensors import (
            DEFAULT_EBS_ROOT_MIB, StorageConfig, ephemeral_storage_mib)
        got = ephemeral_storage_mib(
            self._spec(), StorageConfig(instance_store_policy="RAID0"))
        assert got == DEFAULT_EBS_ROOT_MIB

    def test_root_volume_bdm_wins(self):
        from karpenter_provider_aws_tpu.lattice.tensors import (
            StorageConfig, ephemeral_storage_mib)
        sc = StorageConfig(block_device_mappings=(
            {"device_name": "/dev/xvdb", "volume_size_mib": 50 * 1024.0},
            {"device_name": "/dev/xvda", "root_volume": True,
             "volume_size_mib": 100 * 1024.0},
        ), ephemeral_block_device="/dev/xvda")
        assert ephemeral_storage_mib(self._spec(), sc) == 100 * 1024.0

    def test_family_device_bdm(self):
        from karpenter_provider_aws_tpu.lattice.tensors import (
            StorageConfig, ephemeral_storage_mib)
        sc = StorageConfig(block_device_mappings=(
            {"device_name": "/dev/xvda", "volume_size_mib": 80 * 1024.0},),
            ephemeral_block_device="/dev/xvda")
        assert ephemeral_storage_mib(self._spec(), sc) == 80 * 1024.0

    def test_custom_family_uses_last_bdm(self):
        from karpenter_provider_aws_tpu.lattice.tensors import (
            StorageConfig, ephemeral_storage_mib)
        sc = StorageConfig(block_device_mappings=(
            {"device_name": "/dev/sda1", "volume_size_mib": 30 * 1024.0},
            {"device_name": "/dev/sdb", "volume_size_mib": 60 * 1024.0},),
            custom_ami_family=True)
        assert ephemeral_storage_mib(self._spec(), sc) == 60 * 1024.0

    def test_nodeclass_wiring(self):
        from karpenter_provider_aws_tpu.apis.objects import NodeClass
        from karpenter_provider_aws_tpu.providers.amifamily import storage_config
        nc = NodeClass(name="x", ami_family="Bottlerocket",
                       instance_store_policy="RAID0")
        sc = storage_config(nc)
        assert sc.instance_store_policy == "RAID0"
        assert sc.ephemeral_block_device == "/dev/xvdb"
        nc2 = NodeClass(name="y", ami_family="Custom")
        assert storage_config(nc2).custom_ami_family

    def test_hash_covers_storage_policy(self):
        from karpenter_provider_aws_tpu.apis.objects import NodeClass
        from karpenter_provider_aws_tpu.cloudprovider.cloudprovider import nodeclass_hash
        a = NodeClass(name="x")
        b = NodeClass(name="x", instance_store_policy="RAID0")
        assert nodeclass_hash(a) != nodeclass_hash(b)

    def test_hash_version_restamps_instead_of_drifting(self):
        """A pre-upgrade claim (older hash formula) must be re-stamped, not
        reported NodeClassDrift fleet-wide (mirror of the NodePool
        hash-version guard, controllers/disruption.py)."""
        from karpenter_provider_aws_tpu.apis import wellknown as wk
        from karpenter_provider_aws_tpu.cloudprovider.cloudprovider import (
            NODECLASS_HASH_VERSION, nodeclass_hash)
        from karpenter_provider_aws_tpu.operator import Operator
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        from karpenter_provider_aws_tpu.apis.objects import (
            NodeClaim, NodeClaimPhase)
        op = Operator(clock=FakeClock())
        nc = op.node_classes["default"]
        claim = NodeClaim(name="c0", node_pool="default")
        claim.phase = NodeClaimPhase.LAUNCHED
        claim.annotations[wk.ANNOTATION_NODECLASS_HASH] = "stale-v1-hash"
        # no hash-version annotation = pre-upgrade claim
        assert op.cloud_provider.is_drifted(claim) != "NodeClassDrift"
        assert claim.annotations[wk.ANNOTATION_NODECLASS_HASH] == \
            nodeclass_hash(nc)
        assert claim.annotations[wk.ANNOTATION_NODECLASS_HASH_VERSION] == \
            NODECLASS_HASH_VERSION
        # same version, different hash = REAL drift
        claim.annotations[wk.ANNOTATION_NODECLASS_HASH] = "actually-changed"
        assert op.cloud_provider.is_drifted(claim) == "NodeClassDrift"
