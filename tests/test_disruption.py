"""Disruption tests: emptiness, consolidation (multi/single node), drift,
expiration, budgets, spot-to-spot guard (BASELINE config 4 behavior).

Behavioral spec: reference website concepts/disruption.md:16-27,87-129,
193-222 and designs/consolidation.md. The what-if repack queries run on the
device solver; these tests drive the full controller loop on a FakeClock.
"""

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Operator as ReqOp, Pod, Requirement
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.apis.objects import (
    DisruptionBudget, NodeClaimPhase, NodePoolDisruption,
)
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.utils.clock import FakeClock

_FAMILIES = ("m5", "c5", "r5", "t3")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog() if s.family in _FAMILIES])


def make_env(lattice, **pool_disruption):
    clock = FakeClock()
    disruption = NodePoolDisruption(**pool_disruption) if pool_disruption else NodePoolDisruption()
    # on-demand pool: spot capacity would (correctly) gate replacement
    # consolidation behind SpotToSpotConsolidation — tested separately
    pool = NodePool(name="default", disruption=disruption, requirements=[
        Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("on-demand",))])
    return Operator(options=Options(registration_delay=1.0), lattice=lattice,
                    cloud=FakeCloud(clock), clock=clock, node_pools=[pool])


def pods(n, cpu="500m", mem="1Gi", prefix="pod", **kw):
    return [Pod(name=f"{prefix}-{i}", requests={"cpu": cpu, "memory": mem}, **kw)
            for i in range(n)]


class TestEmptiness:
    def test_empty_node_deleted_after_consolidate_after(self, lattice):
        env = make_env(lattice, consolidate_after=30.0)
        for p in pods(4):
            env.cluster.add_pod(p)
        env.settle()
        assert len(env.cluster.claims) >= 1
        # drain the pods away (deployment scaled to zero)
        for p in list(env.cluster.pods):
            env.cluster.delete_pod(p)
        env.clock.step(31)
        env.run_once()   # disruption decides
        env.run_once()   # termination executes
        assert not env.cluster.claims
        assert all(i.state == "terminated" for i in env.cloud.instances.values())

    def test_empty_node_kept_before_window(self, lattice):
        env = make_env(lattice, consolidate_after=300.0)
        for p in pods(2):
            env.cluster.add_pod(p)
        env.settle()
        for p in list(env.cluster.pods):
            env.cluster.delete_pod(p)
        env.clock.step(30)
        env.run_once()
        env.run_once()
        assert env.cluster.claims, "node deleted before consolidate_after elapsed"


class TestConsolidation:
    def test_multi_node_repack(self, lattice):
        """Config-4 shape (scaled): many under-utilized nodes repack onto
        fewer when most pods disappear."""
        env = make_env(lattice, consolidate_after=10.0)
        # force one pod per node via hostname self-anti-affinity
        from karpenter_provider_aws_tpu.apis.objects import PodAffinityTerm
        anti = [PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                                label_selector=(("app", "spread"),), anti=True)]
        big = [Pod(name=f"b{i}", labels={"app": "spread"},
                   requests={"cpu": "3", "memory": "6Gi"}, pod_affinity=list(anti))
               for i in range(6)]
        for p in big:
            env.cluster.add_pod(p)
        env.settle()
        nodes_before = len(env.cluster.nodes)
        assert nodes_before == 6
        cost_before = sum(i.price for i in env.cloud.instances.values()
                          if i.state == "running")
        # replace the fleet's pods with tiny ones that could share one node
        for p in list(env.cluster.pods):
            env.cluster.delete_pod(p)
        for p in pods(6, cpu="250m", mem="256Mi", prefix="tiny"):
            env.cluster.add_pod(p)
        env.settle()
        env.clock.step(11)
        for _ in range(40):          # let disruption converge
            env.run_once()
            env.clock.step(2)
        running = [i for i in env.cloud.instances.values() if i.state == "running"]
        assert len(env.cluster.nodes) < nodes_before
        cost_after = sum(i.price for i in running)
        assert cost_after < cost_before
        # every tiny pod still bound
        assert all(p.node_name for p in env.cluster.pods.values())

    def test_single_node_cheaper_replacement(self, lattice):
        """A lone pod on an oversized node is moved to a cheaper node."""
        env = make_env(lattice, consolidate_after=10.0)
        # land a big+small pod pair, then remove the big one
        ps = pods(1, cpu="14", mem="24Gi", prefix="big") + pods(1, cpu="250m", mem="256Mi", prefix="small")
        for p in ps:
            env.cluster.add_pod(p)
        env.settle()
        assert len(env.cluster.nodes) == 1
        big_type = next(iter(env.cluster.claims.values())).instance_type
        env.cluster.delete_pod("big-0")
        env.clock.step(11)
        for _ in range(30):
            env.run_once()
            env.clock.step(2)
        assert all(p.node_name for p in env.cluster.pods.values())
        (claim,) = env.cluster.claims.values()
        new_price = env.solver.lattice.price[
            env.solver.lattice.name_to_idx[claim.instance_type]].min()
        old_price = env.solver.lattice.price[
            env.solver.lattice.name_to_idx[big_type]].min()
        assert new_price < old_price

    def test_replacement_launches_before_drain(self, lattice):
        """Mid-disruption there is never a moment with pods unbound AND no
        standing replacement capacity."""
        env = make_env(lattice, consolidate_after=5.0)
        ps = pods(1, cpu="14", mem="24Gi", prefix="big") + pods(1, cpu="250m", mem="256Mi", prefix="small")
        for p in ps:
            env.cluster.add_pod(p)
        env.settle()
        env.cluster.delete_pod("big-0")
        env.clock.step(6)
        env.disruption.reconcile()   # launches replacement, must NOT drain yet
        assert len(env.cluster.claims) == 2, "replacement should coexist with original"
        assert env.cluster.pods["small-0"].node_name is not None

    def test_consolidation_never_when_policy_empty(self, lattice):
        env = make_env(lattice, consolidate_after=5.0,
                       consolidation_policy="WhenEmpty")
        ps = pods(1, cpu="14", mem="24Gi", prefix="big") + pods(1, cpu="250m", mem="256Mi", prefix="small")
        for p in ps:
            env.cluster.add_pod(p)
        env.settle()
        env.cluster.delete_pod("big-0")
        env.clock.step(60)
        for _ in range(10):
            env.run_once()
            env.clock.step(2)
        (claim,) = env.cluster.claims.values()
        assert claim.phase == NodeClaimPhase.INITIALIZED


class TestSpotGuard:
    def _spot_env(self, lattice, gate: bool):
        clock = FakeClock()
        pool = NodePool(name="default",
                        requirements=[Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("spot",))],
                        disruption=NodePoolDisruption(consolidate_after=5.0))
        return Operator(options=Options(registration_delay=1.0,
                                        spot_to_spot_consolidation=gate),
                        lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                        node_pools=[pool])

    def test_spot_to_spot_blocked_without_gate(self, lattice):
        env = self._spot_env(lattice, gate=False)
        ps = pods(1, cpu="14", mem="24Gi", prefix="big") + pods(1, cpu="250m", mem="256Mi", prefix="small")
        for p in ps:
            env.cluster.add_pod(p)
        env.settle()
        big_claim = next(iter(env.cluster.claims.values()))
        env.cluster.delete_pod("big-0")
        env.clock.step(6)
        for _ in range(10):
            env.run_once()
            env.clock.step(2)
        # replacement consolidation did NOT happen (still the big node)
        assert big_claim.name in env.cluster.claims

    def test_spot_to_spot_allowed_with_gate_and_flexibility(self, lattice):
        env = self._spot_env(lattice, gate=True)
        ps = pods(1, cpu="14", mem="24Gi", prefix="big") + pods(1, cpu="250m", mem="256Mi", prefix="small")
        for p in ps:
            env.cluster.add_pod(p)
        env.settle()
        big_claim = next(iter(env.cluster.claims.values()))
        env.cluster.delete_pod("big-0")
        env.clock.step(6)
        for _ in range(30):
            env.run_once()
            env.clock.step(2)
        assert big_claim.name not in env.cluster.claims


class TestDriftAndExpiration:
    def test_drifted_claim_replaced(self, lattice):
        env = make_env(lattice)
        for p in pods(2):
            env.cluster.add_pod(p)
        env.settle()
        (claim,) = env.cluster.claims.values()
        env.node_classes["default"].user_data = "#!/bin/bash new"
        for _ in range(20):
            env.run_once()
            env.clock.step(2)
        claims = list(env.cluster.claims.values())
        assert claims and all(c.name != claim.name for c in claims)
        assert all(p.node_name for p in env.cluster.pods.values())

    def test_drift_disabled_gate(self, lattice):
        clock = FakeClock()
        env = Operator(options=Options(registration_delay=1.0, drift_enabled=False),
                       lattice=lattice, cloud=FakeCloud(clock), clock=clock)
        for p in pods(2):
            env.cluster.add_pod(p)
        env.settle()
        (claim,) = env.cluster.claims.values()
        env.node_classes["default"].user_data = "#!/bin/bash new"
        for _ in range(10):
            env.run_once()
            env.clock.step(2)
        assert claim.name in env.cluster.claims

    def test_expiration_replaces_old_nodes(self, lattice):
        env = make_env(lattice, expire_after=100.0)
        for p in pods(2):
            env.cluster.add_pod(p)
        env.settle()
        (claim,) = env.cluster.claims.values()
        env.clock.step(101)
        for _ in range(20):
            env.run_once()
            env.clock.step(2)
        claims = list(env.cluster.claims.values())
        assert claims and all(c.name != claim.name for c in claims)
        assert all(p.node_name for p in env.cluster.pods.values())


class TestBudgets:
    def test_budget_caps_parallel_empty_deletes(self, lattice):
        clock = FakeClock()
        pool = NodePool(name="default", disruption=NodePoolDisruption(
            consolidate_after=5.0,
            budgets=[DisruptionBudget(nodes="1")]))
        env = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                       cloud=FakeCloud(clock), clock=clock, node_pools=[pool])
        from karpenter_provider_aws_tpu.apis.objects import PodAffinityTerm
        anti = [PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                                label_selector=(("app", "a"),), anti=True)]
        for p in pods(3, cpu="2", mem="4Gi", labels={"app": "a"}, pod_affinity=anti):
            env.cluster.add_pod(p)
        env.settle()
        assert len(env.cluster.claims) == 3
        for p in list(env.cluster.pods):
            env.cluster.delete_pod(p)
        env.clock.step(6)
        env.disruption.reconcile()
        terminating = [c for c in env.cluster.claims.values() if c.deletion_timestamp]
        queued = sum(len(a.claims) for a in env.disruption._in_flight)
        assert queued <= 1, "budget of 1 must cap parallel disruption"

    def test_pricing_refresh_invalidates_failed_fingerprint(self, lattice):
        """Regression (round-1 ADVICE): a pricing refresh can turn a
        previously-unprofitable consolidation profitable, so the cached
        failed-search fingerprint must change with lattice.price_version."""
        env = make_env(lattice, consolidate_after=5.0)
        fp1 = env.disruption._fingerprint()
        env.solver.lattice.price_version += 1
        assert env.disruption._fingerprint() != fp1

    def test_replacement_respects_pool_limits(self, lattice):
        """Regression (round-1 ADVICE): disruption replacements must pass
        through the same NodePool-limits gate as fresh provisioning. A pool
        capped at its current usage cannot launch a replacement (launch-
        before-drain counts both), so consolidation is blocked."""
        env = make_env(lattice, consolidate_after=5.0)
        ps = pods(1, cpu="14", mem="24Gi", prefix="big") + \
            pods(1, cpu="250m", mem="256Mi", prefix="small")
        for p in ps:
            env.cluster.add_pod(p)
        env.settle()
        assert len(env.cluster.nodes) == 1
        (claim,) = env.cluster.claims.values()
        # cap the pool at exactly the current node's cpu: no headroom for a
        # replacement while the original still runs
        env.node_pools["default"].limits = {
            "cpu": str(int(claim.capacity["cpu"] / 1000.0))}
        env.cluster.delete_pod("big-0")
        env.clock.step(6)
        for _ in range(10):
            env.run_once()
            env.clock.step(2)
        # the oversized node survives: replacement would exceed the limit
        assert claim.name in env.cluster.claims
        assert not env.disruption._in_flight
        assert all(p.node_name for p in env.cluster.pods.values())

    def test_zero_budget_blocks_all(self, lattice):
        clock = FakeClock()
        pool = NodePool(name="default", disruption=NodePoolDisruption(
            consolidate_after=5.0, budgets=[DisruptionBudget(nodes="0")]))
        env = Operator(options=Options(registration_delay=1.0), lattice=lattice,
                       cloud=FakeCloud(clock), clock=clock, node_pools=[pool])
        for p in pods(2):
            env.cluster.add_pod(p)
        env.settle()
        for p in list(env.cluster.pods):
            env.cluster.delete_pod(p)
        env.clock.step(10)
        for _ in range(5):
            env.run_once()
            env.clock.step(2)
        assert env.cluster.claims, "0% budget must block disruption entirely"


class TestBatchedWhatIfs:
    def test_consolidation_pass_is_one_probe_plus_one_exact_solve(self, lattice):
        """The prefix ladder + single-node scan ride ONE batched probe
        kernel launch; only the winning candidate set pays an exact solve
        (SURVEY §2.2 "embarrassingly batchable" — was O(log n + budget)
        serial Solve() round trips)."""
        env = make_env(lattice, consolidate_after=10.0)
        from karpenter_provider_aws_tpu.apis.objects import PodAffinityTerm
        anti = [PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                                label_selector=(("app", "spread"),), anti=True)]
        big = [Pod(name=f"b{i}", labels={"app": "spread"},
                   requests={"cpu": "3", "memory": "6Gi"}, pod_affinity=list(anti))
               for i in range(6)]
        for p in big:
            env.cluster.add_pod(p)
        env.settle()
        assert len(env.cluster.nodes) == 6
        for p in list(env.cluster.pods):
            env.cluster.delete_pod(p)
        # one tiny anti-affine pod per (oversized) node: no node is ever
        # empty, so the decision must come from the consolidation search
        tiny = [Pod(name=f"t{i}", labels={"app": "spread"},
                    requests={"cpu": "250m", "memory": "256Mi"},
                    pod_affinity=list(anti))
                for i in range(6)]
        for p in tiny:
            env.cluster.add_pod(p)
        env.settle()
        assert all(self_pods for self_pods in
                   [[q for q in env.cluster.pods.values() if q.node_name == n]
                    for n in env.cluster.nodes]), "expected one pod per node"
        env.clock.step(11)

        calls = {"probe": 0, "solve": 0}
        orig_probe, orig_solve = env.solver.probe_batch, env.solver.solve

        def probe(problems):
            calls["probe"] += 1
            return orig_probe(problems)

        def solve(problem, mesh=None):
            calls["solve"] += 1
            return orig_solve(problem, mesh=mesh)

        env.solver.probe_batch, env.solver.solve = probe, solve
        try:
            env.disruption.reconcile()
        finally:
            env.solver.probe_batch, env.solver.solve = orig_probe, orig_solve
        # the decision landed (replacement launched, originals queued)
        assert env.disruption._in_flight, "consolidation should have begun"
        assert calls["probe"] == 1
        assert calls["solve"] <= 2, calls

    def test_failed_search_cache_expires_with_consolidate_after_window(self, lattice):
        """A failed consolidation search must not be cached across pure
        time passage: candidates become eligible when their
        consolidate_after window elapses even though no pod or claim moved."""
        env = make_env(lattice, consolidate_after=10.0)
        ps = pods(1, cpu="14", mem="24Gi", prefix="big") + \
            pods(1, cpu="250m", mem="256Mi", prefix="small")
        for p in ps:
            env.cluster.add_pod(p)
        env.settle()
        env.cluster.delete_pod("big-0")
        # search inside the window: fails, negative cache set
        env.disruption.reconcile()
        assert not env.disruption._in_flight
        # window elapses with NO cluster change: the cache must expire
        env.clock.step(11)
        env.disruption.reconcile()
        assert env.disruption._in_flight, \
            "consolidation blocked by a stale negative cache"


class TestScheduledBudgets:
    """Budget schedule+duration windows (reference disruption.md:193-222;
    CRD karpenter.sh_nodepools.yaml:97-112): a scheduled budget
    constrains only while inside its cron-opened window."""

    def test_cron_matching(self):
        from karpenter_provider_aws_tpu.utils.cron import Cron
        import calendar
        # 1970-01-01 is a Thursday (dow 4); epoch 0 = 00:00 UTC
        c = Cron("0 0 * * *")                      # daily at midnight
        assert c.matches(0.0)
        assert not c.matches(60.0)
        assert Cron("*/15 * * * *").matches(15 * 60)
        assert not Cron("*/15 * * * *").matches(16 * 60)
        assert Cron("* * * * 4").matches(0.0)       # Thursday
        assert not Cron("* * * * 5").matches(0.0)
        # window: daily-midnight schedule, 1h duration
        assert c.in_window(1800.0, 3600.0)          # 00:30 inside
        assert not c.in_window(7200.0, 3600.0)      # 02:00 outside
        import pytest
        with pytest.raises(ValueError):
            Cron("not a cron")
        with pytest.raises(ValueError):
            Cron("99 * * * *")

    def test_budget_constrains_only_in_window(self, lattice):
        from karpenter_provider_aws_tpu.apis.objects import (
            DisruptionBudget, NodePoolDisruption)
        clock = FakeClock(start=12 * 86400.0)  # a midnight UTC epoch
        pool = NodePool(
            name="default",
            requirements=[Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN,
                                      ("on-demand",))],
            disruption=NodePoolDisruption(
                consolidate_after=5.0,
                budgets=[DisruptionBudget(nodes="0", schedule="0 0 * * *",
                                          duration=3600.0)]))
        env = Operator(options=Options(registration_delay=1.0),
                       lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                       node_pools=[pool])
        ctrl = env.disruption
        # inside the maintenance freeze (00:00-01:00): zero allowed
        assert ctrl._allowed_disruptions(pool, "Underutilized") == 0 or \
            not env.cluster.claims  # no claims yet -> 0 anyway
        for i in range(4):
            env.cluster.add_pod(Pod(name=f"p{i}",
                                    requests={"cpu": "800m", "memory": "1536Mi"}))
        env.settle()
        assert ctrl._allowed_disruptions(pool, "Underutilized") == 0
        # step past the window: the budget no longer constrains
        clock.step(2 * 3600)
        assert ctrl._allowed_disruptions(pool, "Underutilized") > 0

    def test_consolidation_resumes_after_window(self, lattice):
        """The negative-cache fingerprint includes budget window state: a
        failed-during-freeze search re-arms when the window closes."""
        from karpenter_provider_aws_tpu.apis.objects import (
            DisruptionBudget, NodePoolDisruption)
        clock = FakeClock(start=12 * 86400.0)  # midnight UTC
        pool = NodePool(
            name="default",
            requirements=[Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN,
                                      ("on-demand",))],
            disruption=NodePoolDisruption(
                consolidate_after=5.0,
                budgets=[DisruptionBudget(nodes="0", schedule="0 0 * * *",
                                          duration=3600.0)]))
        env = Operator(options=Options(registration_delay=1.0),
                       lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                       node_pools=[pool])
        for i in range(4):
            env.cluster.add_pod(Pod(name=f"p{i}",
                                    requests={"cpu": "800m", "memory": "1536Mi"}))
        env.settle()
        for i in range(1, 4):
            env.cluster.delete_pod(f"p{i}")
        before = set(env.cluster.claims)
        clock.step(6)
        for _ in range(10):
            env.run_once()
            clock.step(3)
        assert set(env.cluster.claims) == before, "freeze window violated"
        clock.step(2 * 3600)
        for _ in range(20):
            env.run_once(force_provision=bool(env.cluster.pending_pods()))
            clock.step(3)
        assert set(env.cluster.claims) != before, \
            "search never re-armed after the budget window closed"

    def test_webhook_requires_schedule_with_duration(self):
        from karpenter_provider_aws_tpu.apis.objects import (
            DisruptionBudget, NodePoolDisruption)
        from karpenter_provider_aws_tpu.webhooks import validate_node_pool
        pool = NodePool(name="x", disruption=NodePoolDisruption(
            budgets=[DisruptionBudget(nodes="1", schedule="0 0 * * *")]))
        assert any("set together" in e for e in validate_node_pool(pool))
        pool2 = NodePool(name="x", disruption=NodePoolDisruption(
            budgets=[DisruptionBudget(nodes="1", schedule="bad cron here",
                                      duration=60.0)]))
        assert any("bad budget schedule" in e for e in validate_node_pool(pool2))

    def test_review_regressions(self):
        """Stray-comma cron parts raise; zero duration rejected at
        admission (it would make the window silently unsatisfiable)."""
        import pytest
        from karpenter_provider_aws_tpu.apis.objects import (
            DisruptionBudget, NodePoolDisruption)
        from karpenter_provider_aws_tpu.utils.cron import Cron
        from karpenter_provider_aws_tpu.webhooks import validate_node_pool
        with pytest.raises(ValueError):
            Cron("0, 0 * * *")
        pool = NodePool(name="x", disruption=NodePoolDisruption(
            budgets=[DisruptionBudget(nodes="0", schedule="0 0 * * *",
                                      duration=0.0)]))
        assert any("duration must be > 0" in e for e in validate_node_pool(pool))

    def test_step_syntax_vixie_semantics(self):
        """'0/6' in the hour field means 0,6,12,18 (vixie/robfig), not
        just hour 0."""
        from karpenter_provider_aws_tpu.utils.cron import Cron
        c = Cron("0 0/6 * * *")
        assert c.hour == {0, 6, 12, 18}
        assert Cron("0/15 * * * *").minute == {0, 15, 30, 45}


class TestHashVersionMigration:
    def test_formula_change_restamps_instead_of_rolling(self, lattice):
        """A claim stamped under an OLDER hash version is re-stamped on
        the next drift pass, never drifted for the formula change itself
        (a controller upgrade must not roll the fleet)."""
        from karpenter_provider_aws_tpu.controllers.provisioning import (
            NODEPOOL_HASH_VERSION, nodepool_hash)
        env = make_env(lattice, consolidate_after=300.0)
        for p in pods(2):
            env.cluster.add_pod(p)
        env.settle()
        (claim,) = env.cluster.claims.values()
        # simulate a pre-upgrade claim: stale formula, no/old version
        claim.annotations[wk.ANNOTATION_NODEPOOL_HASH] = "old-formula-hash"
        claim.annotations.pop(wk.ANNOTATION_NODEPOOL_HASH_VERSION, None)
        env.disruption._reconcile_drift()
        assert not claim.deletion_timestamp, "upgrade rolled the node"
        assert claim.annotations[wk.ANNOTATION_NODEPOOL_HASH] == \
            nodepool_hash(env.node_pools["default"])
        assert claim.annotations[wk.ANNOTATION_NODEPOOL_HASH_VERSION] == \
            NODEPOOL_HASH_VERSION
        # a REAL template change under the current version still drifts
        env.node_pools["default"].labels["rollme"] = "yes"
        env.disruption._reconcile_drift()
        assert any(a.reason == "Drifted" for a in env.disruption._in_flight)

    def test_startup_taints_participate_in_hash(self, lattice):
        """startupTaints are stamped on launched nodes (the init-daemon
        contract), so editing them must change the template hash and
        roll nodes exactly like taints do — the reference hashes them."""
        from karpenter_provider_aws_tpu.apis.objects import Taint
        from karpenter_provider_aws_tpu.controllers.provisioning import (
            nodepool_hash)
        pool = NodePool(name="st")
        before = nodepool_hash(pool)
        pool.startup_taints = [Taint(key="node.example.com/setup",
                                     value="pending", effect="NoSchedule")]
        assert nodepool_hash(pool) != before

    def test_slice_fields_hash_order_insensitively(self, lattice):
        """Reordering semantically-identical taints/requirements must
        NOT change the hash (the reference hashes slices as sets —
        hashstructure SlicesAsSets); a YAML reorder must never roll a
        fleet."""
        from karpenter_provider_aws_tpu.apis.objects import Taint
        from karpenter_provider_aws_tpu.controllers.provisioning import (
            nodepool_hash)
        t1 = Taint(key="a", value="1", effect="NoSchedule")
        t2 = Taint(key="b", value="2", effect="NoExecute")
        r1 = Requirement(wk.LABEL_ZONE, ReqOp.IN,
                         ("us-west-2a", "us-west-2b"))
        r2 = Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("spot",))
        p_fwd = NodePool(name="x", taints=[t1, t2],
                         startup_taints=[t2, t1], requirements=[r1, r2])
        r1_rev = Requirement(wk.LABEL_ZONE, ReqOp.IN,
                             ("us-west-2b", "us-west-2a"))
        p_rev = NodePool(name="x", taints=[t2, t1],
                         startup_taints=[t1, t2], requirements=[r2, r1_rev])
        assert nodepool_hash(p_fwd) == nodepool_hash(p_rev)


class TestWhatIfNodeVanishRace:
    def test_what_if_survives_candidate_node_deletion(self, lattice):
        """Soak-found race: a candidate's node can be deleted (interruption
        / GC under the threaded runtime) between candidate selection and
        the what-if solve — the vanished claim drops out of the whole
        what-if (exclusions, pods, AND price), never crashing the solve
        or over-crediting the savings."""
        env = make_env(lattice)
        for p in pods(4):
            env.cluster.add_pod(p)
        env.settle()
        claim = next(iter(env.cluster.claims.values()))
        node = env.cluster.node_for_claim(claim.name)
        assert node is not None
        env.cluster.evict_node(node.name)          # node gone, claim remains
        plan, removed_cost = env.disruption._what_if([claim])
        assert plan is not None                    # no AttributeError
        # the gone claim contributes NO savings credit and no pods
        assert removed_cost == 0.0
        assert not plan.new_nodes
