"""Vmapped consolidation engine tests (solver/consolidate.py +
controllers/disruption.py; behavioral spec docs/reference/consolidation.md).

Covers the engine seams the end-to-end disruption tests can't isolate:
the zero-leg probe cache (pending-churn hits, bin/price/unavailability
invalidation), the counted host fallback, the host-FFD savings referee,
the skip-code ledger lockstep (metric label + per-node ledger + audit
ring), the weather-advisory hold, the frontier re-verification rule (a
truncated/covered pass must probe NEW candidates first next pass), and
the per-(node, pdb) Unconsolidatable dedup + re-arm.
"""

import types

import pytest

from karpenter_provider_aws_tpu.apis import (
    NodePool, Operator as ReqOp, Pod, Requirement,
)
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.apis.objects import (
    DisruptionBudget, NodePoolDisruption, PodAffinityTerm,
    PodDisruptionBudget,
)
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.solver import taxonomy
from karpenter_provider_aws_tpu.solver.faults import FaultInjector
from karpenter_provider_aws_tpu.utils.clock import FakeClock

_FAMILIES = ("m5", "c5")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog()
                          if s.family in _FAMILIES])


def make_env(lattice, **pool_disruption):
    clock = FakeClock()
    disruption = (NodePoolDisruption(**pool_disruption)
                  if pool_disruption else NodePoolDisruption())
    pool = NodePool(name="default", disruption=disruption, requirements=[
        Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN, ("on-demand",))])
    return Operator(options=Options(registration_delay=1.0),
                    lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                    node_pools=[pool])


def spread_pods(n, cpu="500m", mem="1Gi", prefix="sp", start=0):
    """One pod per node via hostname self-anti-affinity on the group."""
    anti = [PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                            label_selector=(("grp", prefix),), anti=True)]
    return [Pod(name=f"{prefix}-{i}", labels={"grp": prefix},
                requests={"cpu": cpu, "memory": mem},
                pod_affinity=list(anti))
            for i in range(start, start + n)]


def overprovisioned_env(lattice, n=4, consolidate_after=5.0):
    """n oversized nodes each pinned non-empty by one tiny anti-affine
    pod: emptiness can't claim them, consolidation can."""
    env = make_env(lattice, consolidation_policy="WhenUnderutilized",
                   consolidate_after=consolidate_after)
    for p in spread_pods(n, cpu="3", mem="6Gi", prefix="big"):
        env.cluster.add_pod(p)
    env.settle(max_rounds=30)
    assert len(env.cluster.nodes) == n
    for i in range(n):
        env.cluster.delete_pod(f"big-{i}")
    anti = [PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                            label_selector=(("grp", "big"),), anti=True)]
    for i in range(n):
        env.cluster.add_pod(Pod(name=f"tiny-{i}", labels={"grp": "big"},
                                requests={"cpu": "250m", "memory": "256Mi"},
                                pod_affinity=list(anti)))
    env.settle(max_rounds=10)
    assert len(env.cluster.nodes) == n
    env.clock.step(consolidate_after + 1.0)
    return env


def singles(env):
    return [[c] for c in env.cluster.claims.values()]


class TestZeroLegCache:
    def test_pending_churn_served_from_cache(self, lattice):
        env = overprovisioned_env(lattice)
        eng = env.disruption.engine
        sets = singles(env)
        v1 = eng.probe(sets)
        assert eng.counters["vmapped_whatifs"] == 1
        assert eng.counters["batched_candidates"] == len(sets)
        assert not any(v.cached for v in v1)
        # same base problem: every verdict from cache, zero dispatches
        v2 = eng.probe(sets)
        assert all(v.cached for v in v2)
        assert eng.counters["vmapped_whatifs"] == 1
        assert eng.counters["fp_unchanged"] == len(sets)
        # pending-pod churn does not move the bin table
        env.cluster.add_pod(Pod(name="pending-only",
                                requests={"cpu": "100m",
                                          "memory": "64Mi"}))
        v3 = eng.probe(sets)
        assert all(v.cached for v in v3)
        assert eng.counters["vmapped_whatifs"] == 1
        # cached verdicts agree with the originals
        assert [v.probe for v in v3] == [v.probe for v in v1]

    def test_bin_change_invalidates(self, lattice):
        env = overprovisioned_env(lattice)
        eng = env.disruption.engine
        sets = singles(env)
        eng.probe(sets)
        assert all(v.cached for v in eng.probe(sets))
        # a BOUND pod leaving dirties its node's bin: whole cache clears
        env.cluster.delete_pod("tiny-0")
        v = eng.probe(sets)
        assert not any(x.cached for x in v)
        assert eng.counters["cache_invalidations"] == 1
        assert eng.counters["vmapped_whatifs"] == 2

    def test_price_and_unavailability_invalidate(self, lattice):
        env = overprovisioned_env(lattice)
        eng = env.disruption.engine
        sets = singles(env)
        eng.probe(sets)
        env.unavailable.mark_unavailable(
            "InsufficientInstanceCapacity", "on-demand", "m5.large",
            lattice.zones[0])
        assert not any(v.cached for v in eng.probe(sets))
        assert eng.counters["cache_invalidations"] == 1
        # repopulated under the new anchor; a price refresh clears again
        assert all(v.cached for v in eng.probe(sets))
        env.solver.lattice.price_version += 1
        assert not any(v.cached for v in eng.probe(sets))
        assert eng.counters["cache_invalidations"] == 2


class TestHostFallback:
    def test_wave_scale_set_flagged_and_counted(self, lattice):
        # 4 pods bound onto one node -> the candidate's what-if carries
        # 4 evictee groups; a g_limit of 1 puts it past the compiled
        # bucket ceiling and outside the vmapped envelope
        env = make_env(lattice)
        # distinct requests: identical pods coalesce into ONE group and
        # G=1 never crosses the ceiling
        for i in range(4):
            env.cluster.add_pod(Pod(name=f"p-{i}",
                                    requests={"cpu": f"{500 + 10 * i}m",
                                              "memory": "1Gi"}))
        env.settle()
        assert len(env.cluster.claims) == 1
        eng = env.disruption.engine
        # settle's own disruption passes may have probed (and cached)
        # this very set under no faults — the envelope check only runs
        # for cache misses
        eng._cache.clear()
        dispatches = eng.counters["vmapped_whatifs"]
        env.solver.inject_faults(FaultInjector(g_limit=1))
        try:
            v = eng.probe(singles(env))
            assert v[0].host and not v[0].cached
            assert eng.counters["host_fallbacks"] == 1
            # a fallback set pays no dispatch and is never cached
            assert eng.counters["vmapped_whatifs"] == dispatches
            assert not eng._cache
        finally:
            env.solver.inject_faults(None)


class TestReferee:
    def test_accepts_within_envelope(self, lattice):
        env = overprovisioned_env(lattice, n=2)
        eng = env.disruption.engine
        claim = next(iter(env.cluster.claims.values()))
        ok, ratio = eng.referee([claim],
                                types.SimpleNamespace(new_node_cost=0.0))
        assert ok
        assert eng.counters["referee_checks"] == 1
        assert eng.counters["referee_rejects"] == 0

    def test_rejects_outside_envelope(self, lattice):
        env = overprovisioned_env(lattice, n=2)
        eng = env.disruption.engine
        claim = next(iter(env.cluster.claims.values()))
        # the oracle can always place one tiny evictee; a device plan
        # claiming a $1e9/hr replacement is outside any 2% envelope
        ok, ratio = eng.referee([claim],
                                types.SimpleNamespace(new_node_cost=1e9))
        assert not ok and ratio > 1.02
        assert eng.counters["referee_rejects"] == 1


class TestSkipLedger:
    def test_note_skip_lockstep(self, lattice):
        env = make_env(lattice)
        eng = env.disruption.engine
        eng.note_skip("node-a", taxonomy.NOT_CONSOLIDATABLE_PDB,
                      "pdb web-pdb prevents pod evictions")
        st = eng.stats()
        assert st["skip_not_consolidatable_pdb"] == 1
        doc = eng.ledger_doc()["node-a"]
        assert doc["code"] == taxonomy.NOT_CONSOLIDATABLE_PDB
        assert "web-pdb" in doc["detail"]
        # the decision-audit ring (kpctl explain node) got the same entry
        entry = eng.audit.find_node("node-a")
        assert entry and entry["code"] == taxonomy.NOT_CONSOLIDATABLE_PDB

    def test_unknown_code_rejected(self, lattice):
        env = make_env(lattice)
        with pytest.raises(AssertionError):
            env.disruption.engine.note_skip("n", "not-a-real-code")

    def test_note_accept_clears_ledger(self, lattice):
        env = make_env(lattice)
        eng = env.disruption.engine
        eng.note_skip("node-b", taxonomy.CONSOLIDATION_NO_SAVINGS)
        eng.note_accept([types.SimpleNamespace(name="node-b")], 0.25)
        assert "node-b" not in eng.ledger_doc()
        assert eng.counters["nodes_consolidated"] == 1
        assert eng.counters["savings_per_hour"] == pytest.approx(0.25)

    def test_taxonomy_codes_declared(self):
        for code in (taxonomy.NOT_CONSOLIDATABLE_PDB,
                     taxonomy.NOT_CONSOLIDATABLE_BUDGET,
                     taxonomy.CONSOLIDATION_NO_SAVINGS,
                     taxonomy.CONSOLIDATION_WEATHER_HOLD,
                     taxonomy.CONSOLIDATION_SPOT_GUARD):
            assert code in taxonomy.CODES


class TestWeatherGate:
    def test_hold_blocks_then_resumes(self, lattice):
        env = overprovisioned_env(lattice)
        eng = env.disruption.engine
        eng.weather_advisory = lambda: {"hold": True, "reason": "spot-crash"}
        before = set(env.cluster.claims)
        for _ in range(3):
            env.disruption._reconcile_once()
        assert set(env.cluster.claims) == before
        assert eng.counters["weather_holds"] >= 1
        assert eng.stats()["skip_consolidation_weather_hold"] >= len(before)
        codes = {d["code"] for d in eng.ledger_doc().values()}
        assert codes == {taxonomy.CONSOLIDATION_WEATHER_HOLD}
        # a held pass is truncated, never negative-cached: the search
        # resumes the moment the advisory clears
        eng.weather_advisory = lambda: {"hold": False, "reason": ""}
        assert env.disruption._reconcile_once()
        assert eng.counters["accepted"] >= 1

    def test_broken_advisory_never_wedges(self, lattice):
        env = make_env(lattice)
        eng = env.disruption.engine

        def boom():
            raise RuntimeError("advisory down")

        eng.weather_advisory = boom
        assert eng.weather_hold() == ""


class TestBudgetPacing:
    def test_zero_budget_codes_and_refuses(self, lattice):
        env = overprovisioned_env(lattice)
        pool = env.node_pools["default"]
        pool.disruption.budgets = [DisruptionBudget(nodes="0")]
        before = set(env.cluster.claims)
        for _ in range(2):
            env.disruption._reconcile_once()
        assert set(env.cluster.claims) == before
        assert not env.disruption._in_flight
        st = env.disruption.engine.stats()
        assert st["skip_not_consolidatable_budget"] >= 1
        # probes still ran (pre-checked budget, not a dead pass)
        assert st["vmapped_whatifs"] >= 1
        # opening the budget lets the SAME state consolidate (the budget
        # skip never negative-cached the pass)
        pool.disruption.budgets = [DisruptionBudget(nodes="1")]
        assert env.disruption._reconcile_once()
        assert env.disruption.engine.counters["accepted"] == 1


class TestFrontierReverification:
    def test_new_candidate_jumps_the_scan_window(self, lattice):
        """Satellite pin: after the frontier is fully covered under one
        fingerprint, a candidate that ENTERS the frontier by pure time
        passage (its consolidate_after window elapsing — no pod/claim
        motion) must be probed in the very next pass, ahead of nodes the
        sweep already probed, even with a 1-wide scan window."""
        ca = 60.0
        env = make_env(lattice, consolidation_policy="WhenUnderutilized",
                       consolidate_after=ca)
        # right-sized one-pod-per-node fleet: every probe is negative
        # (anti-affinity pins pods, a same-price replacement saves $0),
        # so passes sweep and cover without ever consolidating
        for p in spread_pods(3, prefix="sp"):
            env.cluster.add_pod(p)
        env.settle(max_rounds=30)
        assert len(env.cluster.claims) == 3
        env.disruption.MAX_SINGLE_PROBES = 1
        env.clock.step(ca + 1.0)
        old = set(env.cluster.claims)
        for _ in range(3):
            assert not env.disruption._reconcile_once()
        assert env.disruption._covered == old
        # a 4th node joins, too YOUNG to be a candidate. Disruption is
        # suppressed while it binds: mid-settle its pod is NOMINATED,
        # not bound, and a nominated pod's anti-affinity is invisible to
        # the what-if — the transient would thrash the fleet and rotate
        # every claim name out from under the test
        orig_reconcile = env.disruption.reconcile
        env.disruption.reconcile = lambda: None
        try:
            env.cluster.add_pod(spread_pods(1, prefix="sp", start=3)[0])
            env.settle(max_rounds=30)
        finally:
            env.disruption.reconcile = orig_reconcile
        new_name = (set(env.cluster.claims) - old).pop()
        new_claim = env.cluster.claims[new_name]
        for _ in range(3):   # re-cover the old frontier under the new fp
            env.disruption._reconcile_once()
        assert env.disruption._covered == old
        # pure time passage: the new claim ages into the frontier with
        # zero journal movement. The next 1-wide window must probe IT —
        # not resume the rotation at an already-covered node
        ref = new_claim.initialized_at or new_claim.created_at
        remaining = (ref + ca) - env.clock.now()
        assert remaining > 0, "premise broken: new claim already eligible"
        env.clock.step(remaining + 0.5)
        env.disruption._reconcile_once()
        assert env.disruption._covered == {new_name}


class TestPdbDedupRearm:
    def _blocked_env(self, lattice):
        # budget 0: no disruption method may ACT (emptiness would claim
        # a node the moment its web pod leaves, and a terminating claim
        # can never re-enter candidacy) — episode bookkeeping only
        env = make_env(lattice, consolidation_policy="WhenUnderutilized",
                       consolidate_after=5.0,
                       budgets=[DisruptionBudget(nodes="0")])
        for p in spread_pods(3, prefix="web"):
            env.cluster.add_pod(p)
        env.settle(max_rounds=30)
        assert len(env.cluster.nodes) == 3
        env.clock.step(6.0)
        env.cluster.add_pdb(PodDisruptionBudget(
            name="web-pdb", label_selector={"grp": "web"},
            max_unavailable=0))
        return env

    def test_one_event_and_skip_per_episode(self, lattice):
        env = self._blocked_env(lattice)
        nodes = set(env.cluster.nodes)
        for _ in range(4):
            env.disruption._reconcile_once()
        events = env.recorder.events(reason="Unconsolidatable")
        # once per (node, pdb) episode — 4 passes must not republish
        assert len(events) == len(nodes)
        st = env.disruption.engine.stats()
        assert st["skip_not_consolidatable_pdb"] == len(nodes)
        ledger = env.disruption.engine.ledger_doc()
        assert {n for n in ledger} == nodes
        assert all(d["code"] == taxonomy.NOT_CONSOLIDATABLE_PDB
                   for d in ledger.values())

    def test_rearm_on_pdb_change(self, lattice):
        env = self._blocked_env(lattice)
        nodes = set(env.cluster.nodes)
        for _ in range(2):
            env.disruption._reconcile_once()
        assert len(env.recorder.events(reason="Unconsolidatable")) \
            == len(nodes)
        # the pdb relaxes: blockage episode ends, dedup re-arms...
        env.cluster.delete_pdb("web-pdb")
        env.disruption._reconcile_once()
        # ...and a NEW zero-allowance pdb is a NEW episode per node
        env.cluster.add_pdb(PodDisruptionBudget(
            name="web-pdb", label_selector={"grp": "web"},
            max_unavailable=0))
        for _ in range(2):
            env.disruption._reconcile_once()
        assert len(env.recorder.events(reason="Unconsolidatable")) \
            == 2 * len(nodes)
        assert env.disruption.engine.stats()[
            "skip_not_consolidatable_pdb"] == 2 * len(nodes)

    def test_rearm_on_pod_churn(self, lattice):
        env = self._blocked_env(lattice)
        for _ in range(2):
            env.disruption._reconcile_once()
        node = next(iter(env.cluster.nodes))
        victim = next(p for p in env.cluster.snapshot_pods()
                      if p.node_name == node and not p.is_daemonset)
        before = len(env.recorder.events(reason="Unconsolidatable"))
        # the blocking pod leaves: the node's episode ends
        env.cluster.delete_pod(victim.name)
        env.disruption._reconcile_once()
        # a fresh pod under the same pdb re-blocks it: new episode.
        # Anti-affinity on the group pins it to the ONE node with no web
        # pod left (the victim's) — or a fresh node; either is a new
        # (node, pdb) episode
        anti = [PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                                label_selector=(("grp", "web"),),
                                anti=True)]
        env.cluster.add_pod(Pod(name="web-again", labels={"grp": "web"},
                                requests={"cpu": "250m",
                                          "memory": "256Mi"},
                                pod_affinity=anti))
        env.settle(max_rounds=10)
        for _ in range(2):
            env.disruption._reconcile_once()
        assert len(env.recorder.events(reason="Unconsolidatable")) \
            == before + 1
