import pytest

from karpenter_provider_aws_tpu.utils.units import (
    format_quantity,
    parse_cpu_millis,
    parse_mem_mib,
    parse_quantity,
)


def test_plain_numbers():
    assert parse_quantity("5") == 5
    assert parse_quantity(3) == 3
    assert parse_quantity("2.5") == 2.5


def test_binary_suffixes():
    assert parse_quantity("1Ki") == 1024
    assert parse_quantity("1Mi") == 2**20
    assert parse_quantity("16Gi") == 16 * 2**30


def test_decimal_suffixes():
    assert parse_quantity("1k") == 1000
    assert parse_quantity("100m") == pytest.approx(0.1)
    assert parse_quantity("1G") == 1e9


def test_cpu_millis():
    assert parse_cpu_millis("1") == 1000
    assert parse_cpu_millis("100m") == pytest.approx(100)
    assert parse_cpu_millis("2.5") == 2500


def test_mem_mib():
    assert parse_mem_mib("1Gi") == 1024
    assert parse_mem_mib("512Mi") == 512
    assert parse_mem_mib(2**20) == 1


def test_invalid():
    with pytest.raises(ValueError):
        parse_quantity("abc")
    with pytest.raises(ValueError):
        parse_quantity("1Qi")


def test_format_roundtrip_binary():
    assert format_quantity(2**30) == "1Gi"
    assert format_quantity(512 * 2**20) == "512Mi"
    assert format_quantity(5) == "5"


class TestLogging:
    def test_change_monitor_logs_on_delta_only(self):
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        from karpenter_provider_aws_tpu.utils.logging import ChangeMonitor
        clock = FakeClock()
        m = ChangeMonitor(clock, ttl=100.0)
        assert m.has_changed("k", 1)        # first observation
        assert not m.has_changed("k", 1)    # steady state: quiet
        assert m.has_changed("k", 2)        # delta
        assert not m.has_changed("k", 2)
        clock.step(101.0)
        assert m.has_changed("k", 2)        # TTL re-asserts the fact

    def test_structured_logger_formats_kv(self, capsys):
        import logging as _logging
        from karpenter_provider_aws_tpu.utils import logging as klog
        klog.configure("DEBUG")
        log = klog.get_logger("test")
        handler = _logging.getLogger("karpenter").handlers[0]
        record = _logging.LogRecord("karpenter.test", _logging.INFO, "", 0,
                                    "hello", (), None)
        record.kv = {"b": 2, "a": 1}
        line = handler.format(record)
        assert line.endswith("hello a=1 b=2")
        assert "INFO" in line
