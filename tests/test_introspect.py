"""Introspection layer tests (docs/reference/introspection.md).

Covers the tentpole contracts of introspect/:

- registry semantics: replace-by-name, error isolation, and the
  lock-discipline pin — NO lock held across the stats() fan-out, and a
  provider snapshot is O(1) work per collect (called exactly once).
- sampler: bounded rings, numeric-only series, late-key backfill.
- SLO tracker: burn math against the 200 ms / 2% budgets, the sustained
  SloBudgetBurn event (fire once per episode, re-arm on recovery), and
  the cadence-gated FFD cost referee.
- operator wiring: every registered provider reports after a real
  provisioning pass; pods_state/build_info/slo gauges render; statusz +
  vars serve over live HTTP on BOTH the metrics server and the REST
  apiserver; `kpctl top --once` renders against the live surface.
"""

import json
import threading
import time
import urllib.request

import pytest

from karpenter_provider_aws_tpu import introspect
from karpenter_provider_aws_tpu.apis import Pod
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.events import Recorder
from karpenter_provider_aws_tpu.introspect import (IntrospectRegistry,
                                                   Sampler, SloTracker)
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.metrics import Registry, wire_core_metrics
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.utils.clock import FakeClock

_FAMILIES = ("m5", "c5")


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog()
                          if s.family in _FAMILIES])


@pytest.fixture()
def env(lattice):
    clock = FakeClock()
    return Operator(options=Options(registration_delay=1.0),
                    lattice=lattice, cloud=FakeCloud(clock), clock=clock)


def pods(n, cpu="500m", mem="1Gi", prefix="pod"):
    return [Pod(name=f"{prefix}-{i}", requests={"cpu": cpu, "memory": mem})
            for i in range(n)]


class TestRegistry:
    def test_replace_by_name_and_unregister(self):
        reg = IntrospectRegistry()
        reg.register("x", lambda: {"v": 1})
        reg.register("x", lambda: {"v": 2})
        assert reg.names() == ["x"]
        assert reg.collect() == {"x": {"v": 2}}
        reg.unregister("x")
        assert reg.collect() == {}

    def test_broken_provider_is_isolated(self):
        reg = IntrospectRegistry()
        reg.register("good", lambda: {"v": 1})
        reg.register("bad", lambda: 1 / 0)
        snap = reg.collect()
        assert snap["good"] == {"v": 1}
        assert "ZeroDivisionError" in snap["bad"]["error"]

    def test_non_dict_stats_wrap(self):
        reg = IntrospectRegistry()
        reg.register("scalar", lambda: 42)
        assert reg.collect() == {"scalar": {"value": 42}}

    def test_provider_called_exactly_once_per_collect(self):
        # the O(1)-snapshot pin: one collect = one stats() call per
        # provider, never a retry/double-render
        calls = []
        reg = IntrospectRegistry()
        reg.register("counted", lambda: calls.append(1) or {"n": len(calls)})
        reg.collect()
        reg.collect()
        assert len(calls) == 2

    def test_no_lock_held_across_stats_fanout(self):
        """The lock-discipline pin: while one provider's stats() is
        BLOCKED mid-collect, register() (and the registry lock) must
        stay available — the fan-out runs outside the lock."""
        reg = IntrospectRegistry()
        entered = threading.Event()
        release = threading.Event()

        def blocking_stats():
            entered.set()
            assert release.wait(5.0)
            return {"ok": 1}

        reg.register("blocker", blocking_stats)
        result = {}
        t = threading.Thread(target=lambda: result.update(reg.collect()),
                             daemon=True)
        t.start()
        assert entered.wait(5.0)
        # mid-fan-out: registration must not deadlock behind the
        # blocked provider
        done = threading.Event()

        def try_register():
            reg.register("late", lambda: {"late": 1})
            done.set()
        threading.Thread(target=try_register, daemon=True).start()
        assert done.wait(1.0), "register() blocked during stats() fan-out"
        release.set()
        t.join(5.0)
        assert result["blocker"] == {"ok": 1}
        # the provider registered mid-collect reports from the NEXT one
        assert "late" in reg.collect()

    def test_solver_stats_never_takes_the_solve_lock(self, env):
        """A stats() snapshot must not queue behind an in-flight device
        solve: hold the solver lock and assert stats() still returns."""
        got = {}
        with env.solver._solve_lock:
            t = threading.Thread(
                target=lambda: got.update(env.solver.stats()), daemon=True)
            t.start()
            t.join(2.0)
            assert not t.is_alive(), "Solver.stats() blocked on the " \
                                     "solve lock"
        assert "pipeline" in got


class TestSampler:
    def test_ring_bounded_and_series_aligned(self):
        reg = IntrospectRegistry()
        n = [0]

        def stats():
            n[0] += 1
            return {"count": n[0], "label": "str-excluded",
                    "flag": True}
        reg.register("p", stats)
        s = Sampler(reg, ring=4)
        for _ in range(10):
            s.sample_once()
        series = s.series()["p"]
        assert len(series["t"]) == 4
        # only numerics ride the ring (bools are flags, not series)
        assert set(series["series"]) == {"count"}
        assert series["series"]["count"] == [7.0, 8.0, 9.0, 10.0]
        assert s.samples_taken == 10

    def test_late_key_backfills_zero(self):
        reg = IntrospectRegistry()
        stats = {"a": 1}
        reg.register("p", lambda: dict(stats))
        s = Sampler(reg, ring=8)
        s.sample_once()
        stats["b"] = 5
        s.sample_once()
        series = s.series()["p"]["series"]
        assert series["b"] == [0.0, 5.0]

    def test_thread_lifecycle(self):
        reg = IntrospectRegistry()
        reg.register("p", lambda: {"v": 1})
        s = Sampler(reg, ring=16).start(interval=0.01)
        deadline = time.monotonic() + 5.0
        while s.samples_taken < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        s.stop()
        assert s.samples_taken >= 3


class TestSloTracker:
    def _tracker(self, **kw):
        clock = FakeClock()
        rec = Recorder(clock)
        reg = Registry()
        wire_core_metrics(reg)
        t = SloTracker(clock, recorder=rec, metrics=reg, **kw)
        return t, clock, rec, reg

    def test_latency_burn_math_and_gauges(self):
        t, clock, _, reg = self._tracker()
        for _ in range(10):
            t.record_latency(0.1)     # p50 100 ms of a 200 ms budget
        out = t.update()
        assert out["latency_burn"] == pytest.approx(0.5)
        assert out["latency_p50_ms"] == pytest.approx(100.0)
        assert reg.get("karpenter_slo_latency_budget_burn").value() \
            == pytest.approx(0.5)

    def test_cost_burn_math(self):
        t, clock, _, reg = self._tracker()
        t.record_cost_ratio(1.04)     # 4% regression of a 2% budget
        out = t.update()
        assert out["cost_burn"] == pytest.approx(2.0)
        assert reg.get("karpenter_slo_cost_budget_burn").value() \
            == pytest.approx(2.0)
        # a BETTER-than-referee plan (<1.0 ratio) burns nothing
        t2, _, _, _ = self._tracker()
        t2.record_cost_ratio(0.98)
        assert t2.update()["cost_burn"] == 0.0

    def test_window_prunes_old_samples(self):
        t, clock, _, _ = self._tracker(window_seconds=60.0)
        t.record_latency(1.0)
        assert t.update()["latency_burn"] > 1.0
        clock.step(61)
        assert t.update()["latency_burn"] == 0.0

    def test_sustained_burn_fires_once_then_rearms(self):
        t, clock, rec, _ = self._tracker(window_seconds=1000.0,
                                         sustain_seconds=30.0)
        t.record_latency(0.5)         # burn 2.5
        t.update()                    # burn starts; not yet sustained
        assert rec.events(reason="SloBudgetBurn") == []
        clock.step(31)
        t.record_latency(0.5)
        t.update()
        events = rec.events(reason="SloBudgetBurn")
        assert len(events) == 1
        assert "latency" in events[0].message
        # still burning: no re-fire within the episode
        clock.step(31)
        t.update()
        assert len(rec.events(reason="SloBudgetBurn")) == 1
        # recovery re-arms: a NEW sustained episode fires again
        clock.step(2000)              # window empties -> burn 0
        t.update()
        t.record_latency(0.5)
        t.update()
        clock.step(31)
        t.record_latency(0.5)
        t.update()
        assert len(rec.events(reason="SloBudgetBurn")) == 2

    def test_cost_referee_cadence_gated(self, env):
        """maybe_cost_referee runs the host FFD re-pack at most once per
        referee_interval, and records a sane ratio."""
        built = []
        env.cluster.pods.clear()
        for p in pods(4, prefix="ref"):
            env.cluster.add_pod(p)
        pending = env.cluster.pending_pods()
        from karpenter_provider_aws_tpu.lattice.tensors import \
            masked_view_versioned
        lattice = masked_view_versioned(env.solver.lattice, env.unavailable)
        plan = env.solver.solve_relaxed(pending,
                                        list(env.node_pools.values()),
                                        lattice)
        assert plan.new_nodes

        def builder():
            from karpenter_provider_aws_tpu.solver.problem import \
                build_problem
            built.append(1)
            return build_problem(pending, list(env.node_pools.values()),
                                 lattice)
        ratio = env.slo.maybe_cost_referee(plan, builder)
        assert ratio is not None and 0.5 < ratio < 2.0
        # within the interval: gated, the builder is never invoked
        assert env.slo.maybe_cost_referee(plan, builder) is None
        assert len(built) == 1
        env.clock.step(env.slo.referee_interval + 1)
        assert env.slo.maybe_cost_referee(plan, builder) is not None
        assert len(built) == 2

    def test_referee_failure_is_contained(self):
        t, clock, _, _ = self._tracker()

        class FakePlan:
            new_nodes = [object()]
            new_node_cost = 1.0
        assert t.maybe_cost_referee(FakePlan(), lambda: 1 / 0) is None
        assert t.referee_errors == 1


class TestOperatorWiring:
    def test_every_provider_reports_after_a_pass(self, env):
        for p in pods(6):
            env.cluster.add_pod(p)
        env.settle(max_rounds=20)
        snap = introspect.registry().collect()
        for name in ("cluster", "solver", "provisioner", "ice_cache",
                     "writer", "events", "cloud_batcher",
                     "provider_caches", "slo", "flight_recorder"):
            assert name in snap, f"provider {name} not registered"
            assert "error" not in snap[name], snap[name]
        assert snap["cluster"]["nodes"] >= 1
        assert snap["provisioner"]["passes"] >= 1
        assert snap["provisioner"]["last_pass_pods"] == 6
        assert snap["writer"]["create_claim"] >= 1
        assert snap["writer"]["bind_pod"] >= 1
        assert snap["slo"]["latency_samples"] >= 1

    def test_pods_state_and_build_info_gauges(self, env):
        for p in pods(4, prefix="gauge"):
            env.cluster.add_pod(p)
        env.settle(max_rounds=20)
        text = env.metrics.render()
        assert 'karpenter_pods_state{phase="bound"} 4.0' in text
        assert 'karpenter_pods_state{phase="pending"} 0.0' in text
        assert "karpenter_build_info{" in text
        assert 'version="' in text
        assert "karpenter_slo_latency_budget_burn" in text

    def test_statusz_and_vars_render(self, env):
        env.sampler.sample_once()
        sz = introspect.statusz_text()
        assert sz.startswith("karpenter-tpu statusz")
        assert "== cluster ==" in sz
        doc = introspect.vars_doc(include_series=True)
        json.dumps(doc)   # must be JSON-serializable end to end
        assert "cluster" in doc["providers"]
        assert "cluster" in doc["series"]
        assert doc["sampler"]["samples"] >= 1

    def test_slo_latency_recorded_by_provision_pass(self, env):
        for p in pods(3, prefix="slo"):
            env.cluster.add_pod(p)
        env.provisioner.provision_once()
        stats = env.slo.stats()
        assert stats["latency_samples"] >= 1
        assert stats["latency_p50_ms"] > 0


class TestHttpSurfaces:
    @pytest.fixture()
    def served(self, env):
        from karpenter_provider_aws_tpu.cli import start_server
        server = start_server(env, 0)
        yield env, f"http://127.0.0.1:{server.server_address[1]}"
        server.shutdown()

    def test_metrics_server_serves_statusz_and_vars(self, served):
        env, base = served
        env.sampler.sample_once()
        sz = urllib.request.urlopen(base + "/debug/statusz",
                                    timeout=10).read().decode()
        assert "== solver ==" in sz
        doc = json.loads(urllib.request.urlopen(
            base + "/debug/vars?series=1", timeout=10).read())
        assert set(introspect.registry().names()) <= set(doc["providers"])
        assert "series" in doc
        lean = json.loads(urllib.request.urlopen(
            base + "/debug/vars", timeout=10).read())
        assert "series" not in lean   # rings only on request

    def test_rest_apiserver_serves_debug_routes(self, lattice):
        from karpenter_provider_aws_tpu.kube import FakeAPIServer
        from karpenter_provider_aws_tpu.kube.httpserver import serve
        clock = FakeClock()
        api = FakeAPIServer()
        op = Operator(options=Options(registration_delay=1.0),
                      lattice=lattice, cloud=FakeCloud(clock), clock=clock,
                      api_server=api)
        httpd = serve(api, 0)
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            sz = urllib.request.urlopen(base + "/debug/statusz",
                                        timeout=10).read().decode()
            assert "== watch_hub ==" in sz   # API mode registers the hub
            doc = json.loads(urllib.request.urlopen(
                base + "/debug/vars", timeout=10).read())
            assert doc["providers"]["watch_hub"]["watchers"] >= 0
        finally:
            httpd.shutdown()

    def test_kpctl_top_tolerates_errored_provider(self, monkeypatch):
        """A provider reporting the registry's {"error": ...} shape drops
        its row's details instead of crashing the view."""
        import pathlib
        monkeypatch.syspath_prepend(str(
            pathlib.Path(__file__).resolve().parent.parent / "tools"))
        import kpctl
        doc = {"providers": {"writer": {"error": "RuntimeError: boom"},
                             "cluster": {"error": "RuntimeError: boom"}}}
        lines = kpctl._render_top(doc, "srv")
        assert any(line.startswith("WRITER") for line in lines)

    def test_debug_routes_carry_server_time(self, lattice):
        """The PR 2 invariant holds on the new mounts: every apiserver
        response — /debug/vars included — carries X-Server-Time."""
        from karpenter_provider_aws_tpu.kube import FakeAPIServer
        from karpenter_provider_aws_tpu.kube.httpserver import serve
        api = FakeAPIServer()
        httpd = serve(api, 0)
        try:
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{httpd.server_address[1]}/debug/vars",
                timeout=10)
            assert float(resp.headers["X-Server-Time"]) > 0
        finally:
            httpd.shutdown()

    def test_kpctl_top_once_renders(self, served, capsys, monkeypatch):
        import pathlib
        monkeypatch.syspath_prepend(str(
            pathlib.Path(__file__).resolve().parent.parent / "tools"))
        import kpctl
        env, base = served
        for p in pods(2, prefix="top"):
            env.cluster.add_pod(p)
        env.settle(max_rounds=20)
        rc = kpctl.main(["--server", base, "top", "--once"])
        out = capsys.readouterr().out
        assert rc == 0
        assert "CLUSTER" in out and "SOLVER" in out and "SLO" in out
        assert "latency burn" in out
