"""CLI entrypoint tests: flag parsing → Options (reference
pkg/operator/options/options.go:46-60), the serving surface
(/metrics /healthz, reference cmd/controller/main.go:44), the run loop,
and the xprof profiling hook."""

import json
import os
import urllib.request

import pytest

from karpenter_provider_aws_tpu.cli import (
    build_parser, main, options_from_args, start_server,
)
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.operator import Operator, Options
from karpenter_provider_aws_tpu.utils.clock import FakeClock


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog()
                          if s.family in ("m5", "t3")])


class TestFlags:
    def test_flags_override_env(self, monkeypatch):
        monkeypatch.setenv("CLUSTER_NAME", "from-env")
        monkeypatch.setenv("BATCH_IDLE_DURATION", "3.0")
        args = build_parser().parse_args(
            ["--cluster-name", "from-flag", "--reserved-enis", "2"])
        opts = options_from_args(args)
        assert opts.cluster_name == "from-flag"      # flag wins
        assert opts.batch_idle_duration == 3.0       # env fallback
        assert opts.reserved_enis == 2

    def test_feature_gates(self):
        args = build_parser().parse_args(
            ["--feature-gates", "Drift=false,SpotToSpotConsolidation=true"])
        opts = options_from_args(args)
        assert opts.drift_enabled is False
        assert opts.spot_to_spot_consolidation is True

    def test_unknown_gate_rejected(self):
        args = build_parser().parse_args(["--feature-gates", "Bogus=true"])
        with pytest.raises(SystemExit):
            options_from_args(args)

    def test_invalid_options_rejected(self):
        args = build_parser().parse_args(
            ["--batch-idle-duration", "5", "--batch-max-duration", "1"])
        with pytest.raises(ValueError):
            options_from_args(args)


class TestServing:
    def test_metrics_and_health_endpoints(self, lattice):
        clock = FakeClock()
        op = Operator(options=Options(), lattice=lattice,
                      cloud=FakeCloud(clock), clock=clock)
        op.run_once()
        server = start_server(op, 0)
        try:
            port = server.server_address[1]
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
            assert "karpenter_cluster_state_node_count" in body
            assert "karpenter_cloudprovider_instance_type_offering_price_estimate" in body
            ok = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5).read()
            assert ok == b"ok"
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope", timeout=5)
        finally:
            server.shutdown()


class TestMainLoop:
    def test_main_runs_for_duration_and_exits(self):
        rc = main(["--duration", "0.2", "--step", "0.05",
                   "--metrics-port", "0"])
        assert rc == 0


class TestProfilingHook:
    def test_solver_trace_writes_xprof_artifacts(self, lattice, tmp_path):
        """start_profiling wraps device solves in a JAX trace session;
        artifacts land under <dir>/plugins/profile/* (xprof layout)."""
        from karpenter_provider_aws_tpu.apis import NodePool, Pod
        from karpenter_provider_aws_tpu.solver import Solver, build_problem

        solver = Solver(lattice)
        solver.start_profiling(str(tmp_path))
        try:
            pods = [Pod(name=f"p{i}",
                        requests={"cpu": "500m", "memory": "1Gi"})
                    for i in range(4)]
            plan = solver.solve(build_problem(pods, [NodePool(name="d")],
                                              lattice))
            assert not plan.unschedulable
        finally:
            solver.stop_profiling()
        profile_root = tmp_path / "plugins" / "profile"
        assert profile_root.is_dir()
        runs = list(profile_root.iterdir())
        assert runs and any(run.iterdir() for run in runs)


class TestValidateEndpoint:
    def test_http_admission_answers_allowed_and_denied(self, lattice):
        """The HTTP admission endpoint (reference pkg/webhooks serves the
        same contract): POST a review, get allowed/causes."""
        import json
        import urllib.request
        from karpenter_provider_aws_tpu.apis import NodePool, serde
        from karpenter_provider_aws_tpu.cli import start_server
        from karpenter_provider_aws_tpu.cloud import FakeCloud
        from karpenter_provider_aws_tpu.operator import Operator, Options
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        clock = FakeClock()
        op = Operator(options=Options(), lattice=lattice,
                      cloud=FakeCloud(clock), clock=clock)
        server = start_server(op, 0)
        try:
            port = server.server_address[1]

            def post(doc):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/validate",
                    data=json.dumps(doc).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req) as r:
                    return json.loads(r.read())

            ok = post({"kind": "nodepools",
                       "spec": serde.nodepool_to_dict(NodePool(name="p"))})
            assert ok == {"allowed": True, "causes": []}
            bad_spec = serde.nodepool_to_dict(NodePool(name="p"))
            bad_spec["disruption"]["budgets"] = [{"nodes": "150%"}]
            denied = post({"kind": "nodepools", "spec": bad_spec})
            assert denied["allowed"] is False
            assert any("nodes" in c for c in denied["causes"])
        finally:
            server.shutdown()

    def test_admissionreview_v1_dialect(self, lattice):
        """A real kube-apiserver webhook client POSTs AdmissionReview v1
        (deploy/templates/webhooks.yaml registers exactly that); the
        endpoint must answer in the AdmissionReview response envelope."""
        import json
        import urllib.request
        from karpenter_provider_aws_tpu.apis import NodePool, serde
        from karpenter_provider_aws_tpu.cli import start_server
        from karpenter_provider_aws_tpu.cloud import FakeCloud
        from karpenter_provider_aws_tpu.operator import Operator, Options
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        clock = FakeClock()
        op = Operator(options=Options(), lattice=lattice,
                      cloud=FakeCloud(clock), clock=clock)
        server = start_server(op, 0)
        try:
            port = server.server_address[1]

            def post(doc):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/validate",
                    data=json.dumps(doc).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req) as r:
                    return json.loads(r.read())

            # the REAL AdmissionReview shape: name lives under
            # metadata, not in spec
            spec = serde.nodepool_to_dict(NodePool(name="p"))
            del spec["name"]
            review = {"apiVersion": "admission.k8s.io/v1",
                      "kind": "AdmissionReview",
                      "request": {"uid": "u-1",
                                  "resource": {"resource": "nodepools"},
                                  "object": {"metadata": {"name": "p"},
                                             "spec": spec}}}
            ok = post(review)
            assert ok["kind"] == "AdmissionReview"
            assert ok["response"] == {"uid": "u-1", "allowed": True}
            spec["disruption"]["budgets"] = [{"nodes": "150%"}]
            denied = post(review)
            assert denied["response"]["allowed"] is False
            assert "nodes" in denied["response"]["status"]["message"]
            # the registered group plural for NodeClasses resolves too
            nc_review = {"apiVersion": "admission.k8s.io/v1",
                         "kind": "AdmissionReview",
                         "request": {
                             "uid": "u-2",
                             "resource": {"resource": "ec2nodeclasses"},
                             "object": {"metadata": {"name": "default"},
                                        "spec": {"amiFamily": "AL2",
                                                 "role": "KarpenterNode"}}}}
            ok = post(nc_review)
            assert ok["response"]["allowed"] is True, ok
        finally:
            server.shutdown()

    def test_validate_endpoint_rejects_garbage_without_crashing(self, lattice):
        """Malformed reviews answer 400/denied — never a dropped
        connection (review r4 finding)."""
        import json
        import urllib.error
        import urllib.request
        from karpenter_provider_aws_tpu.cli import start_server
        from karpenter_provider_aws_tpu.cloud import FakeCloud
        from karpenter_provider_aws_tpu.operator import Operator, Options
        from karpenter_provider_aws_tpu.utils.clock import FakeClock
        clock = FakeClock()
        op = Operator(options=Options(), lattice=lattice,
                      cloud=FakeCloud(clock), clock=clock)
        server = start_server(op, 0)
        try:
            port = server.server_address[1]

            def post_raw(payload):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/validate", data=payload,
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req) as r:
                        return r.status, json.loads(r.read())
                except urllib.error.HTTPError as e:
                    return e.code, None

            assert post_raw(b"[1, 2]")[0] == 400          # non-dict review
            assert post_raw(json.dumps(
                {"kind": "nodepools", "spec": "hello"}).encode())[0] == 400
            # unknown kind: denied, not allowed
            code, body = post_raw(json.dumps(
                {"kind": "nodepool", "spec": {"name": "x"}}).encode())
            assert code == 200 and body["allowed"] is False
            assert any("unknown kind" in c for c in body["causes"])
        finally:
            server.shutdown()


class TestApiPortFlag:
    def test_cli_serves_apiserver_rest(self):
        """--api-port: the controller hosts the wire-reachable apiserver;
        an external agent creates a pod over REST while main() runs."""
        import json
        import socket
        import threading
        import urllib.request
        from karpenter_provider_aws_tpu.apis import Pod, serde
        from karpenter_provider_aws_tpu.cli import main
        with socket.socket() as sock:
            sock.bind(("127.0.0.1", 0))
            port = sock.getsockname()[1]
        stop = threading.Event()
        t = threading.Thread(
            target=main,
            args=([f"--api-port={port}", "--metrics-port=0",
                   "--duration=25", "--step=0.1"],),
            kwargs={"stop_event": stop},
            daemon=True)
        t.start()
        import time
        base = f"http://127.0.0.1:{port}"
        deadline = time.monotonic() + 5.0
        created = False
        while time.monotonic() < deadline and not created:
            try:
                r = urllib.request.Request(
                    f"{base}/apis/pods",
                    data=json.dumps(serde.pod_to_dict(Pod(
                        name="ext0",
                        requests={"cpu": "1", "memory": "2Gi"}))).encode())
                urllib.request.urlopen(r, timeout=2)
                created = True
            except OSError:
                time.sleep(0.2)
        assert created, "REST surface never came up"
        # the running operator provisions for it
        bound = False
        deadline = time.monotonic() + 22.0
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(f"{base}/apis/pods",
                                            timeout=2) as resp:
                    items = json.loads(resp.read())["items"]
            except OSError:
                time.sleep(0.3)   # server mid-boot/teardown: retry
                continue
            if items and items[0]["spec"].get("nodeName"):
                bound = True
                break
            time.sleep(0.3)
        stop.set()   # programmatic SIGTERM: no need to burn the full 25s
        t.join(10)
        assert bound, "externally-created pod never got capacity"
