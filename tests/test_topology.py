"""Topology spread + pod (anti-)affinity semantics (BASELINE configs 2-3).

Behavioral spec: reference website concepts/scheduling.md:312-446 — zonal /
hostname / capacity-type topologySpreadConstraints, required podAffinity and
podAntiAffinity, both directions of the k8s symmetry check. Each test
validates the decoded NodePlan directly (skew bounds, co-location,
separation) and, where meaningful, parity with the per-pod FFD oracle.
"""

from collections import Counter, defaultdict

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Pod
from karpenter_provider_aws_tpu.apis.resources import R
from karpenter_provider_aws_tpu.apis.objects import PodAffinityTerm, TopologySpreadConstraint
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.solver import ExistingBin, Solver, build_problem, ffd_oracle
from karpenter_provider_aws_tpu.solver.topology import BoundPod, _water_fill

_FAMILIES = ("m5", "c5", "r5", "t3")


@pytest.fixture(scope="module")
def lattice():
    specs = [s for s in build_catalog() if s.family in _FAMILIES]
    return build_lattice(specs)


@pytest.fixture(scope="module")
def solver(lattice):
    return Solver(lattice)


def spread_pods(n, key=wk.LABEL_ZONE, max_skew=1, labels=None, prefix="sp", **kw):
    labels = labels or {"app": "web"}
    return [Pod(name=f"{prefix}-{i}", labels=dict(labels),
                requests={"cpu": "500m", "memory": "1Gi"},
                topology_spread=[TopologySpreadConstraint(
                    max_skew=max_skew, topology_key=key,
                    label_selector=tuple(labels.items()))], **kw)
            for i in range(n)]


def zone_of_pod(plan):
    """pod name -> zone from the decoded plan (new nodes only)."""
    out = {}
    for node in plan.new_nodes:
        for p in node.pods:
            out[p] = node.zone
    return out


def node_of_pod(plan):
    out = {}
    for i, node in enumerate(plan.new_nodes):
        for p in node.pods:
            out[p] = i
    for name, pods in plan.existing_assignments.items():
        for p in pods:
            out[p] = name
    return out


class TestWaterFill:
    def test_even_split(self):
        assert _water_fill(np.zeros(3, np.int64), 9).tolist() == [3, 3, 3]

    def test_tops_up_lowest_first(self):
        # zones at 5,1,0 + 7 new pods -> levels equalize toward (5,4,4)
        add = _water_fill(np.array([5, 1, 0]), 7)
        final = np.array([5, 1, 0]) + add
        assert add.sum() == 7
        assert final.max() - final.min() <= 1

    def test_tail_round_robin(self):
        add = _water_fill(np.array([2, 2]), 5)
        assert add.sum() == 5
        assert abs(add[0] - add[1]) <= 1

    def test_zero_pods(self):
        assert _water_fill(np.array([3, 1]), 0).tolist() == [0, 0]


class TestZoneSpread:
    def test_even_spread_across_zones(self, solver, lattice):
        pods = spread_pods(12)
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        zones = Counter(zone_of_pod(plan).values())
        assert sum(zones.values()) == 12
        assert max(zones.values()) - min(zones.values()) <= 1
        assert len(zones) == lattice.Z

    def test_spread_counts_bound_pods(self, solver, lattice):
        """Existing replicas skew the domain counts; new pods top up the rest."""
        labels = {"app": "web"}
        bound = [BoundPod(pod=Pod(name=f"b{i}", labels=dict(labels)),
                          node_name=f"n{i}", zone=lattice.zones[0])
                 for i in range(4)]
        pods = spread_pods(4, labels=labels)
        problem = build_problem(pods, [NodePool(name="default")], lattice,
                                bound_pods=bound)
        plan = solver.solve(problem)
        zones = Counter(zone_of_pod(plan).values())
        # all 4 new pods avoid the already-loaded zone 0
        assert zones.get(lattice.zones[0], 0) == 0
        assert sum(zones.values()) == 4

    def test_selector_scopes_the_spread(self, solver, lattice):
        """Pods outside the label selector don't participate in the spread."""
        pods = spread_pods(6, labels={"app": "a"})
        other = [Pod(name=f"o-{i}", labels={"app": "b"},
                     requests={"cpu": "500m", "memory": "1Gi"}) for i in range(5)]
        problem = build_problem(pods + other, [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        zones = Counter(z for p, z in zone_of_pod(plan).items() if p.startswith("sp-"))
        assert max(zones.values()) - min(zones.values()) <= 1


class TestHostnameSpread:
    def test_max_skew_caps_pods_per_node(self, solver, lattice):
        pods = spread_pods(9, key=wk.LABEL_HOSTNAME, max_skew=2)
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        per_node = Counter(node_of_pod(plan).values())
        assert max(per_node.values()) <= 2
        assert sum(per_node.values()) == 9

    def test_hostname_spread_parity_with_oracle(self, solver, lattice):
        pods = spread_pods(10, key=wk.LABEL_HOSTNAME, max_skew=1)
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        oracle = ffd_oracle(problem)
        assert len(plan.new_nodes) == oracle.num_new_nodes == 10
        assert plan.new_node_cost <= oracle.new_node_cost * 1.02 + 1e-6


class TestCapacityTypeSpread:
    def test_spread_across_capacity_types(self, solver, lattice):
        pods = spread_pods(8, key=wk.LABEL_CAPACITY_TYPE)
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        caps = Counter(n.capacity_type for n in plan.new_nodes for _ in n.pods)
        assert sum(caps.values()) == 8
        assert max(caps.values()) - min(caps.values()) <= 1 or len(caps) == lattice.C


class TestPodAntiAffinity:
    def test_cross_class_never_share_node(self, solver, lattice):
        """web anti-affines redis on hostname: no node may hold both."""
        web = [Pod(name=f"w{i}", labels={"app": "web"},
                   requests={"cpu": "250m", "memory": "256Mi"},
                   pod_affinity=[PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                                                 label_selector=(("app", "redis"),),
                                                 anti=True)])
               for i in range(6)]
        redis = [Pod(name=f"r{i}", labels={"app": "redis"},
                     requests={"cpu": "250m", "memory": "256Mi"}) for i in range(6)]
        problem = build_problem(web + redis, [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        by_node = defaultdict(set)
        for p, n in node_of_pod(plan).items():
            by_node[n].add(p[0])  # 'w' or 'r'
        for kinds in by_node.values():
            assert kinds != {"w", "r"}, "anti-affine classes co-located"

    def test_symmetry_blocks_reverse_direction(self, solver, lattice):
        """redis owns no term, but web's anti-term must still keep redis out
        of web's nodes when redis packs later (k8s symmetry)."""
        web = [Pod(name=f"w{i}", labels={"app": "web"},
                   requests={"cpu": "4", "memory": "8Gi"},
                   pod_affinity=[PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                                                 label_selector=(("app", "redis"),),
                                                 anti=True)])
               for i in range(2)]
        redis = [Pod(name=f"r{i}", labels={"app": "redis"},
                     requests={"cpu": "100m", "memory": "128Mi"}) for i in range(4)]
        problem = build_problem(web + redis, [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        by_node = defaultdict(set)
        for p, n in node_of_pod(plan).items():
            by_node[n].add(p[0])
        for kinds in by_node.values():
            assert kinds != {"w", "r"}

    def test_self_anti_zone_limited_by_domains(self, solver, lattice):
        """Zone self-anti-affinity: one replica per zone; surplus unschedulable."""
        labels = {"app": "quorum"}
        pods = [Pod(name=f"q{i}", labels=dict(labels),
                    requests={"cpu": "500m", "memory": "1Gi"},
                    pod_affinity=[PodAffinityTerm(topology_key=wk.LABEL_ZONE,
                                                  label_selector=tuple(labels.items()),
                                                  anti=True)])
                for i in range(lattice.Z + 2)]
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        zones = zone_of_pod(plan)
        assert len(set(zones.values())) == len(zones) == lattice.Z
        assert len(plan.unschedulable) == 2


class TestPodAffinity:
    def test_hostname_self_affinity_colocates(self, solver, lattice):
        labels = {"app": "pair"}
        pods = [Pod(name=f"p{i}", labels=dict(labels),
                    requests={"cpu": "500m", "memory": "512Mi"},
                    pod_affinity=[PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                                                  label_selector=tuple(labels.items()))])
                for i in range(4)]
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        nodes = set(node_of_pod(plan).values())
        assert len(nodes) == 1, "self-affine replicas must share one node"

    def test_zone_self_affinity_pins_one_zone(self, solver, lattice):
        labels = {"app": "zonal"}
        pods = [Pod(name=f"p{i}", labels=dict(labels),
                    requests={"cpu": "2", "memory": "4Gi"},
                    pod_affinity=[PodAffinityTerm(topology_key=wk.LABEL_ZONE,
                                                  label_selector=tuple(labels.items()))])
                for i in range(10)]
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        assert len(set(zone_of_pod(plan).values())) == 1

    def test_cross_class_joins_bound_node(self, solver, lattice):
        """A pod requiring presence of 'cache' joins the existing node that
        already runs a cache pod."""
        cache_pod = Pod(name="cache-0", labels={"app": "cache"})
        existing = [ExistingBin(
            name="node-a", node_pool="default", instance_type="m5.2xlarge",
            zone=lattice.zones[0], capacity_type="on-demand",
            used=np.zeros(R, np.float32))]
        bound = [BoundPod(pod=cache_pod, node_name="node-a", zone=lattice.zones[0])]
        follower = [Pod(name="f0", labels={"app": "follower"},
                        requests={"cpu": "500m", "memory": "1Gi"},
                        pod_affinity=[PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                                                      label_selector=(("app", "cache"),))])]
        problem = build_problem(follower, [NodePool(name="default")], lattice,
                                existing=existing, bound_pods=bound)
        plan = solver.solve(problem)
        assert plan.existing_assignments.get("node-a") == ["f0"]
        assert not plan.new_nodes
        assert not plan.unschedulable

    def test_cross_class_unseedable_is_unschedulable(self, solver, lattice):
        """Presence requirement with no seeded bin and no self-match cannot
        open a fresh node."""
        follower = [Pod(name="f0", labels={"app": "follower"},
                        requests={"cpu": "500m", "memory": "1Gi"},
                        pod_affinity=[PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                                                      label_selector=(("app", "cache"),))])]
        problem = build_problem(follower, [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        assert "f0" in plan.unschedulable


class TestConfig3Composite:
    def test_anti_affinity_plus_spread_mix(self, solver, lattice):
        """BASELINE config-3 shape (scaled down): anti-affinity + zonal and
        hostname topology spread together."""
        web = spread_pods(30, key=wk.LABEL_ZONE, labels={"app": "web"}, prefix="web")
        api = spread_pods(20, key=wk.LABEL_HOSTNAME, max_skew=2,
                          labels={"app": "api"}, prefix="api")
        singleton = [Pod(name=f"s{i}", labels={"app": "s"},
                         requests={"cpu": "1", "memory": "2Gi"},
                         pod_affinity=[PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                                                       label_selector=(("app", "s"),),
                                                       anti=True)])
                     for i in range(5)]
        problem = build_problem(web + api + singleton, [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        zones = Counter(z for p, z in zone_of_pod(plan).items() if p.startswith("web"))
        assert max(zones.values()) - min(zones.values()) <= 1
        per_node_api = Counter(n for p, n in node_of_pod(plan).items() if p.startswith("api"))
        assert max(per_node_api.values()) <= 2
        nodes_s = [n for p, n in node_of_pod(plan).items() if p.startswith("s")]
        assert len(set(nodes_s)) == 5
        # pack quality: within the 2% envelope of the per-pod oracle
        oracle = ffd_oracle(problem)
        assert plan.new_node_cost <= oracle.new_node_cost * 1.02 + 1e-6


class TestReviewRegressions:
    def test_bound_pod_anti_term_blocks_pending_match(self, solver, lattice):
        """A resident pod owning a hostname anti-term keeps pending matches
        off its node even when no pending pod references that selector."""
        guard = Pod(name="guard", labels={"app": "guard"},
                    pod_affinity=[PodAffinityTerm(topology_key=wk.LABEL_HOSTNAME,
                                                  label_selector=(("app", "web"),),
                                                  anti=True)])
        existing = [ExistingBin(
            name="node-a", node_pool="default", instance_type="m5.4xlarge",
            zone=lattice.zones[0], capacity_type="on-demand",
            used=np.zeros(R, np.float32))]
        bound = [BoundPod(pod=guard, node_name="node-a", zone=lattice.zones[0])]
        web = [Pod(name=f"w{i}", labels={"app": "web"},
                   requests={"cpu": "500m", "memory": "1Gi"}) for i in range(3)]
        problem = build_problem(web, [NodePool(name="default")], lattice,
                                existing=existing, bound_pods=bound)
        plan = solver.solve(problem)
        assert "node-a" not in plan.existing_assignments
        assert sum(len(n.pods) for n in plan.new_nodes) == 3

    def test_hostname_spread_counts_bound_pods(self, solver, lattice):
        """maxSkew cap accounts for matching pods already on an existing node."""
        labels = {"app": "web"}
        existing = [ExistingBin(
            name="node-a", node_pool="default", instance_type="m5.4xlarge",
            zone=lattice.zones[0], capacity_type="on-demand",
            used=np.zeros(R, np.float32))]
        bound = [BoundPod(pod=Pod(name=f"b{i}", labels=dict(labels)),
                          node_name="node-a", zone=lattice.zones[0]) for i in range(2)]
        pods = spread_pods(4, key=wk.LABEL_HOSTNAME, max_skew=2, labels=labels)
        problem = build_problem(pods, [NodePool(name="default")], lattice,
                                existing=existing, bound_pods=bound)
        plan = solver.solve(problem)
        # node-a is already at the cap (2 bound matches): nothing new lands there
        assert "node-a" not in plan.existing_assignments
        per_node = Counter(node_of_pod(plan).values())
        assert max(per_node.values()) <= 2

    def test_hostname_spread_counts_sibling_groups(self, solver, lattice):
        """Two deployments sharing labels (distinct requests) share the
        per-node skew budget."""
        labels = {"app": "web"}
        a = spread_pods(4, key=wk.LABEL_HOSTNAME, max_skew=2, labels=labels, prefix="a")
        b = [Pod(name=f"b-{i}", labels=dict(labels),
                 requests={"cpu": "250m", "memory": "512Mi"},
                 topology_spread=[TopologySpreadConstraint(
                     max_skew=2, topology_key=wk.LABEL_HOSTNAME,
                     label_selector=tuple(labels.items()))]) for i in range(4)]
        problem = build_problem(a + b, [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        assert not plan.unschedulable
        per_node = Counter(node_of_pod(plan).values())
        assert max(per_node.values()) <= 2

    def test_capacity_spread_global_across_zone_splits(self, solver, lattice):
        """Zone spread x capacity-type spread: the captype skew bound is
        global, not per zone split."""
        labels = {"app": "web"}
        pods = [Pod(name=f"p{i}", labels=dict(labels),
                    requests={"cpu": "500m", "memory": "1Gi"},
                    topology_spread=[
                        TopologySpreadConstraint(max_skew=1, topology_key=wk.LABEL_ZONE,
                                                 label_selector=tuple(labels.items())),
                        TopologySpreadConstraint(max_skew=1,
                                                 topology_key=wk.LABEL_CAPACITY_TYPE,
                                                 label_selector=tuple(labels.items()))])
                for i in range(9)]
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        caps = Counter(n.capacity_type for n in plan.new_nodes for _ in n.pods)
        assert sum(caps.values()) == 9
        assert max(caps.values()) - min(caps.values()) <= 1

    def test_zone_spread_shared_selector_across_sibling_groups(self, solver, lattice):
        """Two deployments sharing labels/selector but different requests
        must satisfy the skew bound COMBINED, not per group."""
        labels = {"app": "web"}
        a = spread_pods(4, labels=labels, prefix="za")
        b = [Pod(name=f"zb-{i}", labels=dict(labels),
                 requests={"cpu": "250m", "memory": "512Mi"},
                 topology_spread=[TopologySpreadConstraint(
                     max_skew=1, topology_key=wk.LABEL_ZONE,
                     label_selector=tuple(labels.items()))]) for i in range(4)]
        problem = build_problem(a + b, [NodePool(name="default")], lattice)
        plan = solver.solve(problem)
        zones = Counter(zone_of_pod(plan).values())
        assert sum(zones.values()) == 8
        assert max(zones.values()) - min(zones.values()) <= 1

    def test_irrelevant_labels_do_not_break_dedup(self, lattice):
        """StatefulSet-style per-pod-unique labels must not explode the
        group count (they appear in no selector)."""
        from karpenter_provider_aws_tpu.solver import build_problem as bp
        pods = [Pod(name=f"ss-{i}", labels={"app": "db", "pod-name": f"ss-{i}"},
                    requests={"cpu": "500m", "memory": "1Gi"}) for i in range(100)]
        problem = bp(pods, [NodePool(name="default")], lattice)
        assert problem.G == 1

    def test_warnings_deduplicated(self, solver, lattice):
        pods = [Pod(name=f"p{i}", requests={"cpu": "1"}, topology_spread=[
            TopologySpreadConstraint(max_skew=1, topology_key="example.com/rack")])
            for i in range(10)]
        problem = build_problem(pods, [NodePool(name="default")], lattice)
        assert len(problem.warnings) == 1

    def test_split_counts_pins_need_groups_to_shard0(self):
        from karpenter_provider_aws_tpu.parallel import split_counts
        count = np.array([8, 8, 8], dtype=np.int32)
        keep = np.array([False, True, True])
        pin = np.array([False, False, True])
        out = split_counts(count, 4, keep_whole=keep, pin_shard0=pin)
        assert out.sum(axis=0).tolist() == [8, 8, 8]
        assert (out[:, 1] > 0).sum() == 1          # whole on one shard
        assert out[0, 2] == 8 and out[1:, 2].sum() == 0  # pinned to shard 0


class TestSelectorKeyCache:
    def test_per_pod_cache_invalidates_on_reassignment(self):
        """_selector_keys caches each pod's contributed label keys on the
        pod; reassigning a selector field must drop the cache (the same
        __setattr__ contract as the scheduling-signature cache)."""
        from karpenter_provider_aws_tpu.solver.problem import _selector_keys
        p = Pod(name="x", requests={"cpu": "1"},
                topology_spread=[TopologySpreadConstraint(
                    max_skew=1, topology_key=wk.LABEL_ZONE,
                    label_selector=(("app", "a"),))])
        assert _selector_keys([p], []) == frozenset({"app"})
        # steady-state: second pass hits the cache, same answer
        assert _selector_keys([p], []) == frozenset({"app"})
        p.topology_spread = [TopologySpreadConstraint(
            max_skew=1, topology_key=wk.LABEL_ZONE,
            label_selector=(("tier", "web"),))]
        assert _selector_keys([p], []) == frozenset({"tier"})
        p.topology_spread = []
        assert _selector_keys([p], []) == frozenset()
