"""Machine-readable schema contract (apis/schema.py → deploy/crds/).

Mirrors the reference's CRD validation surface: per-requirement minValues
(karpenter.sh_nodepools.yaml:338-401), disruption-budget patterns
(:55-100), operator enums, label patterns, and the EC2NodeClass inline
CEL (ec2nodeclass.go:321-330 role XOR instanceProfile) — all enforced at
the apiserver admission boundary.
"""

import pathlib
import subprocess
import sys

import pytest

from karpenter_provider_aws_tpu.apis import (
    NodeClass, NodePool, Requirement, serde,
)
from karpenter_provider_aws_tpu.apis import Operator as ReqOp
from karpenter_provider_aws_tpu.apis import schema
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.apis.objects import (
    DisruptionBudget, KubeletSpec, NodeClaim, NodePoolDisruption, Taint,
    TaintEffect,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


def pool_spec(**kw) -> dict:
    return serde.nodepool_to_dict(NodePool(name="p", **kw))


class TestRoundTrips:
    def test_default_objects_validate(self):
        assert schema.validate("nodepools", pool_spec()) == []
        assert schema.validate("nodeclasses", serde.nodeclass_to_dict(
            NodeClass(name="d", role="r"))) == []
        assert schema.validate("nodeclaims", serde.nodeclaim_to_dict(
            NodeClaim(name="c", node_pool="p"))) == []

    def test_rich_pool_validates(self):
        spec = pool_spec(
            weight=50,
            labels={"team": "a"},
            requirements=[
                Requirement(wk.LABEL_CAPACITY_TYPE, ReqOp.IN,
                            ("spot", "on-demand")),
                Requirement("karpenter.tpu/instance-cpu", ReqOp.GT, ("4",)),
                Requirement(wk.LABEL_INSTANCE_TYPE, ReqOp.IN,
                            ("m5.large", "m5.xlarge", "c5.large"),
                            min_values=2),
            ],
            taints=[Taint(key="dedicated", value="gpu",
                          effect=TaintEffect.NO_SCHEDULE)],
            limits={"cpu": "1000", "memory": "1000Gi"},
            disruption=NodePoolDisruption(budgets=[
                DisruptionBudget(nodes="10%"),
                DisruptionBudget(nodes="5", schedule="0 9 * * 1-5",
                                 duration=8 * 3600.0),
            ]),
            kubelet=KubeletSpec(max_pods=58))
        assert schema.validate("nodepools", spec) == []

    def test_launched_claim_validates(self):
        claim = NodeClaim(
            name="c1", node_pool="default", provider_id="aws:///z/i-1",
            instance_type="m5.large", zone="us-west-2a",
            capacity_type="spot", phase=__import__(
                "karpenter_provider_aws_tpu.apis.objects",
                fromlist=["NodeClaimPhase"]).NodeClaimPhase.LAUNCHED,
            capacity={"cpu": 2000.0}, allocatable={"cpu": 1930.0})
        assert schema.validate("nodeclaims",
                               serde.nodeclaim_to_dict(claim)) == []


class TestStructuralRejection:
    def test_unknown_field_rejected(self):
        spec = pool_spec()
        spec["unknownKnob"] = True
        assert any("unknownKnob" in e
                   for e in schema.validate("nodepools", spec))

    def test_bad_budget_nodes_pattern(self):
        spec = pool_spec()
        spec["disruption"]["budgets"] = [{"nodes": "200%"}]
        errs = schema.validate("nodepools", spec)
        assert errs and any("nodes" in e for e in errs)

    def test_bad_budget_duration_rejected(self):
        """Wire durations are canonical SECONDS (numeric) — a Go-style
        string or a non-positive number is structurally invalid."""
        spec = pool_spec()
        spec["disruption"]["budgets"] = [
            {"nodes": "10%", "schedule": "* * * * *", "duration": "30s"}]
        assert schema.validate("nodepools", spec)
        spec["disruption"]["budgets"] = [
            {"nodes": "10%", "schedule": "* * * * *", "duration": 0}]
        assert schema.validate("nodepools", spec)

    def test_bad_limit_quantity_rejected(self):
        spec = pool_spec()
        spec["limits"] = {"cpu": "banana"}
        assert schema.validate("nodepools", spec)
        spec["limits"] = {"cpu": "1000", "memory": "512Gi", "pods": 100}
        assert schema.validate("nodepools", spec) == []

    def test_bad_operator_enum(self):
        spec = pool_spec()
        spec["requirements"] = [
            {"key": "team", "operator": "Matches", "values": ["a"]}]
        assert schema.validate("nodepools", spec)

    def test_min_values_bounds(self):
        spec = pool_spec()
        spec["requirements"] = [{"key": "t", "operator": "In",
                                 "values": ["a"], "minValues": 0}]
        assert schema.validate("nodepools", spec)
        spec["requirements"][0]["minValues"] = 51
        assert schema.validate("nodepools", spec)

    def test_wrong_type_rejected(self):
        spec = pool_spec()
        spec["weight"] = "heavy"
        assert schema.validate("nodepools", spec)

    def test_bad_label_value_rejected(self):
        spec = pool_spec()
        spec["labels"] = {"team": "-leading-dash"}
        assert schema.validate("nodepools", spec)


class TestCrossFieldRules:
    def test_in_requires_values(self):
        spec = pool_spec()
        spec["requirements"] = [{"key": "t", "operator": "In", "values": []}]
        errs = schema.validate("nodepools", spec)
        assert any("'In' must have a value" in e for e in errs)

    def test_gt_requires_single_int(self):
        spec = pool_spec()
        spec["requirements"] = [
            {"key": "karpenter.tpu/instance-cpu", "operator": "Gt",
             "values": ["4", "8"]}]
        assert any("'Gt' or 'Lt'" in e
                   for e in schema.validate("nodepools", spec))
        # "-4" is rejected too (structurally, by the value pattern —
        # label values never start with '-')
        spec["requirements"][0]["values"] = ["-4"]
        assert schema.validate("nodepools", spec)

    def test_min_values_coverage(self):
        spec = pool_spec()
        spec["requirements"] = [
            {"key": "node.kubernetes.io/instance-type", "operator": "In",
             "values": ["m5.large"], "minValues": 3}]
        assert any("minValues" in e
                   for e in schema.validate("nodepools", spec))

    def test_exists_must_not_have_values(self):
        spec = pool_spec()
        spec["requirements"] = [
            {"key": "team", "operator": "Exists", "values": ["a"]}]
        assert any("Exists" in e for e in schema.validate("nodepools", spec))

    def test_schedule_requires_duration(self):
        spec = pool_spec()
        spec["disruption"]["budgets"] = [
            {"nodes": "10%", "schedule": "0 9 * * *"}]
        assert any("duration" in e
                   for e in schema.validate("nodepools", spec))

    def test_role_xor_instance_profile(self):
        both = serde.nodeclass_to_dict(
            NodeClass(name="d", role="r", instance_profile="p"))
        assert any("role or instanceProfile" in e
                   for e in schema.validate("nodeclasses", both))
        neither = serde.nodeclass_to_dict(NodeClass(name="d"))
        assert any("role or instanceProfile" in e
                   for e in schema.validate("nodeclasses", neither))


class TestAdmissionIntegration:
    def test_malformed_spec_rejected_not_crashed(self):
        """A defaulter typed-parsing garbage must surface as an admission
        rejection (InvalidObjectError), never a raw exception."""
        from karpenter_provider_aws_tpu.kube import (
            FakeAPIServer, InvalidObjectError, install_admission,
        )
        s = FakeAPIServer()
        install_admission(s)
        spec = pool_spec()
        spec["requirements"] = [{"key": "t", "operator": "Bogus"}]
        with pytest.raises(InvalidObjectError):
            s.create("nodepools", spec)


    def test_schema_errors_surface_through_apiserver(self):
        from karpenter_provider_aws_tpu.kube import (
            FakeAPIServer, InvalidObjectError, install_admission,
        )
        s = FakeAPIServer()
        install_admission(s)
        spec = pool_spec()
        spec["disruption"]["budgets"] = [{"nodes": "999%"}]
        with pytest.raises(InvalidObjectError, match="nodes"):
            s.create("nodepools", spec)

    def test_invalid_claim_rejected_at_boundary(self):
        from karpenter_provider_aws_tpu.kube import (
            FakeAPIServer, InvalidObjectError, install_admission,
        )
        s = FakeAPIServer()
        install_admission(s)
        spec = serde.nodeclaim_to_dict(NodeClaim(name="c", node_pool="p"))
        spec["phase"] = "Exploded"
        with pytest.raises(InvalidObjectError, match="phase"):
            s.create("nodeclaims", spec)


class TestArtifacts:
    def test_checked_in_crds_are_current(self):
        """deploy/crds/ must match the generator byte-for-byte (the
        reference's make-codegen freshness contract)."""
        r = subprocess.run(
            [sys.executable, str(REPO / "tools" / "gen_crds.py"), "--check"],
            capture_output=True, text=True)
        assert r.returncode == 0, r.stdout + r.stderr

    def test_crd_documents_are_structural(self):
        """apiextensions v1 structural-schema legality: no type arrays,
        no prefixItems/propertyNames/anyOf, no null enum members —
        nullable: true instead (kubectl apply must not choke)."""
        def walk(node):
            if isinstance(node, dict):
                assert not isinstance(node.get("type"), list), node
                for bad in ("prefixItems", "propertyNames", "anyOf"):
                    assert bad not in node, bad
                if isinstance(node.get("enum"), list):
                    assert None not in node["enum"], node
                if "exclusiveMinimum" in node:
                    assert isinstance(node["exclusiveMinimum"], bool), node
                if node.get("type") == "array":
                    assert "items" in node, node
                for v in node.values():
                    walk(v)
            elif isinstance(node, list):
                for v in node:
                    walk(v)
        for kind in ("nodepools", "nodeclasses", "nodeclaims"):
            walk(schema.crd_document(kind))

    def test_crd_documents_carry_cel_rules(self):
        doc = schema.crd_document("nodepools")
        spec_schema = (doc["spec"]["versions"][0]["schema"]
                       ["openAPIV3Schema"]["properties"]["spec"])
        rules = spec_schema["x-kubernetes-validations"]
        assert any("minValues" in r["message"] for r in rules)
        doc = schema.crd_document("nodeclasses")
        spec_schema = (doc["spec"]["versions"][0]["schema"]
                       ["openAPIV3Schema"]["properties"]["spec"])
        assert any("role" in r["message"]
                   for r in spec_schema["x-kubernetes-validations"])
