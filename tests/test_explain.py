"""Decision explainability: the reason taxonomy, constraint-elimination
ledgers, the decision-audit ring, FailedScheduling dedup, and the
delta-vs-full explanation parity contract (docs/reference/explain.md).
"""

from __future__ import annotations

import json
import pathlib

import numpy as np
import pytest

from karpenter_provider_aws_tpu.apis import NodePool, Pod
from karpenter_provider_aws_tpu.apis import wellknown as wk
from karpenter_provider_aws_tpu.apis.objects import (NodeClass,
                                                     PodAffinityTerm, Taint)
from karpenter_provider_aws_tpu.cache.unavailable import UnavailableOfferings
from karpenter_provider_aws_tpu.cloud import FakeCloud
from karpenter_provider_aws_tpu.lattice import build_catalog, build_lattice
from karpenter_provider_aws_tpu.lattice.tensors import (masked_view,
                                                        masked_view_versioned)
from karpenter_provider_aws_tpu.solver import Solver, build_problem
from karpenter_provider_aws_tpu.solver import explain as ex
from karpenter_provider_aws_tpu.solver import taxonomy as tx
from karpenter_provider_aws_tpu.solver.incremental import (
    IncrementalProblemBuilder)
from karpenter_provider_aws_tpu.solver.oracle import ffd_oracle
from karpenter_provider_aws_tpu.solver.problem import ExistingBin
from karpenter_provider_aws_tpu.state.cluster import ClusterState
from karpenter_provider_aws_tpu.utils.clock import FakeClock


@pytest.fixture(scope="module")
def lattice():
    return build_lattice([s for s in build_catalog()
                          if s.family in ("m5", "c5")])


@pytest.fixture(scope="module")
def solver(lattice):
    return Solver(lattice)


def _pod(i, shape=None, **kw):
    return Pod(name=f"p{i}",
               requests=shape or {"cpu": "500m", "memory": "1Gi"}, **kw)


# ---------------------------------------------------------------------------
# taxonomy


class TestTaxonomy:
    def test_round_trip_every_code(self):
        for code in tx.CODES:
            assert tx.code_of(tx.reason(code, "some detail")) == code
            assert tx.code_of(tx.reason(code)) == code
            assert tx.detail_of(tx.reason(code, "some detail")) == \
                "some detail"

    def test_legacy_free_text_parses_uncoded(self):
        legacy = "does not fit any existing node or new-node shape"
        assert tx.code_of(legacy) == tx.UNCODED
        assert tx.detail_of(legacy) == legacy

    def test_undeclared_code_asserts(self):
        with pytest.raises(AssertionError):
            tx.reason("not-a-code", "x")

    def test_uncoded_is_not_a_member(self):
        assert tx.UNCODED not in tx.CODES


# ---------------------------------------------------------------------------
# ledger capture


class TestLedgerCapture:
    def test_explain_off_attaches_no_ledger(self, lattice):
        p = build_problem([_pod(1)], [NodePool(name="default")], lattice)
        assert p.groups[0].ledger is None

    def test_waterfall_monotone_and_consistent(self, lattice):
        p = build_problem(
            [_pod(1, node_selector={wk.LABEL_INSTANCE_TYPE: "m5.large"})],
            [NodePool(name="default")], lattice, explain=True)
        led = p.groups[0].ledger
        rows = led.stages
        assert [r.stage for r in rows[:1]] == ["offered"]
        for prev, cur in zip(rows, rows[1:]):
            assert cur.remaining <= prev.remaining
            assert cur.eliminated == prev.remaining - cur.remaining
        # the selector eliminated every non-m5.large offering
        req = next(r for r in rows if r.stage == "requirements")
        assert req.eliminated > 0 and req.remaining > 0
        assert led.blame() == "" and led.blame_code() == ""
        assert "m5.large" in led.label or "cpu=" in led.label

    def test_ice_attribution_with_examples(self, lattice):
        view = masked_view(lattice, np.zeros_like(lattice.available))
        p = build_problem(
            [_pod(1, node_selector={wk.LABEL_INSTANCE_TYPE: "m5.large"})],
            [NodePool(name="default")], view, explain=True)
        assert not p.groups and p.dropped_groups
        led = p.dropped_groups[0].ledger
        assert led.blame() == "ice"
        assert led.blame_code() == tx.ICE_HOLD
        ice = next(r for r in led.stages if r.stage == "ice")
        assert ice.eliminated > 0 and ice.remaining == 0
        assert ice.examples and "m5.large/" in ice.examples[0]
        assert tx.code_of(p.unschedulable["p1"]) == tx.ICE_HOLD

    def test_impossible_selector_blames_requirements(self, lattice):
        p = build_problem(
            [_pod(1, node_selector={wk.LABEL_INSTANCE_TYPE: "nope.xl"})],
            [NodePool(name="default")], lattice, explain=True)
        assert p.dropped_groups
        led = p.dropped_groups[0].ledger
        assert led.blame() == "requirements"
        assert led.blame_code() == tx.NO_OFFERING
        assert tx.code_of(p.unschedulable["p1"]) == tx.NO_OFFERING

    def test_resource_fit_stage_zeroes_impossible_request(self, lattice):
        # no m5/c5 type carries a GPU: resource-fit eliminates everything
        p = build_problem(
            [_pod(1, shape={"cpu": "500m", "nvidia.com/gpu": "1"})],
            [NodePool(name="default")], lattice, explain=True)
        group = (p.groups + p.dropped_groups)[0]
        fit = next(r for r in group.ledger.stages
                   if r.stage == "resource-fit")
        assert fit.remaining == 0

    def test_accel_narrowing_records_a_recoverable_row(self):
        lat = build_lattice([s for s in build_catalog()
                             if s.family in ("g5", "m5")])
        pods = [Pod(name=f"g{i}", requests={"cpu": "500m",
                                            "nvidia.com/gpu": "1"})
                for i in range(4)]
        p = build_problem(pods, [NodePool(name="default")], lat,
                          explain=True)
        led = p.groups[0].ledger
        nar = [r for r in led.stages if r.stage == "narrowing"]
        assert nar and nar[0].examples  # eliminated type names
        assert led.remaining > 0       # narrowing never zeroes (fallback)

    def test_with_count_copy_on_write(self, lattice):
        p = build_problem([_pod(1), _pod(2)], [NodePool(name="default")],
                          lattice, explain=True)
        led = p.groups[0].ledger
        assert led.with_count(led.pods) is led
        led2 = led.with_count(7)
        assert led2.pods == 7 and led2.stages == led.stages
        assert led.pods == 2   # original untouched

    def test_pool_stage_counts_pools(self, lattice):
        tainted = NodePool(name="t", taints=[
            Taint(key="team", value="a", effect="NoSchedule")])
        p = build_problem([_pod(1)], [NodePool(name="default"), tainted],
                          lattice, explain=True)
        led = p.groups[0].ledger
        assert led.pools_total == 2 and led.pools_ok == 1


# ---------------------------------------------------------------------------
# taxonomy codes out of the solve paths


class TestSolveCodes:
    def test_oracle_no_new_node_shape(self, lattice):
        # fits no type at all; without ledgers the FFD rung's own
        # distinction applies (compatible pools exist, no shape fits)
        p = build_problem([_pod(1, shape={"cpu": "10000"})],
                          [NodePool(name="default")], lattice)
        plan = ffd_oracle(p)
        assert tx.code_of(plan.unschedulable["p1"]) == tx.NO_NEW_NODE_SHAPE

    def test_oracle_ledger_refines_to_no_offering(self, lattice):
        # WITH ledgers the same pod reads no-offering: the resource-fit
        # stage already proved no offering can ever hold it
        p = build_problem([_pod(1, shape={"cpu": "10000"})],
                          [NodePool(name="default")], lattice,
                          explain=True)
        plan = ffd_oracle(p)
        assert tx.code_of(plan.unschedulable["p1"]) == tx.NO_OFFERING

    def test_oracle_no_existing_fit(self, lattice):
        # no compatible pool (untolerated taint) + an existing bin with
        # no room: only existing capacity could host, none fits
        pool = NodePool(name="t", taints=[
            Taint(key="team", value="a", effect="NoSchedule")])
        ti = lattice.name_to_idx["m5.large"]
        full = lattice.alloc[ti].copy()
        p = build_problem(
            [_pod(1)], [pool], lattice,
            existing=[ExistingBin(
                name="n1", node_pool="t", instance_type="m5.large",
                zone=lattice.zones[0], capacity_type="on-demand",
                used=full)])
        plan = ffd_oracle(p)
        assert tx.code_of(plan.unschedulable["p1"]) == tx.NO_EXISTING_FIT

    def test_oracle_single_bin_full(self, lattice):
        # hostname self-affinity co-locates every replica; more replicas
        # than the biggest node holds ⇒ overflow is single-bin-full
        pods = [Pod(name=f"s{i}", labels={"app": "a"},
                    requests={"cpu": "16", "memory": "4Gi"},
                    pod_affinity=[PodAffinityTerm(
                        topology_key=wk.LABEL_HOSTNAME,
                        label_selector=(("app", "a"),))])
                for i in range(12)]
        p = build_problem(pods, [NodePool(name="default")], lattice)
        plan = ffd_oracle(p)
        assert plan.unschedulable
        assert {tx.code_of(r) for r in plan.unschedulable.values()} == \
            {tx.SINGLE_BIN_FULL}

    def test_device_decode_leftover_is_coded(self, solver, lattice):
        p = build_problem([_pod(1, shape={"cpu": "10000"})],
                          [NodePool(name="default")], lattice)
        plan = solver.solve(p)
        code = tx.code_of(plan.unschedulable["p1"])
        assert code in (tx.NO_FIT, tx.NO_NEW_NODE_SHAPE)

    def test_relaxation_skips_unknown_resource_rounds(self, solver,
                                                      lattice):
        plan = solver.solve_relaxed(
            [_pod(1, shape={"cpu": "1", "bogus.io/widget": "1"})],
            [NodePool(name="default")], lattice)
        assert tx.code_of(plan.unschedulable["p1"]) == tx.UNKNOWN_RESOURCE


# ---------------------------------------------------------------------------
# pass explanation + audit ring


class TestAuditRing:
    def _pass(self, solver, lattice, pods, pass_id=1):
        p = build_problem(pods, [NodePool(name="default")], lattice,
                          explain=True)
        plan = solver.solve(p)
        return ex.explain_pass(p, plan, pass_id, f"trace{pass_id}", 123.0)

    def test_outcomes_and_eliminations(self, solver, lattice):
        expl = self._pass(solver, lattice, [
            _pod(1, node_selector={wk.LABEL_INSTANCE_TYPE: "m5.large"}),
            _pod(2, shape={"cpu": "10000"})])
        assert expl.pods == 2 and expl.groups_total == 2
        assert expl.unschedulable_total == 1
        assert tx.code_of(expl.unschedulable["p2"]) == tx.NO_OFFERING
        assert expl.reason_counts == {tx.NO_OFFERING: 1}
        assert expl.eliminations.get("requirements", 0) > 0
        # the unplaced group sorts first and the pod maps to it
        gi = expl.pod_group["p2"]
        assert expl.groups[gi].unplaced == 1
        assert expl.groups[gi].code == tx.NO_OFFERING

    def test_ring_lookups_and_stats(self, solver, lattice):
        ring = ex.DecisionAuditRing(size=2)
        for i in range(3):
            ring.record(self._pass(
                solver, lattice,
                [_pod(1, shape={"cpu": "10000"})], pass_id=i + 1))
        assert ring.passes_recorded == 3
        st = ring.stats()
        assert st["ring"] == 2.0 and st["last_pass"] == 3.0
        assert st["reason_no_offering"] == 3.0
        assert any(k.startswith("elim_") for k in st)
        # pod lookup renders the newest pass's ledger
        doc = ring.find_pod("p1")
        assert doc["pass"] == 3 and doc["code"] == tx.NO_OFFERING
        assert doc["group"]["stages"][0]["stage"] == "offered"
        assert ring.find_pass(2).trace_id == "trace2"
        assert ring.find_pass() is ring.find_pass(3)
        assert ring.find_pod("nobody") is None

    def test_claim_rationale_and_placements(self, solver, lattice):
        p = build_problem([_pod(1)], [NodePool(name="default")], lattice,
                          explain=True)
        plan = solver.solve(p)
        expl = ex.explain_pass(p, plan, 1, "t", 0.0)
        node = plan.new_nodes[0]
        ex.add_claim(expl, "default-00001", node,
                     runner_up=("m5.xlarge", node.price_per_hour + 0.5))
        ring = ex.DecisionAuditRing()
        ring.record(expl)
        doc = ring.find_claim("default-00001")
        r = doc["rationale"]
        assert r["instanceType"] == node.instance_type
        assert r["runnerUpType"] == "m5.xlarge"
        assert r["runnerUpPriceDelta"] == pytest.approx(0.5)
        pod_doc = ring.find_pod("p1")
        assert pod_doc["outcome"] == "scheduled"
        assert pod_doc["node"] == "default-00001"
        assert pod_doc["rationale"]["instanceType"] == node.instance_type

    def test_split_groups_sharing_a_signature_attribute_correctly(self):
        """Topology splits produce multiple PodGroups with ONE signature;
        pod→group attribution must key on group index, never signature
        (review regression: the ICE'd split's pod rendered the healthy
        split's waterfall)."""
        rows = (ex.StageRow("offered", 10, 0),)
        led_ok = ex.GroupLedger(label="a", signature="SIG", pods=2,
                                stages=rows)
        led_bad = ex.GroupLedger(
            label="a", signature="SIG", pods=1,
            stages=(ex.StageRow("offered", 10, 0),
                    ex.StageRow("ice", 0, 10)))

        class G:
            def __init__(self, names, led):
                self.pod_names = names
                self.ledger = led

        class P:
            groups = [G(["a1", "a2"], led_ok), G(["b1"], led_bad)]
            dropped_groups = []

        class Plan:
            unschedulable = {"b1": tx.reason(tx.ICE_HOLD)}
            existing_assignments = {"n1": ["a1", "a2"]}
            degraded_reason = ""

        expl = ex.explain_pass(P(), Plan(), 1, "t", 0.0)
        entry = expl.groups[expl.pod_group["b1"]]
        assert entry.ledger is led_bad and entry.unplaced == 1
        assert entry.ledger.blame() == "ice"

    def test_add_placements_folds_retry_rounds(self, solver, lattice):
        p = build_problem([_pod(1)], [NodePool(name="default")], lattice,
                          explain=True)
        plan = solver.solve(p)
        expl = ex.explain_pass(p, plan, 1, "t", 0.0)

        class Retry:
            existing_assignments = {"node-9": ["late-pod"]}

        ex.add_placements(expl, Retry())
        assert expl.placements["late-pod"] == "node-9"
        # idempotent: re-folding the same plan double-counts nothing
        n = expl.placements_total
        ex.add_placements(expl, Retry())
        assert expl.placements_total == n

    def test_doc_query_shapes(self, solver, lattice):
        ring = ex.DecisionAuditRing()
        ring.record(self._pass(solver, lattice,
                               [_pod(1, shape={"cpu": "10000"})]))
        base = ring.doc({})
        assert base["recorded"] == 1 and len(base["passes"]) == 1
        assert base["reasons"] == {tx.NO_OFFERING: 1}
        assert ring.doc({"pod": ["p1"]})["code"] == tx.NO_OFFERING
        assert ring.doc({"pod": ["ghost"]})["found"] is False
        assert ring.doc({"pass": ["1"]})["groupDetails"]
        assert ring.doc({"pass": ["99"]})["found"] is False
        assert ring.doc({"nodeclaim": ["x"]})["found"] is False


# ---------------------------------------------------------------------------
# delta-vs-full explanation parity (the tentpole's pinned contract)


class TestExplanationParity:
    def test_delta_ledgers_match_full_rebuild(self, lattice):
        rng = np.random.default_rng(7)
        cluster = ClusterState(FakeClock())
        pools = [NodePool(name="default")]
        serial = 0
        for _ in range(60):
            serial += 1
            cluster.add_pod(_pod(serial, shape={
                "cpu": ["250m", "500m", "1"][serial % 3],
                "memory": "512Mi"}))
        builder = IncrementalProblemBuilder(explain=True)
        last_rev = -1
        incremental_seen = 0
        for step in range(25):
            r = rng.random()
            if r < 0.5:
                for _ in range(int(rng.integers(1, 4))):
                    serial += 1
                    cluster.add_pod(_pod(serial, shape={
                        "cpu": ["250m", "500m", "1"][serial % 3],
                        "memory": "512Mi"}))
            else:
                pending = cluster.pending_pods()
                if pending:
                    cluster.delete_pod(
                        pending[int(rng.integers(len(pending)))].name)
            dirty = cluster.dirty_since(last_rev)
            touched = cluster.touched_pods(dirty.pods)
            pending = cluster.pending_pods()
            res = builder.build(pending, pools, lattice,
                                existing=lambda: [], dirty=dirty,
                                touched=touched)
            last_rev = builder.rev
            incremental_seen += bool(res.incremental)
            scratch = build_problem(pending, pools, lattice,
                                    explain=True)
            got = {g.signature: g.ledger.to_doc()
                   for g in res.problem.groups + res.problem.dropped_groups}
            want = {g.signature: g.ledger.to_doc()
                    for g in scratch.groups + scratch.dropped_groups}
            assert got == want, f"step {step}: explanation diverged " \
                                f"(incremental={res.incremental})"
        assert incremental_seen > 5, \
            f"only {incremental_seen}/25 steps took the delta path"

    def test_dropped_group_churn_forces_full_rebuild(self, lattice):
        """A build-time-dropped group's membership changing would leave
        the retained dropped_groups (and their ledgers) stale — the
        delta path must stand down (review regression)."""
        cluster = ClusterState(FakeClock())
        pools = [NodePool(name="default")]
        for i in range(5):
            cluster.add_pod(_pod(i + 1))
        # two pods in a dropped group (impossible selector)
        for n in ("drop-1", "drop-2"):
            cluster.add_pod(Pod(name=n, requests={"cpu": "250m"},
                                node_selector={
                                    wk.LABEL_INSTANCE_TYPE: "nope.xl"}))
        builder = IncrementalProblemBuilder(explain=True)
        dirty = cluster.dirty_since(-1)
        res = builder.build(cluster.pending_pods(), pools, lattice,
                            existing=lambda: [], dirty=dirty,
                            touched=cluster.touched_pods(dirty.pods))
        assert res.problem.dropped_groups
        rev = builder.rev
        # plain churn still deltas
        cluster.add_pod(_pod(100))
        dirty = cluster.dirty_since(rev)
        res = builder.build(cluster.pending_pods(), pools, lattice,
                            existing=lambda: [], dirty=dirty,
                            touched=cluster.touched_pods(dirty.pods))
        assert res.incremental
        rev = builder.rev
        # deleting a dropped-group pod forces the full rebuild
        cluster.delete_pod("drop-1")
        dirty = cluster.dirty_since(rev)
        res = builder.build(cluster.pending_pods(), pools, lattice,
                            existing=lambda: [], dirty=dirty,
                            touched=cluster.touched_pods(dirty.pods))
        assert not res.incremental
        assert res.reason == "dropped-group-churn"
        # and the rebuilt dropped ledger reflects the new membership
        assert [len(g.pod_names)
                for g in res.problem.dropped_groups] == [1]


# ---------------------------------------------------------------------------
# the provisioning controller: dedup + metrics + ring wiring


class TestProvisionerExplain:
    def _op(self, lattice):
        from karpenter_provider_aws_tpu.operator import Operator, Options
        clock = FakeClock()
        return Operator(options=Options(registration_delay=0.5),
                        lattice=lattice, cloud=FakeCloud(clock),
                        clock=clock), clock

    def _ice_family(self, op, lattice, family="c5."):
        for z in lattice.zones:
            for ct in lattice.capacity_types:
                for t in [n for n in lattice.names
                          if n.startswith(family)]:
                    op.unavailable.mark_unavailable("test", ct, t, z)

    def test_failed_scheduling_dedup_and_metric(self, lattice):
        op, clock = self._op(lattice)
        self._ice_family(op, lattice)
        op.cluster.add_pod(Pod(
            name="stuck", requests={"cpu": "500m"},
            node_selector={"karpenter.k8s.aws/instance-family": "c5"}))
        for _ in range(3):
            op.run_once(force_provision=True)
            clock.step(1.0)
        evs = [e for e in op.recorder.events(reason="FailedScheduling")
               if e.object_name == "stuck"]
        assert len(evs) == 1, [e.message for e in evs]
        assert tx.code_of(evs[0].message) == tx.ICE_HOLD
        m = op.metrics.get("karpenter_pods_unschedulable_reasons_total")
        assert m.value(code=tx.ICE_HOLD) == 3.0   # per-pass, rate-able
        elim = op.metrics.get(
            "karpenter_explain_offering_eliminations_total")
        assert elim.value(stage="ice") > 0

    def test_dedup_rearms_on_code_change_and_progress(self, lattice):
        op, _ = self._op(lattice)
        prov = op.provisioner
        seen = {}
        prov._publish_failed("x", tx.reason(tx.ICE_HOLD), seen)
        prov._publish_failed("x", tx.reason(tx.ICE_HOLD), seen)
        assert len(op.recorder.events(reason="FailedScheduling")) == 1
        # reason change publishes again
        prov._publish_failed("x", tx.reason(tx.NO_OFFERING), seen)
        assert len(op.recorder.events(reason="FailedScheduling")) == 2
        # progress (not unschedulable this pass) re-arms the pair
        from karpenter_provider_aws_tpu.controllers.provisioning import (
            ProvisionResult)
        prov._finish_pass(ProvisionResult(plan=None), 0, seen_unsched={})
        prov._publish_failed("x", tx.reason(tx.NO_OFFERING), {})
        assert len(op.recorder.events(reason="FailedScheduling")) == 3

    def test_recreated_pod_republishes(self, lattice):
        """A same-name RECREATED pod is a new pod: its failure gets its
        own event even when the reason code never changed (review
        regression — object identity re-arms the dedup)."""
        op, _ = self._op(lattice)
        prov = op.provisioner
        pod_a = _pod(1)
        seen = {}
        prov._publish_failed("p1", tx.reason(tx.ICE_HOLD), seen, pod=pod_a)
        prov._publish_failed("p1", tx.reason(tx.ICE_HOLD), seen, pod=pod_a)
        assert len(op.recorder.events(reason="FailedScheduling")) == 1
        pod_b = _pod(1)   # recreated: new object, same name
        prov._publish_failed("p1", tx.reason(tx.ICE_HOLD), seen, pod=pod_b)
        assert len(op.recorder.events(reason="FailedScheduling")) == 2

    def test_runner_up_prices_against_the_ice_mask(self, lattice):
        """The claim rationale must never present an ICE'd-out offering
        as the viable alternative (review regression)."""
        from karpenter_provider_aws_tpu.solver.solve import PlannedNode
        op, _ = self._op(lattice)
        node = PlannedNode(
            node_pool="default", instance_type="m5.large",
            zone=lattice.zones[0], capacity_type="on-demand",
            price_per_hour=0.1, pods=["p1"],
            feasible_types=("m5.large", "c5.large"),
            feasible_zones=(lattice.zones[0],),
            feasible_capacity_types=("on-demand",))
        ru = op.provisioner._runner_up(node)
        assert ru is not None and ru[0] == "c5.large"
        # ICE the runner-up's every offering: no alternative to present
        for z in lattice.zones:
            for ct in lattice.capacity_types:
                op.unavailable.mark_unavailable("t", ct, "c5.large", z)
        assert op.provisioner._runner_up(node) is None

    def test_ring_records_passes_and_serves_debug_doc(self, lattice):
        from karpenter_provider_aws_tpu import introspect
        op, clock = self._op(lattice)
        self._ice_family(op, lattice)
        op.cluster.add_pod(Pod(
            name="stuck", requests={"cpu": "500m"},
            node_selector={"karpenter.k8s.aws/instance-family": "c5"}))
        op.cluster.add_pod(Pod(name="fine",
                               requests={"cpu": "500m", "memory": "1Gi"}))
        op.run_once(force_provision=True)
        assert "explain" in introspect.registry().names()
        assert introspect.explain_ring() is op.provisioner.explain
        body, ctype = introspect.debug_doc("/debug/explain",
                                           {"pod": ["stuck"]})
        doc = json.loads(body)
        assert ctype == "application/json"
        assert doc["code"] == tx.ICE_HOLD
        assert doc["group"]["blame"] == "ice"
        # the created claim carries a placement rationale
        claims = op.provisioner.explain.find_pass().claims
        assert claims and all("instanceType" in r for r in claims.values())

    def test_solve_error_pass_recorded(self, lattice):
        op, _ = self._op(lattice)
        op.cluster.add_pod(_pod(1))

        def boom(*a, **kw):
            raise RuntimeError("device gone")
        op.provisioner.solver = type("S", (), {
            "supports_delta": False,
            "solve_relaxed": staticmethod(boom),
            "lattice": lattice, "stats": staticmethod(lambda: {})})()
        op.provisioner._delta_enabled = False
        res = op.provisioner.provision_once()
        assert res.degraded and res.pods_unschedulable == 1
        e = op.provisioner.explain.find_pass()
        assert e.reason_counts == {tx.SOLVE_ERROR: 1}
        assert "device gone" in e.note


# ---------------------------------------------------------------------------
# kpctl surfaces


class FakeClient:
    def __init__(self, routes):
        self.routes = routes

    def request(self, method, path, doc=None, stream=False, raw=False):
        for prefix, payload in self.routes.items():
            if path.startswith(prefix):
                return payload
        raise AssertionError(f"unexpected request {path}")


class TestKpctl:
    @pytest.fixture(autouse=True)
    def _tools_path(self, monkeypatch):
        monkeypatch.syspath_prepend(str(
            pathlib.Path(__file__).resolve().parent.parent / "tools"))

    def _pod_doc(self):
        return {
            "pod": "w3", "pass": 7, "traceId": "abc",
            "outcome": "unschedulable", "code": "ice-hold",
            "reason": "ice-hold: all compatible offerings currently "
                      "unavailable",
            "group": {"label": "cpu=500m", "pods": 12, "poolsOk": 1,
                      "poolsTotal": 1, "remaining": 0, "blame": "ice",
                      "stages": [
                          {"stage": "offered", "remaining": 150,
                           "eliminated": 0},
                          {"stage": "ice", "remaining": 0,
                           "eliminated": 12,
                           "examples": ["m5.large/us-east-1a/spot"]}]},
        }

    def test_explain_pod_renders_waterfall(self, capsys):
        import kpctl
        c = FakeClient({"/debug/explain?pod=w3": self._pod_doc()})
        args = type("A", (), {"what": "pod", "name": "w3"})
        assert kpctl.cmd_explain(c, args) == 0
        out = capsys.readouterr().out
        assert "eliminated by ice: 12 offerings" in out
        assert "m5.large/us-east-1a/spot" in out
        assert "ice-hold" in out

    def test_explain_nodeclaim_renders_rationale(self, capsys):
        import kpctl
        doc = {"nodeclaim": "default-00001", "pass": 3,
               "rationale": {"instanceType": "m5.large",
                             "zone": "us-east-1a",
                             "capacityType": "spot",
                             "pricePerHour": 0.03, "pods": 4,
                             "flexibleTypes": 12,
                             "runnerUpType": "m5.xlarge",
                             "runnerUpPricePerHour": 0.05,
                             "runnerUpPriceDelta": 0.02}}
        c = FakeClient({"/debug/explain?nodeclaim=": doc})
        args = type("A", (), {"what": "nodeclaim", "name": "default-00001"})
        assert kpctl.cmd_explain(c, args) == 0
        out = capsys.readouterr().out
        assert "m5.large/us-east-1a/spot" in out
        assert "Runner-up: m5.xlarge" in out

    def test_explain_missing_pod_exits_1(self, capsys):
        import kpctl
        c = FakeClient({"/debug/explain?pod=": {"found": False,
                                                "message": "not seen"}})
        args = type("A", (), {"what": "pod", "name": "ghost"})
        assert kpctl.cmd_explain(c, args) == 1

    def test_top_renders_explain_row(self):
        import kpctl
        doc = {"providers": {"explain": {
            "passes": 12.0, "ring": 12.0, "last_unschedulable": 3.0,
            "reason_ice_hold": 9.0, "reason_no_fit": 2.0}}}
        lines = kpctl._render_top(doc, "srv")
        row = next(line for line in lines if line.startswith("EXPLAIN"))
        assert "passes 12" in row and "ice-hold 9" in row

    def test_top_without_explain_provider_has_no_row(self):
        import kpctl
        lines = kpctl._render_top({"providers": {}}, "srv")
        assert not any(line.startswith("EXPLAIN") for line in lines)

    def test_describe_pod_reasons_block(self, capsys):
        import kpctl
        c = FakeClient({"/debug/explain?pod=w3": self._pod_doc()})
        kpctl._print_pod_reasons(c, "w3")
        out = capsys.readouterr().out
        assert "Reasons:" in out
        assert "ice-hold" in out
        assert "Eliminated by:  ice: 12 offerings" in out

    def test_describe_pod_reasons_quiet_on_missing(self, capsys):
        import kpctl
        c = FakeClient({"/debug/explain?pod=": {"found": False}})
        kpctl._print_pod_reasons(c, "ghost")
        assert capsys.readouterr().out == ""


# ---------------------------------------------------------------------------
# graftlint reason-code rule fixtures live in tests/test_lint.py
