"""Fake cloud network / IAM / image / template surface.

Mirror of the reference's non-EC2-fleet fakes (reference pkg/fake: EKS,
SSM, IAM fakes + subnet/SG/image describe APIs): subnets with free-IP
accounting, security groups, machine images with SSM alias parameters,
IAM instance profiles, and launch templates. Seeded with a plausible
default VPC so the provider layer works out of the box; tests override.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..errors import AlreadyExistsError, NotFoundError


@dataclass
class Subnet:
    id: str
    zone: str
    cidr: str
    available_ips: int
    tags: Dict[str, str] = field(default_factory=dict)
    # "availability-zone" | "local-zone" (DescribeAvailabilityZones
    # ZoneType; the reference's localzone suite selects zones by it)
    zone_type: str = "availability-zone"


@dataclass
class SecurityGroup:
    id: str
    name: str
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class Image:
    id: str
    name: str
    arch: str                  # amd64 | arm64
    creation_date: float
    deprecated: bool = False
    tags: Dict[str, str] = field(default_factory=dict)
    requirements: Dict[str, str] = field(default_factory=dict)  # e.g. gpu-only images


@dataclass
class InstanceProfile:
    name: str
    role: str
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class LaunchTemplate:
    id: str
    name: str
    image_id: str
    user_data: str
    security_group_ids: Tuple[str, ...]
    instance_profile: str
    tags: Dict[str, str] = field(default_factory=dict)
    metadata_options: Dict[str, str] = field(default_factory=dict)
    block_device_mappings: Tuple = ()


def _match_tags(obj_tags: Dict[str, str], want: Dict[str, str]) -> bool:
    for k, v in want.items():
        if v == "*":
            if k not in obj_tags:
                return False
        elif obj_tags.get(k) != v:
            return False
    return True


class FakeNetwork:
    """Attached to FakeCloud as `.network`."""

    def __init__(self, zones: Optional[Sequence[str]] = None,
                 cluster_name: str = "sim", k8s_version: str = "1.29",
                 ip_family: str = "ipv4"):
        self._lock = threading.RLock()
        self._ids = itertools.count(1)
        self.k8s_version = k8s_version
        self.cluster_endpoint = f"https://{cluster_name}.sim.local"
        # single-stack IP family (reference test/suites/ipv6): the kube-dns
        # service IP the operator discovers best-effort
        # (operator.go:125-132) and the address family of launched nodes
        assert ip_family in ("ipv4", "ipv6"), ip_family
        self.ip_family = ip_family
        self.kube_dns_ip = ("fd30:7061:6b65:74::a" if ip_family == "ipv6"
                           else "10.100.0.10")
        self.subnets: Dict[str, Subnet] = {}
        self.security_groups: Dict[str, SecurityGroup] = {}
        self.images: Dict[str, Image] = {}
        self.instance_profiles: Dict[str, InstanceProfile] = {}
        self.launch_templates: Dict[str, LaunchTemplate] = {}
        self.ssm_parameters: Dict[str, str] = {}
        discovery = {f"kubernetes.io/cluster/{cluster_name}": "owned"}
        from ..lattice import catalog as cat
        if zones is None:
            zones = cat.ZONES  # incl. the local zone (its subnet is tagged)
        for i, z in enumerate(zones):
            sid = f"subnet-{i+1:04d}"
            self.subnets[sid] = Subnet(
                id=sid, zone=z, cidr=f"10.0.{i}.0/24", available_ips=250,
                tags=dict(discovery),
                zone_type=cat.ZONE_TYPES.get(z, "availability-zone"))
        for i, name in enumerate(("default", "nodes")):
            gid = f"sg-{i+1:04d}"
            self.security_groups[gid] = SecurityGroup(id=gid, name=name,
                                                      tags=dict(discovery))
        # default AMIs per family x arch, exposed via SSM alias parameters
        # (reference amifamily/ami.go:136-181 SSM default-AMI discovery).
        # Keys come from each family strategy's own
        # default_ami_ssm_parameters() so the fake and the resolver can
        # never drift on the parameter paths. Deferred import: amifamily
        # imports this module for the Image type.
        from ..providers.amifamily import AMI_FAMILIES
        t = 1_000.0
        for fam_name, fam in AMI_FAMILIES.items():
            for arch, path in fam.default_ami_ssm_parameters(k8s_version).items():
                slug = fam_name.lower()
                iid = f"ami-{slug}-{arch}"
                if iid not in self.images:
                    self.images[iid] = Image(id=iid, name=f"{slug}-{arch}-v{k8s_version}",
                                             arch=arch, creation_date=t)
                self.ssm_parameters[path] = iid

    # ---- describe APIs ---------------------------------------------------

    def describe_subnets(self, tags: Optional[Dict[str, str]] = None,
                         ids: Sequence[str] = ()) -> List[Subnet]:
        with self._lock:
            out = []
            for s in self.subnets.values():
                if ids and s.id not in ids:
                    continue
                if tags and not _match_tags(s.tags, tags):
                    continue
                out.append(s)
            return out

    def describe_security_groups(self, tags: Optional[Dict[str, str]] = None,
                                 ids: Sequence[str] = (),
                                 names: Sequence[str] = ()) -> List[SecurityGroup]:
        with self._lock:
            out = []
            for g in self.security_groups.values():
                if ids and g.id not in ids:
                    continue
                if names and g.name not in names:
                    continue
                if tags and not _match_tags(g.tags, tags):
                    continue
                out.append(g)
            return out

    def describe_images(self, tags: Optional[Dict[str, str]] = None,
                        ids: Sequence[str] = (),
                        names: Sequence[str] = ()) -> List[Image]:
        with self._lock:
            out = []
            for im in self.images.values():
                if ids and im.id not in ids:
                    continue
                if names and im.name not in names:
                    continue
                if tags and not _match_tags(im.tags, tags):
                    continue
                out.append(im)
            return out

    def get_parameter(self, name: str) -> str:
        with self._lock:
            if name not in self.ssm_parameters:
                raise NotFoundError(f"ssm parameter not found: {name}")
            return self.ssm_parameters[name]

    # ---- IAM -------------------------------------------------------------

    def create_instance_profile(self, name: str, role: str,
                                tags: Optional[Dict[str, str]] = None) -> InstanceProfile:
        with self._lock:
            if name in self.instance_profiles:
                raise AlreadyExistsError(f"instance profile exists: {name}")
            p = InstanceProfile(name=name, role=role, tags=dict(tags or {}))
            self.instance_profiles[name] = p
            return p

    def get_instance_profile(self, name: str) -> InstanceProfile:
        with self._lock:
            if name not in self.instance_profiles:
                raise NotFoundError(f"instance profile not found: {name}")
            return self.instance_profiles[name]

    def delete_instance_profile(self, name: str) -> None:
        with self._lock:
            if name not in self.instance_profiles:
                raise NotFoundError(f"instance profile not found: {name}")
            del self.instance_profiles[name]

    # ---- launch templates --------------------------------------------------

    def create_launch_template(self, lt: LaunchTemplate) -> LaunchTemplate:
        with self._lock:
            if any(x.name == lt.name for x in self.launch_templates.values()):
                raise AlreadyExistsError(f"launch template exists: {lt.name}")
            lt.id = f"lt-{next(self._ids):06d}"
            self.launch_templates[lt.id] = lt
            return lt

    def describe_launch_templates(self, names: Sequence[str] = (),
                                  tags: Optional[Dict[str, str]] = None) -> List[LaunchTemplate]:
        with self._lock:
            out = []
            for lt in self.launch_templates.values():
                if names and lt.name not in names:
                    continue
                if tags and not _match_tags(lt.tags, tags):
                    continue
                out.append(lt)
            return out

    def delete_launch_template(self, name: str) -> None:
        with self._lock:
            found = [i for i, lt in self.launch_templates.items() if lt.name == name]
            if not found:
                raise NotFoundError(f"launch template not found: {name}")
            for i in found:
                del self.launch_templates[i]

    def reset(self) -> None:
        with self._lock:
            self.instance_profiles.clear()
            self.launch_templates.clear()
