from .fake import CloudInstance, FakeCloud, LaunchOverride

__all__ = ["FakeCloud", "CloudInstance", "LaunchOverride"]
