"""In-memory behavioral cloud backend.

Mirror of the reference's fake EC2 (reference pkg/fake/ec2api.go): a fleet
launch honors configured insufficient-capacity pools and picks the
lowest-priced available override (the CreateFleet lowest-price allocation
strategy); instances are describable/terminable; every API records its
calls and supports one-shot error injection (`next_error`, the
reference's AtomicError at ec2api.go:58-67). This is the stratum-2 test
backend AND the default backend of the simulation environment — swap in a
real cloud by implementing the same surface.
"""

from __future__ import annotations

import collections
import itertools
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..errors import NotFoundError, Offering, UnfulfillableCapacityError
from ..utils.clock import Clock


@dataclass
class LaunchOverride:
    """One (type, zone, capacity_type) candidate with its bid price."""

    instance_type: str
    zone: str
    capacity_type: str
    price: float

    @property
    def offering(self) -> Offering:
        return (self.capacity_type, self.instance_type, self.zone)


@dataclass
class CloudInstance:
    id: str
    instance_type: str
    zone: str
    capacity_type: str
    state: str = "running"            # pending|running|shutting-down|terminated
    launch_time: float = 0.0
    price: float = 0.0
    tags: Dict[str, str] = field(default_factory=dict)
    # launch materialization, consulted by live drift detection
    # (reference drift.go:44-135 compares these against the NodeClass)
    image_id: Optional[str] = None
    subnet_id: Optional[str] = None
    security_group_ids: Tuple[str, ...] = ()
    private_ip: Optional[str] = None  # InternalIP; v6 on ipv6 clusters

    @property
    def provider_id(self) -> str:
        return f"fake:///{self.zone}/{self.id}"


@dataclass
class FleetResult:
    """CreateFleet outcome: the launched instance plus the exhausted
    offerings skipped by the lowest-price walk (the analog of
    CreateFleetOutput.Instances + .Errors)."""

    instance: CloudInstance
    ice: List[Offering] = field(default_factory=list)


def parse_instance_id(provider_id: str) -> str:
    """Mirror of utils.ParseInstanceID over 'fake:///zone/i-…' provider IDs
    (reference pkg/utils/utils.go)."""
    parts = provider_id.rsplit("/", 1)
    if len(parts) != 2 or not parts[1]:
        raise ValueError(f"malformed provider id {provider_id!r}")
    return parts[1]


class FakeCloud:
    """Thread-safe in-memory cloud. Capacity pools: offering -> remaining
    instance count (absent = unlimited; 0 = ICE), mirroring
    InsufficientCapacityPools (ec2api.go:40-44, 112-190)."""

    def __init__(self, clock: Optional[Clock] = None,
                 cluster_name: str = "sim", k8s_version: str = "1.29",
                 ip_family: str = "ipv4"):
        from .network import FakeNetwork
        self.clock = clock or Clock()
        self._lock = threading.RLock()
        self._ids = itertools.count(1)
        self.instances: Dict[str, CloudInstance] = {}
        self.capacity_pools: Dict[Offering, int] = {}
        self.next_error: Optional[BaseException] = None
        # bounded: a long-running daemon polls list/describe every pass
        self.calls: "collections.deque[Tuple[str, object]]" = \
            collections.deque(maxlen=10000)
        # the session's assumed role, recorded by assume_role (the STS
        # layering seam, reference operator.go:93-107); None = base
        # credentials
        self.assumed_role_arn: Optional[str] = None
        # the VPC/IAM/image surface (subnets, SGs, AMIs+SSM, profiles, LTs)
        self.network = FakeNetwork(cluster_name=cluster_name,
                                   k8s_version=k8s_version, ip_family=ip_family)

    # ---- fault injection -------------------------------------------------

    def set_capacity(self, capacity_type: str, instance_type: str, zone: str,
                     remaining: int) -> None:
        with self._lock:
            self.capacity_pools[(capacity_type, instance_type, zone)] = remaining

    def clear_capacity(self, capacity_type: str, instance_type: str,
                       zone: str) -> None:
        """Drop a pool's limit entirely (absent = unlimited) — how the
        weather simulator thaws an ICE'd offering back to fair weather."""
        with self._lock:
            self.capacity_pools.pop((capacity_type, instance_type, zone), None)

    def inject_error(self, err: BaseException) -> None:
        with self._lock:
            self.next_error = err

    def _maybe_raise(self):
        if self.next_error is not None:
            err, self.next_error = self.next_error, None
            raise err

    # ---- APIs ------------------------------------------------------------

    def assume_role(self, role_arn: str) -> None:
        """Layer an assumed role onto the session (STS analog: every
        later call runs 'as' this role; the fake just records it so the
        operator's session wiring is observable)."""
        with self._lock:
            self.calls.append(("assume_role", role_arn))
            self.assumed_role_arn = role_arn

    def create_fleet(self, overrides: Sequence[LaunchOverride],
                     tags: Optional[Dict[str, str]] = None) -> "FleetResult":
        """Launch ONE instance from the cheapest available override.

        Returns the instance TOGETHER with every exhausted offering the
        lowest-price walk skipped on the way — real CreateFleet reports
        per-override errors even on success, and the provider feeds them
        into the UnavailableOfferings cache (reference instance.go:348-354
        updateUnavailableOfferingsCache on createFleetOutput.Errors).
        Raises UnfulfillableCapacityError naming every exhausted offering
        when no override has capacity.
        """
        with self._lock:
            self.calls.append(("create_fleet", tuple(o.offering for o in overrides)))
            self._maybe_raise()
            ice: List[Offering] = []
            for o in sorted(overrides, key=lambda o: o.price):
                remaining = self.capacity_pools.get(o.offering)
                if remaining is not None and remaining <= 0:
                    ice.append(o.offering)
                    continue
                if remaining is not None:
                    self.capacity_pools[o.offering] = remaining - 1
                n = next(self._ids)
                ip = (f"2600:1f14:73::{n:x}"
                      if self.network.ip_family == "ipv6"
                      else f"10.0.{(n >> 8) & 0xff}.{n & 0xff}")
                inst = CloudInstance(
                    id=f"i-{n:08x}", instance_type=o.instance_type,
                    zone=o.zone, capacity_type=o.capacity_type,
                    launch_time=self.clock.now(), price=o.price,
                    tags=dict(tags or {}), private_ip=ip)
                self.instances[inst.id] = inst
                return FleetResult(instance=inst, ice=ice)
            raise UnfulfillableCapacityError(offerings=ice or [o.offering for o in overrides])

    def describe_instances(self, ids: Sequence[str]) -> List[CloudInstance]:
        with self._lock:
            self.calls.append(("describe_instances", tuple(ids)))
            self._maybe_raise()
            return [self.instances[i] for i in ids if i in self.instances]

    def list_instances(self, include_terminated: bool = False) -> List[CloudInstance]:
        with self._lock:
            self.calls.append(("list_instances", ()))
            self._maybe_raise()
            return [i for i in self.instances.values()
                    if include_terminated or i.state not in ("terminated",)]

    def peek_instances(self) -> List[CloudInstance]:
        """Side-effect-free running-instance snapshot for observers (the
        weather simulator's storm targeting): no call recording and no
        injected-error consumption — a chaos observer must never race a
        controller for a test-injected fault (same contract as
        liveness_probe)."""
        with self._lock:
            return [i for i in self.instances.values()
                    if i.state == "running"]

    def liveness_probe(self) -> None:
        """Side-effect-free connectivity check for health endpoints: no
        call recording, no injected-error consumption (a /healthz poll must
        never race a controller for a test-injected fault)."""
        with self._lock:
            pass

    def create_tags(self, instance_id: str, tags: Dict[str, str]) -> None:
        """Merge tags onto a live instance (EC2 CreateTags analog; consumed
        by the post-registration tagging controller)."""
        with self._lock:
            self.calls.append(("create_tags", (instance_id, tuple(sorted(tags.items())))))
            self._maybe_raise()
            inst = self.instances.get(instance_id)
            if inst is None or inst.state == "terminated":
                raise NotFoundError(f"instance not found: {instance_id}")
            inst.tags.update(tags)

    def terminate_instances(self, ids: Sequence[str]) -> List[str]:
        """Terminate; unknown ids raise NotFoundError (callers treat it as
        already-gone, reference errors.go not-found taxonomy)."""
        with self._lock:
            self.calls.append(("terminate_instances", tuple(ids)))
            self._maybe_raise()
            missing = [i for i in ids if i not in self.instances]
            if missing:
                raise NotFoundError(f"instance(s) not found: {missing}")
            out = []
            for i in ids:
                inst = self.instances[i]
                if inst.state != "terminated":
                    inst.state = "terminated"
                    # freed pool capacity returns to the market
                    key = (inst.capacity_type, inst.instance_type, inst.zone)
                    if key in self.capacity_pools:
                        self.capacity_pools[key] += 1
                out.append(i)
            return out

    def tag_instance(self, instance_id: str, tags: Dict[str, str]) -> None:
        with self._lock:
            self.calls.append(("tag_instance", (instance_id, tuple(sorted(tags)))))
            self._maybe_raise()
            inst = self.instances.get(instance_id)
            if inst is None:
                raise NotFoundError(f"instance not found: {instance_id}")
            inst.tags.update(tags)

    def reset(self) -> None:
        with self._lock:
            self.instances.clear()
            self.capacity_pools.clear()
            self.next_error = None
            self.calls.clear()
            self.network.reset()
