"""Provider registry: every stateful subsystem reports cheap stats().

The reference exposes its runtime state through controller-runtime's
/metrics plus ad-hoc pprof/healthz handlers; the gap both it and this
repo had is a LIVE structured view of subsystem state — batcher
occupancy, solve-window coalescing, cache residency, writer throughput,
watch fan-out — without waiting for the next Prometheus scrape or
grepping logs. This registry is that seam: a subsystem registers a
zero-argument ``stats()`` callable returning a flat dict of numbers and
short strings; consumers (the statusz/vars endpoints, the Sampler, the
debug.Monitor soak artifact, ``kpctl top``) fan out over the providers.

Contract (pinned by tests/test_introspect.py):

- ``register()`` is O(1) and replace-by-name: a subsystem rebuilt in the
  same process (tests construct many Operators) replaces its old
  provider instead of leaking it.
- ``collect()`` snapshots the provider list under the registry lock and
  calls every ``stats()`` OUTSIDE it — a provider blocking on its own
  subsystem lock can never wedge registration or other providers'
  collection, and the registry lock is never held across user code.
- a provider that raises reports ``{"error": ...}`` for its name; one
  broken subsystem must not blind the view of the others.
- ``stats()`` implementations must be cheap snapshots (counter reads
  under the subsystem's own lock), never work: the sampler calls every
  provider once per second forever.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List

StatsProvider = Callable[[], Dict]


class IntrospectRegistry:
    def __init__(self):
        self._providers: Dict[str, StatsProvider] = {}
        self._lock = threading.Lock()

    def register(self, name: str, provider: StatsProvider) -> None:
        """Attach (or replace) the provider serving ``name``."""
        with self._lock:
            self._providers[name] = provider

    def unregister(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._providers)

    def collect(self) -> Dict[str, Dict]:
        """One stats snapshot per provider, registration-safe: the lock
        guards only the list copy, never the ``stats()`` calls."""
        with self._lock:
            providers = list(self._providers.items())
        out: Dict[str, Dict] = {}
        for name, provider in sorted(providers):
            try:
                stats = provider()
                out[name] = stats if isinstance(stats, dict) else {
                    "value": stats}
            except Exception as e:   # one broken provider never blinds the rest
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out
