"""SLO burn tracking: the paper's contract, watched at runtime.

The paper's contract is an SLO — 50k pods x 700+ instance types solved
in <200 ms p50 at <=2% cost regression vs the FFD referee — and until
now nothing in the process MEASURED it continuously: benches prove it
offline, traces explain one slow pass after the fact. This tracker
keeps rolling windows of both bars:

- **latency**: every provisioning pass records its end-to-end solve
  latency (``NodePlan.solve_seconds`` — tensorize + device solve +
  decode); the tracker maintains windowed p50/p99 and reports
  ``latency burn = p50 / 200 ms``.
- **cost**: on a sampled cadence (default every 60 s of passes that
  actually opened nodes — the FFD referee is host work and must never
  ride every pass) the provisioner re-packs the SAME problem with the
  host FFD oracle and records ``plan cost / referee cost``; the tracker
  reports ``cost burn = (windowed p50 ratio - 1) / 2%``.

``update()`` (driven from Operator.emit_gauges — every deterministic
pass, the 5 s metrics controller in the async runtime) exports both
burns as ``karpenter_slo_latency_budget_burn`` /
``karpenter_slo_cost_budget_burn`` gauges and publishes ONE
``SloBudgetBurn`` warning event per sustained episode (burn > 1.0 for
``sustain_seconds``), re-arming when the burn recovers.

Burn > 1.0 means the window is violating the paper's bar; a dashboard
alert on either gauge is the runtime restatement of the acceptance
criteria every perf PR is judged against.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Deque, Dict, Optional, Tuple

LATENCY_BUDGET_SECONDS = 0.200   # PAPER.md: <200 ms p50 end-to-end
COST_BUDGET_RATIO = 0.02         # PAPER.md: <=2% regression vs FFD referee
WINDOW_SECONDS = 300.0
SUSTAIN_SECONDS = 30.0
REFEREE_INTERVAL_SECONDS = 60.0
MAX_SAMPLES = 4096               # per window ring; bounds memory forever


def _percentile(values, q: float) -> float:
    if not values:
        return 0.0
    s = sorted(values)
    idx = min(int(q * len(s)), len(s) - 1)
    return float(s[idx])


class SloTracker:
    def __init__(self, clock, recorder=None, metrics=None,
                 latency_budget_seconds: float = LATENCY_BUDGET_SECONDS,
                 cost_budget_ratio: float = COST_BUDGET_RATIO,
                 window_seconds: float = WINDOW_SECONDS,
                 sustain_seconds: float = SUSTAIN_SECONDS,
                 referee_interval: float = REFEREE_INTERVAL_SECONDS):
        self._clock = clock
        self._recorder = recorder
        self.latency_budget_seconds = latency_budget_seconds
        self.cost_budget_ratio = cost_budget_ratio
        self.window_seconds = window_seconds
        self.sustain_seconds = sustain_seconds
        self.referee_interval = referee_interval
        self._lat: Deque[Tuple[float, float]] = deque(maxlen=MAX_SAMPLES)
        self._cost: Deque[Tuple[float, float]] = deque(maxlen=MAX_SAMPLES)
        self._lock = threading.Lock()
        self._gauges = None
        if metrics is not None:
            self._gauges = (
                metrics.gauge("karpenter_slo_latency_budget_burn"),
                metrics.gauge("karpenter_slo_cost_budget_burn"))
        # per-burn-kind episode state: when the burn FIRST exceeded 1.0
        # (None = within budget) and whether this episode already fired
        self._over_since: Dict[str, Optional[float]] = {"latency": None,
                                                        "cost": None}
        self._fired: Dict[str, bool] = {"latency": False, "cost": False}
        self._last_referee = float("-inf")
        self.referee_runs = 0
        self.referee_errors = 0
        # explicit boot warmup window: while open, latency samples are
        # DROPPED — a cold-compile first pass is boot cost, not steady-
        # state SLO signal, and must not fire a SloBudgetBurn episode
        # (SOAK_r06 recorded peak burn ~8 from exactly this). Opened by
        # the operator when AOT warmup starts, closed by the warmup
        # thread's on_done (with max_seconds as the crash backstop).
        self._warmup_until = float("-inf")
        self.warmup_dropped = 0
        # observers of the sustained-burn edge. ``on_sustained(kind,
        # burn, detail)`` fires EXACTLY where the SloBudgetBurn event
        # does — once per episode, re-armed on recovery — so a
        # burn-triggered profile capture (introspect/profiler.py
        # BurnCapture) inherits the episode semantics for free.
        # ``_capture`` additionally sees every recorded pass latency
        # (its own slow-pass trigger).
        self.on_sustained: Optional[Callable[[str, float, str], None]] = None
        self._capture = None

    def attach_capture(self, capture) -> None:
        """Wire a BurnCapture: sustained episodes AND grossly
        over-budget single passes snapshot profile+contention evidence
        (docs/reference/profiling.md)."""
        self._capture = capture
        if capture is not None:
            self.on_sustained = capture.on_sustained_burn

    def headroom_probe(self) -> Dict[str, float]:
        """Sample-window ring occupancy (introspect/headroom.py): the
        fuller of the latency/cost rings. ``kind="ring"`` — the windows
        are bounded by design; old samples aging out IS the window."""
        with self._lock:
            depth = max(len(self._lat), len(self._cost))
        return {"depth": float(depth), "capacity": float(MAX_SAMPLES),
                "kind": "ring"}

    # ---- boot warmup window ----------------------------------------------

    def begin_warmup(self, max_seconds: float = 600.0) -> None:
        """Open the warmup window: latency recorded before end_warmup()
        (or ``max_seconds`` from now, whichever first) is boot compile
        cost and stays out of the burn windows."""
        with self._lock:
            self._warmup_until = self._clock.now() + max_seconds

    def end_warmup(self) -> None:
        """Close the warmup window (idempotent; safe from the warmup
        thread)."""
        with self._lock:
            self._warmup_until = min(self._warmup_until, self._clock.now())

    def warmup_active(self) -> bool:
        return self._clock.now() < self._warmup_until

    # ---- recording (hot path: O(1) appends) -------------------------------

    def record_latency(self, seconds: float) -> None:
        with self._lock:
            now = self._clock.now()
            if now < self._warmup_until:
                # boot warmup: cold-compile passes are not SLO signal
                self.warmup_dropped += 1
                return
            self._lat.append((now, float(seconds)))
        cap = self._capture
        if cap is not None:
            # outside the lock: a capture walks profiler/contention
            # state and must never serialize the recording hot path
            try:
                cap.note_latency(float(seconds))
            except Exception:
                pass   # evidence collection must not fail provisioning

    def record_cost_ratio(self, ratio: float) -> None:
        with self._lock:
            self._cost.append((self._clock.now(), float(ratio)))

    def maybe_cost_referee(self, plan, problem_builder: Callable[[], object]
                           ) -> Optional[float]:
        """Sampled FFD-referee comparison: at most one host re-pack per
        ``referee_interval``, only for passes that opened new nodes (an
        all-existing pass has no cost to regress). Never raises — a
        referee bug must not take down provisioning."""
        if not plan.new_nodes or plan.new_node_cost <= 0:
            return None
        now = self._clock.now()
        with self._lock:
            if now - self._last_referee < self.referee_interval:
                return None
            self._last_referee = now
        try:
            from ..solver.oracle import ffd_oracle
            oracle = ffd_oracle(problem_builder())
            if oracle.new_node_cost <= 0:
                return None
            ratio = float(plan.new_node_cost) / float(oracle.new_node_cost)
        except Exception:
            with self._lock:
                self.referee_errors += 1
            return None
        with self._lock:
            self.referee_runs += 1
        self.record_cost_ratio(ratio)
        return ratio

    # ---- windowed reads ---------------------------------------------------

    def _window(self, ring: Deque[Tuple[float, float]]) -> list:
        cutoff = self._clock.now() - self.window_seconds
        with self._lock:
            # prune in place (left side is oldest), then copy values
            while ring and ring[0][0] < cutoff:
                ring.popleft()
            return [v for _, v in ring]

    def latency_percentiles(self) -> Tuple[float, float]:
        vals = self._window(self._lat)
        return _percentile(vals, 0.50), _percentile(vals, 0.99)

    def cost_ratio_p50(self) -> float:
        return _percentile(self._window(self._cost), 0.50)

    # ---- the burn decision ------------------------------------------------

    def update(self) -> Dict[str, float]:
        """Recompute both burns, export the gauges, and fire/re-arm the
        sustained-burn event. Cheap enough for every reconcile pass."""
        p50, p99 = self.latency_percentiles()
        latency_burn = p50 / self.latency_budget_seconds
        ratio = self.cost_ratio_p50()
        cost_burn = (max(ratio - 1.0, 0.0) / self.cost_budget_ratio
                     if ratio > 0 else 0.0)
        if self._gauges is not None:
            self._gauges[0].set(round(latency_burn, 4))
            self._gauges[1].set(round(cost_burn, 4))
        self._check_sustained("latency", latency_burn,
                              f"p50 {p50 * 1000:.1f} ms over the "
                              f"{self.latency_budget_seconds * 1000:.0f} ms "
                              "budget")
        self._check_sustained("cost", cost_burn,
                              f"cost ratio {ratio:.4f} over the "
                              f"{1 + self.cost_budget_ratio:.2f}x FFD-referee "
                              "budget")
        return {"latency_burn": round(latency_burn, 4),
                "cost_burn": round(cost_burn, 4),
                "latency_p50_ms": round(p50 * 1000, 3),
                "latency_p99_ms": round(p99 * 1000, 3),
                "cost_ratio_p50": round(ratio, 4)}

    def _check_sustained(self, kind: str, burn: float, detail: str) -> None:
        # episode state mutates under the lock: update() runs from both
        # the metrics controller and the sampler thread, and an episode
        # must fire its event exactly once
        now = self._clock.now()
        fire = False
        with self._lock:
            if burn <= 1.0:
                self._over_since[kind] = None
                self._fired[kind] = False   # episode over: re-arm
                return
            if self._over_since[kind] is None:
                self._over_since[kind] = now
            if (not self._fired[kind]
                    and now - self._over_since[kind] >= self.sustain_seconds):
                self._fired[kind] = True
                fire = True
        if fire:
            if self._recorder is not None:
                self._recorder.publish(
                    "Warning", "SloBudgetBurn", "Provisioner", "default",
                    f"{kind} budget burn {burn:.2f} sustained "
                    f">{self.sustain_seconds:.0f}s ({detail})")
            cb = self.on_sustained
            if cb is not None:
                try:
                    cb(kind, burn, detail)
                except Exception:
                    pass   # a capture bug must not break burn tracking

    # ---- introspection provider -------------------------------------------

    def stats(self) -> Dict:
        burns = self.update()
        with self._lock:
            burns.update({
                "latency_samples": len(self._lat),
                "cost_samples": len(self._cost),
                "referee_runs": self.referee_runs,
                "referee_errors": self.referee_errors,
                "latency_budget_ms": self.latency_budget_seconds * 1000.0,
                "cost_budget_pct": self.cost_budget_ratio * 100.0,
                "warmup_active": (1.0 if self._clock.now()
                                  < self._warmup_until else 0.0),
                "warmup_dropped": self.warmup_dropped,
            })
        return burns
