"""Saturation observatory: the process-wide headroom registry.

ROADMAP item 4 ends with "whatever profiling shows breaking first at
that scale is the next refactor target" — this module turns that
question into an instrument. Every bounded resource in the process
(watch queues, publish queues, journal windows, audit/sampler rings,
batcher buckets, caches) registers a CHEAP probe, and the registry
derives, on the injected clock:

- windowed EWMA **fill/drain rates** from successive depth readings
  (dropped items count as fill pressure — an overflowing queue whose
  depth is pinned at the bound is still filling),
- a **headroom burn rate** (occupancy / high-water fraction — the
  occupancy analog of the SLO burn: > 1.0 means the resource is past
  the fraction a saturating process crosses before it breaks),
- a per-resource **time-to-exhaustion forecast**
  ``(capacity - depth) / net fill rate``, ranked into a first-to-break
  table — so a scaled-up soak names its next refactor target while the
  run is still green, not after the 410/overflow already fired.

Probe contract (see docs/reference/headroom.md): a zero-argument
callable returning a dict of cheap counter reads —

    {"depth": float,            # current occupancy (required)
     "capacity": float,         # bound; 0 = unbounded (forecast only)
     "highwater": float,        # optional structure-kept high water
     "drops": float,            # optional cumulative overflow/drop count
     "kind": "queue" | "ring"}  # ring = full-by-design (see below)

``kind="ring"`` marks circular telemetry buffers (sampler rings, the
decision-audit ring, event history) whose *job* is to sit at capacity:
they stay in the registry and the gauge families, but they never rank
in the first-to-break table, never fire the high-water capture, and
never fail the soak's no-unexplained-saturation verdict — wrapping is
retention policy, not data loss. ``kind="queue"`` (the default) is a
backlog whose saturation means drops/410s/stalls.

High water is MONOTONIC PER PROCESS: the registry folds every observed
depth (and any structure-kept high water) into a max that never resets,
even when the probe's own readout regresses (e.g. a dropped watcher
taking its queue with it).

Crossing the configurable high-water fraction (default 0.9) of a
queue-kind resource triggers the existing burn-capture machinery
(introspect/profiler.py BurnCapture) EXACTLY ONCE PER EPISODE — armed
again only after occupancy recovers below the fraction — so the
flamegraph of the saturating moment is retained at
``/debug/pprof/captures`` with reason ``headroom-<resource>``.

Probes are registered by ``Operator._wire_headroom`` and error-isolated
exactly like introspection providers: one broken probe marks its own
row with ``error`` and can never poison the ranked table.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Dict, List, Optional

DEFAULT_HIGH_WATER_FRACTION = 0.9
# EWMA time constant for the fill/drain rates: ~30 s of history, the
# same order as the SLO tracker's sustain window — long enough that one
# bursty pass does not name a false first-to-break, short enough that a
# soak's ramp shows up before the overflow does
EWMA_TAU_SECONDS = 30.0
# net fill below this (items/second) reads as "not filling": forecast
# noise floor, so a flat queue never reports a billion-second TTE
MIN_NET_FILL = 1e-9

Probe = Callable[[], Dict]


class _Resource:
    """Per-resource observation state (mutated only under the registry
    lock; the probe itself is called outside it)."""

    __slots__ = ("name", "probe", "kind", "depth", "capacity", "highwater",
                 "drops", "fill_rate", "drain_rate", "last_t", "last_depth",
                 "last_drops", "error", "fired", "episodes", "observations")

    def __init__(self, name: str, probe: Probe):
        self.name = name
        self.probe = probe
        self.kind = "queue"
        self.depth = 0.0
        self.capacity = 0.0
        self.highwater = 0.0       # monotonic per process, never resets
        self.drops = 0.0
        self.fill_rate = 0.0       # EWMA items/s of inflow pressure
        self.drain_rate = 0.0      # EWMA items/s of outflow
        self.last_t: Optional[float] = None
        self.last_depth = 0.0
        self.last_drops = 0.0
        self.error: Optional[str] = None
        self.fired = False         # high-water episode armed/fired state
        self.episodes = 0
        self.observations = 0


class HeadroomRegistry:
    """Process-wide registry of bounded-resource probes + the forecast.

    ``register_probe`` is replace-by-name like the introspection
    registry (a rebuilt Operator swaps its probes instead of leaking
    them); ``observe()`` takes one reading of every probe on the
    injected clock; ``table()`` returns the ranked first-to-break view;
    ``stats()`` is the ``headroom`` introspection provider; ``doc()``
    serves ``/debug/headroom`` on both HTTP servers."""

    def __init__(self, clock,
                 high_water_fraction: float = DEFAULT_HIGH_WATER_FRACTION,
                 tau_seconds: float = EWMA_TAU_SECONDS):
        self._clock = clock
        self.high_water_fraction = float(high_water_fraction)
        self.tau_seconds = float(tau_seconds)
        self._lock = threading.Lock()
        self._resources: Dict[str, _Resource] = {}
        self._capture = None
        self.probe_errors = 0

    # ---- registration ------------------------------------------------------

    def register_probe(self, name: str, probe: Probe) -> None:
        with self._lock:
            self._resources[name] = _Resource(name, probe)

    def unregister_probe(self, name: str) -> None:
        with self._lock:
            self._resources.pop(name, None)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._resources)

    def attach_capture(self, capture) -> None:
        """Wire the burn-capture machinery: a queue-kind resource
        crossing the high-water fraction snapshots profile + contention
        evidence once per episode (docs/reference/profiling.md)."""
        self._capture = capture

    # ---- observation -------------------------------------------------------

    def observe(self) -> None:
        """One reading of every probe. Cheap (counter reads), never
        raises: a broken probe marks its own row and the rest of the
        sweep proceeds. Called from Operator.emit_gauges (every pass /
        the 5 s metrics controller) and from stats()."""
        with self._lock:
            targets = list(self._resources.values())
        now = float(self._clock.now())
        fire: List[Dict] = []
        for r in targets:
            try:
                reading = r.probe()
                depth = float(reading["depth"])
            except Exception as e:   # noqa: BLE001 — probe isolation
                with self._lock:
                    if r.error is None:
                        self.probe_errors += 1
                    r.error = f"{type(e).__name__}: {e}"
                continue
            capacity = float(reading.get("capacity", 0.0) or 0.0)
            drops = float(reading.get("drops", 0.0) or 0.0)
            kind = str(reading.get("kind", "queue"))
            probe_hw = float(reading.get("highwater", 0.0) or 0.0)
            with self._lock:
                r.error = None
                r.kind = kind
                r.capacity = capacity
                # monotonic high water: fold the probe's own readout in,
                # never let either side reset it (satellite-6 pin)
                r.highwater = max(r.highwater, r.depth, depth, probe_hw)
                if r.last_t is not None:
                    dt = now - r.last_t
                    if dt > 0.0:
                        net = (depth - r.last_depth) / dt
                        drop_rate = max(drops - r.last_drops, 0.0) / dt
                        # dropped items were inflow that never raised
                        # depth: an overflowing queue pinned at its
                        # bound is still FILLING at the drop rate
                        fill = max(net, 0.0) + drop_rate
                        drain = max(-net, 0.0)
                        alpha = 1.0 - math.exp(-dt / self.tau_seconds)
                        r.fill_rate += alpha * (fill - r.fill_rate)
                        r.drain_rate += alpha * (drain - r.drain_rate)
                r.depth = depth
                r.drops = drops
                r.last_t = now
                r.last_depth = depth
                r.last_drops = drops
                r.observations += 1
                # the high-water episode edge (the SloTracker
                # _check_sustained shape): fire once when a queue-kind
                # resource crosses the fraction, re-arm on recovery
                if capacity > 0.0 and kind == "queue":
                    occ = depth / capacity
                    if occ >= self.high_water_fraction:
                        if not r.fired:
                            r.fired = True
                            r.episodes += 1
                            fire.append(self._row_locked(r))
                    else:
                        r.fired = False
        cap = self._capture
        if cap is not None:
            for row in fire:
                try:
                    # outside the lock: a capture walks profiler +
                    # contention state and must never serialize observe()
                    cap.capture(f"headroom-{row['resource']}",
                                resource=row["resource"],
                                occupancy=row["occupancy"],
                                depth=row["depth"],
                                capacity=row["capacity"],
                                fill_rate=row["fill_rate"],
                                seconds_to_exhaustion=row[
                                    "seconds_to_exhaustion"])
                except Exception:
                    pass   # evidence collection must not fail the sweep

    # ---- the forecast ------------------------------------------------------

    def _forecast_locked(self, r: _Resource) -> Optional[float]:
        """Seconds until ``depth`` reaches ``capacity`` at the current
        EWMA net fill. None = no exhaustion in sight: unbounded, a ring
        (full-by-design), or draining at least as fast as it fills."""
        if r.capacity <= 0.0 or r.kind != "queue":
            return None
        net = r.fill_rate - r.drain_rate
        if net <= MIN_NET_FILL:
            return None
        return max(r.capacity - r.depth, 0.0) / net

    def _row_locked(self, r: _Resource) -> Dict:
        tte = self._forecast_locked(r)
        occ = (r.depth / r.capacity) if r.capacity > 0.0 else 0.0
        burn = (occ / self.high_water_fraction
                if r.capacity > 0.0 and r.kind == "queue" else 0.0)
        return {
            "resource": r.name,
            "kind": r.kind,
            "depth": round(r.depth, 3),
            "capacity": round(r.capacity, 3),
            "highwater": round(r.highwater, 3),
            "drops": round(r.drops, 3),
            "fill_rate": round(r.fill_rate, 6),
            "drain_rate": round(r.drain_rate, 6),
            "occupancy": round(occ, 6),
            "burn": round(burn, 6),
            "seconds_to_exhaustion": (round(tte, 3)
                                      if tte is not None else None),
            "episodes": r.episodes,
            **({"error": r.error} if r.error else {}),
        }

    def read(self, name: str) -> Dict:
        """The latest observation of one resource — the registry-read
        seam the hand-maintained readouts folded into (the interruption
        queue-depth gauge, the karpenter_api_* queue gauges): the same
        number can never be reported two ways."""
        with self._lock:
            r = self._resources.get(name)
            if r is None:
                return {}
            return self._row_locked(r)

    def table(self) -> List[Dict]:
        """The ranked first-to-break table: finite time-to-exhaustion
        first (soonest break leads), then highest occupancy, then name —
        a stable total order so two polls of a quiet process agree."""
        with self._lock:
            rows = [self._row_locked(r) for r in self._resources.values()]

        def key(row):
            tte = row["seconds_to_exhaustion"]
            return (0 if tte is not None else 1,
                    tte if tte is not None else 0.0,
                    -row["occupancy"], row["resource"])

        return sorted(rows, key=key)

    # ---- surfaces ----------------------------------------------------------

    def stats(self) -> Dict:
        """The ``headroom`` introspection provider: summary numerics
        plus per-resource occupancy/depth keys so the sampler rings (and
        soak artifacts) carry the saturation trajectory for free."""
        self.observe()
        table = self.table()
        finite = [row for row in table
                  if row["seconds_to_exhaustion"] is not None]
        saturated = sum(1 for row in table
                        if row["kind"] == "queue" and row["capacity"] > 0
                        and row["depth"] >= row["capacity"])
        out: Dict = {
            "resources": float(len(table)),
            "probe_errors": float(self.probe_errors),
            "episodes": float(sum(row["episodes"] for row in table)),
            "saturated": float(saturated),
            "high_water_fraction": self.high_water_fraction,
            # -1 = nothing forecast to break (the JSON-safe infinity)
            "min_tte_seconds": (finite[0]["seconds_to_exhaustion"]
                                if finite else -1.0),
            "first_to_break": (finite[0]["resource"] if finite else ""),
        }
        for row in table:
            out[f"{row['resource']}_depth"] = row["depth"]
            out[f"{row['resource']}_occ"] = row["occupancy"]
            out[f"{row['resource']}_drops"] = row["drops"]
        return out

    def doc(self) -> Dict:
        """The /debug/headroom JSON document (both HTTP servers)."""
        self.observe()
        return {
            "enabled": True,
            "now": round(float(self._clock.now()), 3),
            "high_water_fraction": self.high_water_fraction,
            "tau_seconds": self.tau_seconds,
            "probe_errors": self.probe_errors,
            "resources": self.table(),
        }
