"""Cluster introspection layer (docs/reference/introspection.md).

A process-wide provider registry every stateful subsystem reports cheap
``stats()`` into, a bounded-ring sampler off the hot path, rolling SLO
burn tracking against the paper's 200 ms / 2% bars, and two debug
surfaces rendered by both the metrics server and the REST apiserver:

    GET /debug/statusz            human-readable subsystem state
    GET /debug/vars[?series=1]    machine-readable JSON (+ ring series)

``kpctl top`` renders /debug/vars as a live terminal view; tools/soak.py
and debug.Monitor persist the same snapshots as per-subsystem
time-series in soak artifacts.

Usage (subsystem side):

    from karpenter_provider_aws_tpu import introspect
    introspect.registry().register("my_subsystem", my_obj.stats)

The registry is process-wide and replace-by-name (a rebuilt Operator
re-registers over its predecessor); the sampler and SLO tracker are
per-Operator, with the most recent one published here for the HTTP
surfaces (`set_sampler`), mirroring how trace.enable() publishes the
flight recorder.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from . import contention
from .headroom import HeadroomRegistry
from .profiler import BurnCapture, SamplingProfiler
from .registry import IntrospectRegistry, StatsProvider
from .sampler import Sampler
from .slo import SloTracker

__all__ = [
    "IntrospectRegistry", "Sampler", "SloTracker", "StatsProvider",
    "SamplingProfiler", "BurnCapture", "HeadroomRegistry", "contention",
    "registry", "sampler", "set_sampler", "statusz_text", "vars_doc",
    "debug_doc", "profiler_instance", "set_profiler", "enable_profiling",
    "profiler_stats", "burn_capture", "set_burn_capture",
    "explain_ring", "set_explain_ring",
    "headroom_registry", "set_headroom",
]

_REGISTRY = IntrospectRegistry()
_SAMPLER: Optional[Sampler] = None
_PROFILER: Optional[SamplingProfiler] = None
_BURN_CAPTURE: Optional[BurnCapture] = None
_EXPLAIN = None   # solver/explain.py DecisionAuditRing
_HEADROOM: Optional[HeadroomRegistry] = None
_STARTED_AT = time.time()


def registry() -> IntrospectRegistry:
    """The process-wide provider registry."""
    return _REGISTRY


def sampler() -> Optional[Sampler]:
    """The most recently published Sampler (None before any Operator)."""
    return _SAMPLER


def set_sampler(s: Optional[Sampler]) -> None:
    global _SAMPLER
    _SAMPLER = s


# ---- the sampling profiler (docs/reference/profiling.md) ------------------

def profiler_instance() -> Optional[SamplingProfiler]:
    """The published whole-process sampling profiler, or None when
    profiling is off (the default — nothing is constructed, sampled, or
    allocated until ``enable_profiling``/``set_profiler``)."""
    return _PROFILER


def set_profiler(p: Optional[SamplingProfiler]) -> None:
    global _PROFILER
    _PROFILER = p


def enable_profiling(hz: float = 50.0) -> SamplingProfiler:
    """Construct, publish, and start the daemon sampler (the CLI's
    ``--profile``). Idempotent-ish: an already-published profiler is
    restarted rather than replaced (its aggregate survives)."""
    global _PROFILER
    if _PROFILER is None:
        _PROFILER = SamplingProfiler(hz=hz)
    return _PROFILER.start()


def profiler_stats() -> Dict:
    """The ``profiler`` introspection provider: stats when running, the
    explicit disabled marker otherwise (a provider must never be
    empty)."""
    p = _PROFILER
    return p.stats() if p is not None else {"enabled": 0.0}


def burn_capture() -> Optional[BurnCapture]:
    return _BURN_CAPTURE


def set_burn_capture(bc: Optional[BurnCapture]) -> None:
    global _BURN_CAPTURE
    _BURN_CAPTURE = bc


def explain_ring():
    """The published decision-audit ring (solver/explain.py
    DecisionAuditRing), or None before any Operator wired one — the
    store behind /debug/explain and `kpctl explain`."""
    return _EXPLAIN


def set_explain_ring(ring) -> None:
    global _EXPLAIN
    _EXPLAIN = ring


def headroom_registry() -> Optional[HeadroomRegistry]:
    """The published saturation observatory (introspect/headroom.py
    HeadroomRegistry), or None before any Operator wired one — the
    store behind /debug/headroom and `kpctl headroom`."""
    return _HEADROOM


def set_headroom(hr: Optional[HeadroomRegistry]) -> None:
    global _HEADROOM
    _HEADROOM = hr


# ---- the two debug documents ---------------------------------------------

def vars_doc(include_series: bool = False) -> Dict:
    """The /debug/vars JSON document: current stats per provider, plus
    (on request) the sampler's bounded ring series. Machine-readable —
    the backbone of kpctl top and the soak artifact."""
    doc: Dict = {
        "now": round(time.time(), 3),
        "uptimeSeconds": round(time.time() - _STARTED_AT, 1),
        "providers": _REGISTRY.collect(),
    }
    s = _SAMPLER
    if s is not None:
        doc["sampler"] = {"samples": s.samples_taken, "ring": s.ring}
        if include_series:
            doc["series"] = s.series()
    return doc


def statusz_text() -> str:
    """The /debug/statusz page: the same snapshot, for humans. Plain
    text — readable in a terminal (`curl .../debug/statusz`) without
    any tooling."""
    snap = _REGISTRY.collect()
    lines: List[str] = [
        "karpenter-tpu statusz",
        f"uptime: {time.time() - _STARTED_AT:.0f}s   "
        f"providers: {len(snap)}",
        "",
    ]
    if not snap:
        lines.append("(no providers registered yet — operator still "
                     "constructing)")
    for name in sorted(snap):
        stats = snap[name]
        lines.append(f"== {name} ==")
        if not stats:
            lines.append("  (empty)")
        for k in sorted(stats):
            v = stats[k]
            if isinstance(v, float):
                v = f"{v:g}"
            lines.append(f"  {k}: {v}")
        lines.append("")
    return "\n".join(lines) + "\n"


def debug_doc(path: str, query: Dict[str, List[str]]):
    """Route /debug/statusz, /debug/vars, and /debug/pprof/* for an
    HTTP handler.

    Returns ``(body_bytes, content_type)`` or None when the path is not
    ours — the same shape both kube/httpserver.py and cli.py mount next
    to the flight recorder's /debug/traces."""
    import json
    p = path.rstrip("/")
    if p == "/debug/statusz":
        return statusz_text().encode(), "text/plain; charset=utf-8"
    if p == "/debug/vars":
        series = query.get("series", ["0"])[0] in ("1", "true")
        return (json.dumps(vars_doc(include_series=series)).encode(),
                "application/json")
    if p == "/debug/explain":
        # the decision-audit surface (docs/reference/explain.md):
        # ?pod= / ?nodeclaim= / ?pass= look one decision up; bare GET
        # lists the ring. Served on BOTH HTTP servers like the rest.
        ring = _EXPLAIN
        doc = (ring.doc(query) if ring is not None
               else {"enabled": False,
                     "message": "no decision-audit ring published "
                                "(operator still constructing?)"})
        return json.dumps(doc).encode(), "application/json"
    if p == "/debug/headroom":
        # the saturation observatory (docs/reference/headroom.md): the
        # ranked first-to-break table of every bounded resource. Served
        # on BOTH HTTP servers like the rest.
        hr = _HEADROOM
        doc = (hr.doc() if hr is not None
               else {"enabled": False,
                     "message": "no headroom registry published "
                                "(operator still constructing?)"})
        return json.dumps(doc).encode(), "application/json"
    if p.startswith("/debug/pprof"):
        return _pprof_doc(p, query)
    return None


def _pprof_doc(p: str, query: Dict[str, List[str]]):
    """The profiling read surface (docs/reference/profiling.md):

        /debug/pprof/profile                folded collapsed stacks (text;
                                            the flamegraph.pl/speedscope
                                            input), ?format=json|chrome
        /debug/pprof/contention             lock/queue accounting (JSON)
        /debug/pprof/lockorder              lock acquisition-order graph +
                                            deadlock cycles w/ witness
                                            stacks (JSON)
        /debug/pprof/device                 device cost model (JSON)
        /debug/pprof/captures               burn-triggered snapshots (JSON)
    """
    import json

    def _json(doc):
        return json.dumps(doc).encode(), "application/json"

    if p == "/debug/pprof/profile":
        fmt = query.get("format", ["folded"])[0]
        prof = _PROFILER
        if prof is None:
            if fmt == "folded":
                return (b"# profiler disabled (--profile)\n",
                        "text/plain; charset=utf-8")
            return _json({"enabled": False})
        if fmt == "chrome":
            return _json(prof.to_chrome())
        if fmt == "json":
            try:
                n = min(max(int(query.get("n", ["40"])[0]), 1), 1000)
            except ValueError:
                n = 40
            return _json({**prof.stats(), "top": prof.top(n)})
        return prof.folded().encode(), "text/plain; charset=utf-8"
    if p == "/debug/pprof/contention":
        return _json(contention.detail())
    if p == "/debug/pprof/lockorder":
        return _json(contention.lockorder_detail())
    if p == "/debug/pprof/device":
        from ..solver import costmodel
        return _json(costmodel.model().summary())
    if p == "/debug/pprof/captures":
        bc = _BURN_CAPTURE
        return _json(bc.doc() if bc is not None else
                     {"captures": [], "total": 0})
    return None
