"""Whole-process wall-clock sampling profiler + burn-triggered capture.

The third leg of the attribution story: traces (trace/) explain ONE
request, ``/debug/vars`` explains current state, and this profiler
explains WHERE TIME GOES over an interval — which frames the write path
burns under 15k-pod API churn, whether the watch fan-out or the solver
decode owns the p99. Zero dependencies: a daemon thread samples
``sys._current_frames()`` at ``hz`` (default 50) and folds each
thread's stack into a bounded count store; the deterministic stratum
calls ``sample_once()`` under FakeClock instead.

Exports (served at ``/debug/pprof/profile`` on both the metrics server
and the REST apiserver; ``kpctl profile`` is the CLI):

- **folded / collapsed-stack text** — ``thread;root;..;leaf N`` lines,
  the flamegraph.pl / speedscope / `pprof -flame` input format,
- **Chrome trace-event JSON** — consecutive identical samples merged
  into B/E duration events per frame (the standard samples→spans
  reconstruction), loadable in Perfetto next to an xprof device trace,
- **top frames** — inclusive/self sample counts per frame.

Cost model: one sample walks every live thread's stack (~tens of µs for
a dozen threads); at 50 Hz that is well under 1% of one core, and the
profiler measures ITSELF (``avg_sample_ms`` / ``overhead_pct`` in
``stats()``) so the <5% bound is observable, not asserted. Disabled
(the default — nothing constructs a profiler unless ``--profile`` or a
harness does): zero threads, zero allocation, zero hooks anywhere on
the hot path — pinned by tests/test_profiler.py.

``BurnCapture`` is the flight-recorder analog for profiles: when the
SLO tracker sustains burn >= 1.0 (its exactly-once-per-episode edge) or
a pass grossly exceeds the latency budget, it snapshots the profile's
top frames + the contention accounting + the device cost model into a
bounded ring keyed to the episode — the 3 a.m. degradation ships with
its own evidence (``/debug/pprof/captures``).
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ..utils.clock import WALL
from . import contention

DEFAULT_HZ = 50.0
MAX_STACK_DEPTH = 48
MAX_UNIQUE_STACKS = 20_000   # bounded store: beyond this, samples count
                             # as dropped instead of growing memory
RAW_RING = 4096              # recent raw samples kept for Chrome export


def _norm_thread(name: str) -> str:
    """Bound thread-name cardinality: 'Thread-12 (run)' → 'Thread-N (run)'."""
    return "".join("N" if c.isdigit() else c for c in name)


class SamplingProfiler:
    """Aggregating wall-clock sampler over ``sys._current_frames()``.

    ``start()`` runs the daemon sampler; ``sample_once()`` serves the
    deterministic stratum (``clock`` — FakeClock — stamps the sample
    time; frame capture is real either way)."""

    def __init__(self, hz: float = DEFAULT_HZ, clock=None,
                 max_stacks: int = MAX_UNIQUE_STACKS,
                 max_depth: int = MAX_STACK_DEPTH,
                 raw_ring: int = RAW_RING):
        self.hz = max(float(hz), 0.1)
        self._clock = clock
        self.max_stacks = int(max_stacks)
        self.max_depth = int(max_depth)
        self._lock = threading.Lock()
        # folded stack ("thr;root;..;leaf") -> samples
        self._counts: Dict[str, int] = {}
        # (t, thread, frames-root-first) for the Chrome reconstruction
        self._raw: Deque[Tuple[float, str, Tuple[str, ...]]] = deque(
            maxlen=int(raw_ring))
        self.samples = 0
        self.dropped_stacks = 0
        self.started_at: Optional[float] = None
        self.sample_cost_s = 0.0      # self-measured profiler overhead
        # code-object -> "file.py:func" label memo: the per-frame string
        # build dominates sample cost; code objects are stable for the
        # process lifetime, so one format each bounds the work to dict
        # lookups (~5x cheaper per sample, measured)
        self._frame_labels: Dict[object, str] = {}
        # tid -> normalized thread name, rebuilt only when an unknown
        # tid appears (thread births are rare; per-sample
        # threading.enumerate() + re-normalization measured ~30% of the
        # whole sample cost)
        self._tid_names: Dict[int, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _now(self) -> float:
        return (self._clock.now() if self._clock is not None
                else WALL.now())

    # ---- sampling ---------------------------------------------------------

    def sample_once(self) -> int:
        """Sample every live thread once; returns threads sampled."""
        t0 = time.perf_counter()
        t = self._now()
        me = threading.get_ident()
        frames = sys._current_frames()
        n = 0
        labels = self._frame_labels
        names = self._tid_names
        if any(tid not in names for tid in frames):
            # a thread was born (or this is the first sample): refresh
            # the whole map once, then go back to pure dict lookups
            names = self._tid_names = {
                th.ident: _norm_thread(th.name)
                for th in threading.enumerate()}
        with self._lock:
            for tid, frame in frames.items():
                if tid == me:
                    continue   # never profile the sampler's own stack
                stack: List[str] = []
                depth = 0
                f = frame
                while f is not None and depth < self.max_depth:
                    co = f.f_code
                    label = labels.get(co)
                    if label is None:
                        if len(labels) > 4 * self.max_stacks:
                            labels.clear()   # runaway codegen bound
                        label = labels[co] = (
                            f"{co.co_filename.rsplit('/', 1)[-1]}"
                            f":{co.co_name}")
                    stack.append(label)
                    depth += 1
                    f = f.f_back
                stack.reverse()   # root-first, the folded convention
                thr = names.get(tid) or f"tid-{tid}"
                key = thr + ";" + ";".join(stack)
                if key in self._counts:
                    self._counts[key] += 1
                elif len(self._counts) < self.max_stacks:
                    self._counts[key] = 1
                else:
                    self.dropped_stacks += 1
                self._raw.append((t, thr, tuple(stack)))
                n += 1
            self.samples += 1
            if self.started_at is None:
                self.started_at = t
        self.sample_cost_s += time.perf_counter() - t0
        return n

    def start(self) -> "SamplingProfiler":
        if self._thread is not None and self._thread.is_alive():
            return self

        def run():
            interval = 1.0 / self.hz
            while not self._stop.is_set():
                try:
                    self.sample_once()
                except Exception:
                    pass   # the profiler must never die mid-run
                self._stop.wait(interval)
        self._stop.clear()
        self._thread = threading.Thread(target=run, name="sampling-profiler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._raw.clear()
            self.samples = 0
            self.dropped_stacks = 0
            self.started_at = None
            self.sample_cost_s = 0.0

    # ---- exports ----------------------------------------------------------

    def folded(self) -> str:
        """Collapsed-stack text: one ``stack count`` line per unique
        folded stack — flamegraph.pl / speedscope input."""
        with self._lock:
            items = sorted(self._counts.items())
        return "".join(f"{k} {v}\n" for k, v in items)

    def top(self, n: int = 20) -> List[Dict]:
        """Top frames by inclusive samples (+ self samples where the
        frame was the leaf)."""
        incl: Dict[str, int] = {}
        self_c: Dict[str, int] = {}
        with self._lock:
            items = list(self._counts.items())
        for key, count in items:
            frames = key.split(";")[1:]   # drop the thread prefix
            if not frames:
                continue
            for fr in set(frames):
                incl[fr] = incl.get(fr, 0) + count
            leaf = frames[-1]
            self_c[leaf] = self_c.get(leaf, 0) + count
        ranked = sorted(incl.items(), key=lambda kv: -kv[1])[:n]
        return [{"frame": fr, "inclusive": c, "self": self_c.get(fr, 0)}
                for fr, c in ranked]

    def to_chrome(self) -> Dict:
        """Chrome trace-event JSON from the raw sample ring: per thread,
        consecutive samples sharing a stack prefix merge into one
        complete ("X") event per frame — the flame chart renders the
        sampled timeline directly."""
        with self._lock:
            raw = list(self._raw)
        interval = 1.0 / self.hz
        by_thread: Dict[str, List[Tuple[float, Tuple[str, ...]]]] = {}
        for t, thr, stack in raw:
            by_thread.setdefault(thr, []).append((t, stack))
        events: List[Dict] = []
        tids = {}
        for thr, samples in sorted(by_thread.items()):
            tid = tids.setdefault(thr, len(tids) + 1)
            samples.sort(key=lambda s: s[0])
            open_frames: List[Tuple[str, float]] = []   # (frame, start)

            def close(depth: int, t_end: float):
                while len(open_frames) > depth:
                    fr, t_start = open_frames.pop()
                    events.append({
                        "name": fr, "ph": "X", "cat": "sample",
                        "ts": round(t_start * 1e6, 1),
                        "dur": round(max(t_end - t_start, interval) * 1e6, 1),
                        "pid": 1, "tid": tid,
                        "args": {"depth": len(open_frames)}})

            prev_t = None
            for t, stack in samples:
                if prev_t is not None and t - prev_t > 2 * interval:
                    close(0, prev_t + interval)   # gap: the thread idled
                common = 0
                for (fr, _), new in zip(open_frames, stack):
                    if fr != new:
                        break
                    common += 1
                close(common, t)
                for fr in stack[common:]:
                    open_frames.append((fr, t))
                prev_t = t
            if prev_t is not None:
                close(0, prev_t + interval)
            events.append({"ph": "M", "name": "thread_name", "pid": 1,
                           "tid": tid, "args": {"name": thr}})
        events.append({"ph": "M", "name": "process_name", "pid": 1,
                       "tid": 0, "args": {"name": "karpenter-tpu"}})
        return {"displayTimeUnit": "ms", "traceEvents": events}

    # ---- introspection ----------------------------------------------------

    def stats(self) -> Dict:
        with self._lock:
            unique = len(self._counts)
            # one "sample" is one sampling round over ALL threads; a
            # frame's inclusive count is per thread-stack — percentages
            # must divide by the thread-stack total, not the round count
            stack_samples = sum(self._counts.values())
        avg_ms = (self.sample_cost_s / self.samples * 1e3
                  if self.samples else 0.0)
        return {
            "enabled": 1.0,
            "hz": self.hz,
            "samples": self.samples,
            "stack_samples": stack_samples,
            "unique_stacks": unique,
            "dropped_stacks": self.dropped_stacks,
            "avg_sample_ms": round(avg_ms, 4),
            # self-measured: fraction of one core the sampler itself eats
            "overhead_pct": round(avg_ms * self.hz / 10.0, 3),
            "running": 1.0 if (self._thread is not None
                               and self._thread.is_alive()) else 0.0,
        }

    def headroom_probe(self) -> Dict[str, float]:
        """Unique-stack store occupancy (introspect/headroom.py): a
        queue-kind bound — exhausting it means NEW stacks stop being
        attributed (counted by the pre-existing ``dropped_stacks``),
        which is evidence loss, not retention policy."""
        with self._lock:
            unique = len(self._counts)
        return {"depth": float(unique), "capacity": float(self.max_stacks),
                "drops": float(self.dropped_stacks)}


# ---- burn-triggered capture -------------------------------------------------


class BurnCapture:
    """Bounded episode-keyed retention of profile+contention snapshots.

    Two triggers, both rate-limited by construction:

    - ``on_sustained_burn`` — wired to ``SloTracker.on_sustained``,
      which fires EXACTLY ONCE per sustained-burn episode and re-arms on
      recovery (introspect/slo.py): one capture per episode, for free.
    - ``note_latency`` — a single pass so far over budget
      (``slow_pass_factor`` x the 200 ms bar) captures immediately,
      re-armed only after a within-budget pass AND ``cooldown_seconds``
      — a stretch of slow passes yields one capture, not a capture
      storm.

    Retention is a ``deque(maxlen=retain)``: repeated episodes keep the
    newest N captures, flight-recorder style. Each capture carries the
    profiler's top frames + folded size, the contention top list, and
    the device cost model summary — enough to answer "what was the
    process doing" without shipping the whole profile.
    """

    def __init__(self, clock, retain: int = 8,
                 latency_budget_seconds: float = 0.200,
                 slow_pass_factor: float = 10.0,
                 cooldown_seconds: float = 60.0):
        self._clock = clock
        self._lock = threading.Lock()
        self.captures: Deque[Dict] = deque(maxlen=max(int(retain), 1))
        self.capture_count = 0
        self.latency_budget_seconds = latency_budget_seconds
        self.slow_pass_factor = slow_pass_factor
        self.cooldown_seconds = cooldown_seconds
        self._slow_armed = True
        self._last_slow_capture = float("-inf")

    def resize(self, retain: int) -> None:
        with self._lock:
            self.captures = deque(self.captures, maxlen=max(int(retain), 1))

    # -- triggers --

    def on_sustained_burn(self, kind: str, burn: float, detail: str) -> None:
        """SloTracker.on_sustained hook: one capture per episode."""
        self.capture(f"slo-{kind}-burn", burn=round(burn, 3), detail=detail)

    def note_latency(self, seconds: float) -> None:
        """Per-pass hook (SloTracker.record_latency): a grossly
        over-budget pass captures once, then re-arms only after a
        within-budget pass + cooldown."""
        threshold = self.latency_budget_seconds * self.slow_pass_factor
        now = self._clock.now()
        with self._lock:
            if seconds <= self.latency_budget_seconds:
                if now - self._last_slow_capture >= self.cooldown_seconds:
                    self._slow_armed = True
                return
            if seconds < threshold or not self._slow_armed:
                return
            self._slow_armed = False
            self._last_slow_capture = now
        self.capture("slow-pass",
                     pass_seconds=round(seconds, 4),
                     budget_seconds=self.latency_budget_seconds)

    # -- the capture itself --

    def capture(self, reason: str, **meta) -> Dict:
        snap: Dict = {
            "t": round(self._clock.now(), 3),
            "reason": reason,
            **meta,
        }
        try:
            from . import profiler_instance
            prof = profiler_instance()
            if prof is not None:
                snap["profile"] = {
                    "samples": prof.samples,
                    "top": prof.top(20),
                }
        except Exception:
            pass
        try:
            snap["contention"] = [
                {"lock": name, "waitP99Ms": round(p99 * 1e3, 3),
                 "contended": n}
                for name, p99, n in contention.top_waits(5)]
        except Exception:
            pass
        try:
            from ..solver import costmodel
            snap["device"] = costmodel.model().summary()
        except Exception:
            pass
        with self._lock:
            self.capture_count += 1
            snap["episode"] = self.capture_count
            self.captures.append(snap)
        return snap

    # -- reporting --

    def stats(self) -> Dict:
        with self._lock:
            last = self.captures[-1] if self.captures else None
            return {
                "retained": len(self.captures),
                "total": self.capture_count,
                "last_t": last["t"] if last else 0.0,
                **({"last_reason": last["reason"]} if last else {}),
            }

    def doc(self) -> Dict:
        with self._lock:
            return {"captures": list(self.captures),
                    "total": self.capture_count,
                    "retain": self.captures.maxlen}

    def headroom_probe(self) -> Dict[str, float]:
        """Capture-ring occupancy (introspect/headroom.py).
        ``kind="ring"`` — flight-recorder retention: keeping only the
        newest N episodes is the design, not loss."""
        with self._lock:
            depth = len(self.captures)
            return {"depth": float(depth),
                    "capacity": float(self.captures.maxlen or 0),
                    "drops": float(max(self.capture_count - depth, 0)),
                    "kind": "ring"}
