"""Sampler: bounded ring time-series over the provider registry.

One daemon thread (or on-demand ``sample_once()`` in the deterministic
stratum) collects every provider's stats on an interval and appends the
NUMERIC keys into a bounded per-provider ring. The rings are what
``/debug/vars?series=1`` serves and what the soak harness's Monitor
persists — per-subsystem series instead of ad-hoc counters.

Everything is bounded and off the hot path: subsystems never see the
sampler (they only expose ``stats()``), the rings are fixed-depth
deques, and a sampling failure is recorded, never raised.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

from ..utils.clock import WALL
from .registry import IntrospectRegistry

DEFAULT_RING = 600   # 10 min of 1 Hz samples per provider


class Sampler:
    def __init__(self, registry: IntrospectRegistry, ring: int = DEFAULT_RING,
                 clock=None):
        self.registry = registry
        self.ring = max(int(ring), 2)
        self._clock = clock          # None = wall clock (threaded strata)
        # provider -> deque[(t, {numeric stats})]; created lazily so a
        # provider registered mid-run starts recording at its next sample
        self._rings: Dict[str, Deque[Tuple[float, Dict[str, float]]]] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples_taken = 0
        self.started_at = self._now()

    def _now(self) -> float:
        return (self._clock.now() if self._clock is not None
                else WALL.now())

    # ---- sampling ---------------------------------------------------------

    def sample_once(self) -> Dict[str, Dict]:
        """Collect one snapshot and append its numeric keys to the rings.
        Returns the full (numeric + string) snapshot."""
        t = self._now()
        snap = self.registry.collect()
        with self._lock:
            for name, stats in snap.items():
                nums = {k: float(v) for k, v in stats.items()
                        if isinstance(v, (int, float))
                        and not isinstance(v, bool)}
                ring = self._rings.get(name)
                if ring is None:
                    ring = self._rings[name] = deque(maxlen=self.ring)
                ring.append((t, nums))
            self.samples_taken += 1
        return snap

    def start(self, interval: float = 1.0) -> "Sampler":
        if self._thread is not None and self._thread.is_alive():
            return self

        def run():
            while not self._stop.is_set():
                try:
                    self.sample_once()
                except Exception:
                    pass   # the sampler must never die mid-soak
                self._stop.wait(interval)
        self._stop.clear()
        self._thread = threading.Thread(target=run, name="introspect-sampler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None

    def headroom_probe(self) -> Dict[str, float]:
        """Ring occupancy (introspect/headroom.py): the FULLEST
        per-provider ring vs the shared depth. ``kind="ring"`` — a full
        ring is 10 minutes of history, exactly as designed."""
        with self._lock:
            fullest = max((len(r) for r in self._rings.values()), default=0)
        return {"depth": float(fullest), "capacity": float(self.ring),
                "kind": "ring"}

    # ---- series export ----------------------------------------------------

    def series(self) -> Dict[str, Dict]:
        """Columnar per-provider series: ``{provider: {"t": [...],
        "series": {key: [...]}}}``. A key absent from an early sample
        (counter added mid-run) backfills 0.0 so columns stay aligned."""
        with self._lock:
            rings = {name: list(ring) for name, ring in self._rings.items()}
        out: Dict[str, Dict] = {}
        for name, points in rings.items():
            keys: List[str] = sorted({k for _, nums in points for k in nums})
            out[name] = {
                "t": [round(t, 3) for t, _ in points],
                "series": {k: [nums.get(k, 0.0) for _, nums in points]
                           for k in keys},
            }
        return out
