"""Lock/queue contention accounting: instrumented locks for the hot path.

Traces (trace/) explain one request and the sampling profiler
(introspect/profiler.py) attributes CPU time to frames, but neither says
where threads BLOCK — which lock the watch fan-out serializes on, how
long a solve queues behind another caller, whether the ClusterState
mirror is a convoy under API-mode churn. ``InstrumentedLock`` wraps a
``threading.Lock``/``RLock`` with:

- **wait-time accounting** — only a CONTENDED acquire pays any timing:
  the fast path is one non-blocking ``acquire(False)`` plus two
  attribute writes, so an uncontended lock costs near-zero extra and
  records no samples,
- **hold-time accounting** — first-acquire to last-release (re-entrant
  RLock depth tracked), bucketed only when the hold exceeds
  ``HOLD_RECORD_SECONDS`` so steady microsecond holds never churn the
  histogram,
- **owner-at-contention tag** — a blocked waiter resolves the current
  owner's top frame via ``sys._current_frames()`` (only on contention,
  never on the fast path), so "who was holding it" ships with the wait,
- a process-wide **name-keyed registry**: every instance named
  ``"cluster_state"`` aggregates into one ``LockStats`` (tests build
  many Operators; stats must not leak one entry per instance).

Counters are plain int/float attribute updates under the GIL — a rare
lost increment under a true race is acceptable for diagnostics and the
alternative (a meta-lock inside every lock) is not. Everything reports
through ``stats()`` (the introspection registry's ``contention``
provider, flattened numeric keys for the sampler rings), ``detail()``
(the ``/debug/pprof/contention`` document, with owner tags), and the
``karpenter_lock_wait_seconds{lock}`` histogram when a metrics registry
is attached.

``set_enabled(False)`` turns every wrapper into a raw pass-through
(no counters, no clock reads) — the zero-overhead-when-disabled
contract tests/test_profiler.py pins.

**Lock-order witness** (docs/reference/linting.md): every FIRST
acquire also records, per thread, the set of instrumented locks
already held, feeding a process-wide acquisition-order graph — the
edge ``A -> B`` means "some thread held A while acquiring B", with the
acquiring thread's stack captured the first time the edge appears.
Any cycle in that graph is a POTENTIAL DEADLOCK (two threads can
interleave the two orders and wait on each other forever), reported
with every member edge's witness stack via ``lockorder_stats()`` (the
``lockorder`` introspection provider), ``lockorder_detail()``
(``/debug/pprof/lockorder``), and asserted empty as a standing
invariant by the threaded tier-1 tests, ``tools/soak.py``, and the
weather smoke. Edges are keyed by lock NAME (the same aggregation the
wait stats use): two locks sharing a name cannot witness an ordering
between themselves.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

# wait/hold bucket upper bounds, SECONDS (percentile estimates mirror
# metrics.Histogram: first bucket whose cumulative count crosses q).
# 50 ms sits between the old 20 ms and 100 ms bounds: scheduler-noise
# tails (a preempted lock holder under CPU saturation) and genuine
# convoy waits straddle exactly that range, and a p99 quantized to one
# shared 100 ms bucket could not rank them (the SOAK_r08 contention
# acceptance needed the resolution).
BUCKETS = (0.00005, 0.0002, 0.001, 0.005, 0.02, 0.05, 0.1, 0.5, 2.0, 10.0)
HOLD_RECORD_SECONDS = 0.0001   # holds under 100 µs: totals only, no bucket
OWNER_TAGS_MAX = 8             # distinct owner-at-contention sites kept

_enabled = True
_reg_lock = threading.Lock()
_registry: Dict[str, "LockStats"] = {}
_metric_hist = None            # karpenter_lock_wait_seconds, when attached

# ---- lock-order witness state ----
_WITNESS_STACK_LIMIT = 18      # frames kept per edge witness
_tls = threading.local()       # .held: this thread's held lock names,
                               # in acquisition order
_order_lock = threading.Lock()
# (held_name, acquired_name) -> {"count": int, "stack": [str, ...]}
_order_edges: Dict[Tuple[str, str], Dict] = {}


def set_enabled(flag: bool) -> None:
    """Process-wide kill switch: False makes every InstrumentedLock a
    raw pass-through (no counters, no perf_counter calls)."""
    global _enabled
    _enabled = bool(flag)


def enabled() -> bool:
    return _enabled


def attach_metrics(histogram) -> None:
    """Attach the ``karpenter_lock_wait_seconds{lock}`` histogram (the
    most recent Operator's registry wins, like the published sampler).
    Observed only on contention — the uncontended path never sees it."""
    global _metric_hist
    _metric_hist = histogram


def reset() -> None:
    """Drop all accumulated stats (test isolation)."""
    with _reg_lock:
        _registry.clear()
    lockorder_reset()


def _stats_for(name: str) -> "LockStats":
    with _reg_lock:
        ls = _registry.get(name)
        if ls is None:
            ls = _registry[name] = LockStats(name)
        return ls


class LockStats:
    """Aggregated accounting for every lock sharing one name."""

    __slots__ = ("name", "acquisitions", "contended", "wait_total_s",
                 "max_wait_s", "wait_buckets", "hold_total_s", "max_hold_s",
                 "hold_buckets", "holds", "owner_tags",
                 "qwaits", "qwait_total_s", "max_qwait_s")

    def __init__(self, name: str):
        self.name = name
        self.acquisitions = 0
        self.contended = 0
        self.wait_total_s = 0.0
        self.max_wait_s = 0.0
        self.wait_buckets = [0] * (len(BUCKETS) + 1)
        self.holds = 0
        self.hold_total_s = 0.0
        self.max_hold_s = 0.0
        self.hold_buckets = [0] * (len(BUCKETS) + 1)
        # owner-site -> times seen at contention (bounded)
        self.owner_tags: Dict[str, int] = {}
        # condition-variable wait (queue wait, e.g. a watcher parked for
        # its next event): kept SEPARATE from lock-wait so idle consumer
        # time never reads as lock contention
        self.qwaits = 0
        self.qwait_total_s = 0.0
        self.max_qwait_s = 0.0

    @staticmethod
    def _bucket_idx(seconds: float) -> int:
        for i, b in enumerate(BUCKETS):
            if seconds <= b:
                return i
        return len(BUCKETS)

    def note_wait(self, seconds: float, owner_tag: Optional[str]) -> None:
        self.contended += 1
        self.wait_total_s += seconds
        if seconds > self.max_wait_s:
            self.max_wait_s = seconds
        self.wait_buckets[self._bucket_idx(seconds)] += 1
        if owner_tag and (owner_tag in self.owner_tags
                          or len(self.owner_tags) < OWNER_TAGS_MAX):
            self.owner_tags[owner_tag] = self.owner_tags.get(owner_tag, 0) + 1
        h = _metric_hist
        if h is not None:
            try:
                h.observe(seconds, lock=self.name)
            except Exception:
                pass   # a torn-down registry must not fail an acquire

    def note_hold(self, seconds: float) -> None:
        self.holds += 1
        self.hold_total_s += seconds
        if seconds > self.max_hold_s:
            self.max_hold_s = seconds
        if seconds >= HOLD_RECORD_SECONDS:
            self.hold_buckets[self._bucket_idx(seconds)] += 1

    def note_qwait(self, seconds: float) -> None:
        self.qwaits += 1
        self.qwait_total_s += seconds
        if seconds > self.max_qwait_s:
            self.max_qwait_s = seconds

    @staticmethod
    def _percentile(buckets: List[int], q: float) -> float:
        total = sum(buckets)
        if total == 0:
            return 0.0
        target = q * total
        cum = 0
        for i, n in enumerate(buckets):
            cum += n
            if cum >= target:
                return BUCKETS[i] if i < len(BUCKETS) else BUCKETS[-1] * 2
        return BUCKETS[-1] * 2

    def wait_p99_s(self) -> float:
        return self._percentile(self.wait_buckets, 0.99)

    def hold_p99_s(self) -> float:
        return self._percentile(self.hold_buckets, 0.99)

    def flat(self) -> Dict[str, float]:
        """Numeric keys for the introspection provider / sampler rings."""
        out = {
            f"{self.name}_acquisitions": self.acquisitions,
            f"{self.name}_contended": self.contended,
            f"{self.name}_wait_total_ms": round(self.wait_total_s * 1e3, 3),
            f"{self.name}_wait_p99_ms": round(self.wait_p99_s() * 1e3, 3),
            f"{self.name}_max_wait_ms": round(self.max_wait_s * 1e3, 3),
            f"{self.name}_max_hold_ms": round(self.max_hold_s * 1e3, 3),
        }
        if self.qwaits:
            out[f"{self.name}_qwait_total_ms"] = round(
                self.qwait_total_s * 1e3, 3)
            out[f"{self.name}_max_qwait_ms"] = round(self.max_qwait_s * 1e3, 3)
        return out

    def doc(self) -> Dict:
        """Full per-lock document (/debug/pprof/contention)."""
        return {
            "acquisitions": self.acquisitions,
            "contended": self.contended,
            "waitTotalMs": round(self.wait_total_s * 1e3, 3),
            "waitP99Ms": round(self.wait_p99_s() * 1e3, 3),
            "maxWaitMs": round(self.max_wait_s * 1e3, 3),
            "holdTotalMs": round(self.hold_total_s * 1e3, 3),
            "holdP99Ms": round(self.hold_p99_s() * 1e3, 3),
            "maxHoldMs": round(self.max_hold_s * 1e3, 3),
            "ownersAtContention": dict(sorted(
                self.owner_tags.items(), key=lambda kv: -kv[1])),
            **({"queueWaits": self.qwaits,
                "queueWaitTotalMs": round(self.qwait_total_s * 1e3, 3),
                "maxQueueWaitMs": round(self.max_qwait_s * 1e3, 3)}
               if self.qwaits else {}),
        }


# ---- lock-order witness ----------------------------------------------------


def _held_list() -> List[str]:
    lst = getattr(_tls, "held", None)
    if lst is None:
        lst = _tls.held = []
    return lst


def _witness_stack() -> List[str]:
    """The acquiring thread's stack as ``file.py:line:func`` frames —
    captured ONCE per distinct edge, never on the steady path."""
    frames = traceback.extract_stack(limit=_WITNESS_STACK_LIMIT + 3)
    out = []
    for fr in frames:
        fname = fr.filename.rsplit("/", 1)[-1]
        if fname == "contention.py":
            continue   # the witness's own frames add no evidence
        out.append(f"{fname}:{fr.lineno}:{fr.name}")
    return out[-_WITNESS_STACK_LIMIT:]


def _note_first_acquire(name: str) -> None:
    """Record ordering edges held->name for every lock this thread
    already holds, then push name onto the thread's held list. Fast
    path per acquire: one thread-local read + a loop over the (almost
    always 0-2 entry) held list + dict membership checks; the stack
    capture and graph lock are paid only the first time an edge is
    seen process-wide."""
    held = _held_list()
    for h in held:
        if h == name:
            continue   # same-name pair (e.g. two per-kind store locks)
        pair = (h, name)
        e = _order_edges.get(pair)
        if e is not None:
            e["count"] += 1    # GIL-atomic enough for diagnostics
            continue
        stack = _witness_stack()
        with _order_lock:
            e = _order_edges.get(pair)
            if e is None:
                _order_edges[pair] = {"count": 1, "stack": stack}
            else:
                e["count"] += 1
    held.append(name)


def _note_last_release(name: str) -> None:
    held = getattr(_tls, "held", None)
    if held:
        # LIFO in the common case; tolerate out-of-order releases (and
        # entries stranded by an enable-toggle mid-hold) by scanning
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return


def lockorder_reset() -> None:
    """Drop the acquisition-order graph (test isolation — the
    deliberate lock-inversion test must not poison later no-cycle
    assertions)."""
    with _order_lock:
        _order_edges.clear()


def lockorder_cycles() -> List[List[str]]:
    """Elementary cycles in the acquisition-order graph, each as the
    list of lock names in order (first repeated implicitly). Empty =
    no potential deadlock witnessed. Each cycle is enumerated once,
    anchored at its lexicographically-smallest member."""
    with _order_lock:
        edges = list(_order_edges.keys())
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
    for vs in adj.values():
        vs.sort()
    cycles: List[List[str]] = []
    for start in sorted(adj):
        # DFS restricted to nodes >= start: every elementary cycle is
        # found exactly once, rooted at its smallest node
        path = [start]
        on_path = {start}

        def dfs(node: str) -> None:
            for nxt in adj.get(node, ()):
                if nxt == start:
                    cycles.append(list(path))
                elif nxt > start and nxt not in on_path:
                    path.append(nxt)
                    on_path.add(nxt)
                    dfs(nxt)
                    on_path.discard(nxt)
                    path.pop()

        dfs(start)
    return cycles


def lockorder_stats() -> Dict[str, float]:
    """The ``lockorder`` introspection provider: flat numeric keys for
    the sampler rings and the kpctl top LOCKORDER cell."""
    with _order_lock:
        edges = len(_order_edges)
        acquisitions = sum(e["count"] for e in _order_edges.values())
    return {"edges": float(edges),
            "cycles": float(len(lockorder_cycles())),
            "ordered_acquires": float(acquisitions),
            "enabled": 1.0 if _enabled else 0.0}


def lockorder_detail() -> Dict:
    """The /debug/pprof/lockorder document: the full acquisition-order
    graph with per-edge counts and first-witness stacks, plus every
    cycle with ALL of its member edges' witness stacks — the two (or
    more) code paths that can deadlock each other, named."""
    with _order_lock:
        edges = {f"{a} -> {b}": {"count": e["count"], "stack": e["stack"]}
                 for (a, b), e in sorted(_order_edges.items())}
        raw = dict(_order_edges)
    cycles = []
    for cyc in lockorder_cycles():
        members = []
        for i, a in enumerate(cyc):
            b = cyc[(i + 1) % len(cyc)]
            e = raw.get((a, b), {"count": 0, "stack": []})
            members.append({"edge": f"{a} -> {b}", "count": e["count"],
                            "stack": e["stack"]})
        cycles.append({"locks": cyc, "edges": members})
    return {"enabled": _enabled, "edges": edges, "cycles": cycles}


def _owner_frame_tag(tid: Optional[int]) -> Optional[str]:
    """The owner thread's top frame, ``file.py:func`` — resolved ONLY on
    contention (sys._current_frames walks every thread)."""
    if not tid:
        return None
    try:
        frame = sys._current_frames().get(tid)
        if frame is None:
            return None
        co = frame.f_code
        fname = co.co_filename.rsplit("/", 1)[-1]
        return f"{fname}:{co.co_name}"
    except Exception:
        return None


class InstrumentedLock:
    """A named Lock/RLock wrapper with contention accounting.

    Drop-in for ``with``-style use plus explicit acquire/release and
    ``threading.Condition`` interop (``_is_owned``). Re-entrant iff the
    wrapped lock is an RLock; hold time spans first acquire → matching
    last release."""

    __slots__ = ("_raw", "_stats", "_owner", "_depth", "_t_acq")

    def __init__(self, name: str, raw=None):
        self._raw = raw if raw is not None else threading.Lock()
        self._stats = _stats_for(name)
        self._owner: Optional[int] = None
        self._depth = 0
        self._t_acq = 0.0

    # -- lock protocol --

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not _enabled:
            return self._raw.acquire(blocking, timeout)
        st = self._stats
        if self._raw.acquire(False):
            ok = True
        elif not blocking:
            return False
        else:
            # contended: the only path that pays timing + owner lookup
            tag = _owner_frame_tag(self._owner)
            t0 = time.perf_counter()
            ok = self._raw.acquire(True, timeout)
            if ok:
                st.note_wait(time.perf_counter() - t0, tag)
        if not ok:
            return False
        # we hold the lock: owner bookkeeping is race-free (re-entrant
        # RLock acquires land here with _owner already == us)
        me = threading.get_ident()
        if self._owner == me:
            self._depth += 1
        else:
            self._owner = me
            self._depth = 1
            self._t_acq = time.perf_counter()
            # lock-order witness: a FIRST acquire while other locks are
            # held records an ordering edge (re-entrant re-acquires are
            # not an ordering event)
            _note_first_acquire(st.name)
        st.acquisitions += 1
        return True

    def release(self) -> None:
        if not _enabled:
            self._raw.release()
            return
        if self._owner == threading.get_ident() and self._depth == 1:
            # last matching release: the hold ends now
            self._stats.note_hold(time.perf_counter() - self._t_acq)
            self._owner = None
            self._depth = 0
            _note_last_release(self._stats.name)
        elif self._depth > 0:
            self._depth -= 1
        self._raw.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def _is_owned(self) -> bool:
        """threading.Condition interop — answer from our owner tracking
        instead of letting Condition probe with acquire(False) (which
        would count phantom acquisitions)."""
        if _enabled:
            return self._owner == threading.get_ident()
        o = getattr(self._raw, "_is_owned", None)
        if o is not None:
            return o()
        if self._raw.acquire(False):
            self._raw.release()
            return False
        return True

    @property
    def stats(self) -> LockStats:
        return self._stats


class InstrumentedCondition(threading.Condition):
    """A Condition over an InstrumentedLock whose ``wait()`` time is
    accounted as QUEUE wait (``qwait`` keys) — time a consumer parked
    for a producer, e.g. a watch subscriber awaiting its next event —
    kept apart from lock-wait so idle parking never reads as lock
    contention."""

    def __init__(self, name: str):
        self._ilock = InstrumentedLock(name)
        super().__init__(lock=self._ilock)

    def wait(self, timeout: Optional[float] = None) -> bool:
        if not _enabled:
            return super().wait(timeout)
        t0 = time.perf_counter()
        try:
            return super().wait(timeout)
        finally:
            self._ilock.stats.note_qwait(time.perf_counter() - t0)


def lock(name: str) -> InstrumentedLock:
    """An instrumented non-reentrant lock."""
    return InstrumentedLock(name, threading.Lock())


def rlock(name: str) -> InstrumentedLock:
    """An instrumented re-entrant lock."""
    return InstrumentedLock(name, threading.RLock())


def condition(name: str) -> InstrumentedCondition:
    return InstrumentedCondition(name)


# ---- reporting -------------------------------------------------------------


def stats() -> Dict[str, float]:
    """The introspection provider: flattened numeric keys per lock
    (``<lock>_wait_p99_ms`` etc. — what `kpctl top`'s CONTENTION row and
    the sampler rings consume)."""
    with _reg_lock:
        entries = sorted(_registry.items())
    out: Dict[str, float] = {"locks": len(entries),
                             "enabled": 1.0 if _enabled else 0.0}
    for _, ls in entries:
        out.update(ls.flat())
    return out


def detail() -> Dict:
    """The /debug/pprof/contention document: per-lock accounting with
    owner-at-contention tags."""
    with _reg_lock:
        entries = sorted(_registry.items())
    return {"enabled": _enabled,
            "locks": {name: ls.doc() for name, ls in entries}}


def top_waits(n: int = 3) -> List[Tuple[str, float, int]]:
    """Top-N locks by wait p99: (name, p99_seconds, contended).
    Bucketed p99s tie often; contended count breaks the tie (at equal
    p99 the lock more threads actually blocked on ranks worse) — the
    ordering is deterministic instead of registry-insertion order."""
    with _reg_lock:
        entries = list(_registry.values())
    ranked = sorted(((ls.name, ls.wait_p99_s(), ls.contended)
                     for ls in entries if ls.contended),
                    key=lambda t: (-t[1], -t[2]))
    return ranked[:n]
