"""Prometheus-style metrics registry.

Mirror of the reference's metric surface (reference website
reference/metrics.md catalog; pkg/providers/instancetype/metrics.go;
batcher metrics): counters, gauges, and histograms with label sets,
rendered in the Prometheus text exposition format. Series names follow the
reference catalog (karpenter_*) so dashboards port over.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                   2.5, 5.0, 10.0, 30.0, 60.0)


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(f"{self.name}: labels {sorted(labels)} != declared {sorted(self.labelnames)}")
        return tuple(str(labels[k]) for k in self.labelnames)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help, labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        k = self._key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def _render(self) -> List[str]:
        with self._lock:
            return [f"{self.name}{_fmt(self.labelnames, k)} {v}"
                    for k, v in sorted(self._values.items())]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help, labelnames=()):
        super().__init__(name, help, labelnames)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        k = self._key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(self._key(labels), 0.0)

    def clear(self) -> None:
        with self._lock:
            self._values.clear()

    def replace(self, values: Dict[Tuple[str, ...], float]) -> None:
        """Atomically swap the whole series set. For bulk snapshot surfaces
        (the lattice offering gauges) where per-cell set() calls would pay
        label validation ~10k times per refresh."""
        n = len(self.labelnames)
        for k in values:
            if len(k) != n:
                raise ValueError(
                    f"{self.name}: key {k!r} has {len(k)} labels, "
                    f"declared {n}")
        with self._lock:
            self._values = {tuple(map(str, k)): float(v)
                            for k, v in values.items()}

    def _render(self) -> List[str]:
        with self._lock:
            return [f"{self.name}{_fmt(self.labelnames, k)} {v}"
                    for k, v in sorted(self._values.items())]


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help, labelnames=(), buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}
        # last exemplar per series: (trace_id, observed value). The
        # OpenMetrics bridge between a histogram's aggregate shape and
        # ONE concrete retained trace in the flight recorder
        # (docs/reference/tracing.md) — a dashboard's slow bucket links
        # to `kpctl trace export <trace_id>`.
        self._exemplars: Dict[Tuple[str, ...], Tuple[str, float]] = {}

    def observe(self, value: float, exemplar: Optional[str] = None,
                **labels) -> None:
        k = self._key(labels)
        with self._lock:
            counts = self._counts.setdefault(k, [0] * len(self.buckets))
            # cumulative buckets: every upper bound >= value increments
            for j in range(bisect_left(self.buckets, value), len(self.buckets)):
                counts[j] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._totals[k] = self._totals.get(k, 0) + 1
            if exemplar is not None:
                self._exemplars[k] = (str(exemplar), float(value))

    def exemplar(self, **labels) -> Optional[Tuple[str, float]]:
        """The series' last (trace_id, value) exemplar, if any."""
        with self._lock:
            return self._exemplars.get(self._key(labels))

    def count(self, **labels) -> int:
        with self._lock:
            return self._totals.get(self._key(labels), 0)

    def sum(self, **labels) -> float:
        with self._lock:
            return self._sums.get(self._key(labels), 0.0)

    def percentile(self, q: float, **labels) -> float:
        """Approximate percentile from bucket counts (upper-bound estimate)."""
        k = self._key(labels)
        with self._lock:
            total = self._totals.get(k, 0)
            counts = self._counts.get(k, [0] * len(self.buckets))
        if total == 0:
            return 0.0
        target = q * total
        for j, b in enumerate(self.buckets):
            if counts[j] >= target:
                return b
        return self.buckets[-1]

    def _render(self) -> List[str]:
        out = []
        with self._lock:
            for k in sorted(self._totals):
                for j, b in enumerate(self.buckets):
                    lbl = _fmt(self.labelnames + ("le",), k + (repr(b),))
                    out.append(f"{self.name}_bucket{lbl} {self._counts[k][j]}")
                lbl = _fmt(self.labelnames + ("le",), k + ("+Inf",))
                out.append(f"{self.name}_bucket{lbl} {self._totals[k]}")
                # exemplar as a COMMENT line: this surface serves the
                # classic text format (text/plain; version=0.0.4), where
                # an OpenMetrics `# {...}` suffix on the sample line
                # would fail the whole scrape — comment lines are
                # ignored by every classic parser, and series without
                # an exemplar render byte-identically to before
                ex = self._exemplars.get(k)
                if ex is not None:
                    out.append(f'# exemplar {self.name}_bucket{lbl} '
                               f'{{trace_id="{ex[0]}"}} {ex[1]}')
                out.append(f"{self.name}_sum{_fmt(self.labelnames, k)} {self._sums[k]}")
                out.append(f"{self.name}_count{_fmt(self.labelnames, k)} {self._totals[k]}")
        return out


def _fmt(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{v}"' for n, v in zip(names, values))
    return "{" + inner + "}"


class Registry:
    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_make(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_make(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "", labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help, labelnames, buckets)
                self._metrics[name] = m
            elif not isinstance(m, Histogram):
                raise ValueError(f"{name} already registered as {m.kind}")
            return m

    def _get_or_make(self, cls, name, help, labelnames):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, labelnames)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise ValueError(f"{name} already registered as {m.kind}")
            return m

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def render(self) -> str:
        """Prometheus text exposition format."""
        lines: List[str] = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m._render())
        return "\n".join(lines) + "\n"


# The well-known series (reference website reference/metrics.md) — created
# on a registry by wire_core_metrics so every deployment exposes the same
# names the reference's dashboards scrape.
def wire_core_metrics(reg: Registry) -> Dict[str, _Metric]:
    return {
        "cloudprovider_duration": reg.histogram(
            "karpenter_cloudprovider_duration_seconds",
            "Duration of cloud provider method calls.", ("controller", "method")),
        "cloudprovider_errors": reg.counter(
            "karpenter_cloudprovider_errors_total",
            "Total number of errors returned from CloudProvider calls.",
            ("controller", "method", "error")),
        "scheduling_duration": reg.histogram(
            "karpenter_provisioner_scheduling_duration_seconds",
            "Duration of one scheduling pass (Solve).", ()),
        "scheduling_simulation_duration": reg.histogram(
            "karpenter_provisioner_scheduling_simulation_duration_seconds",
            "Device solve time inside a scheduling pass.", ()),
        "batch_size": reg.histogram(
            "karpenter_provisioner_batch_size",
            "Pending pods per scheduling batch.", (),
            buckets=(1, 5, 10, 50, 100, 500, 1000, 5000, 10000, 50000)),
        "pods_scheduled": reg.counter(
            "karpenter_pods_scheduled_total",
            "Pods placed by the provisioner (scheduling decisions: "
            "direct binds count on success; nominations to pending "
            "claims count at decision time).", ()),
        "pods_unschedulable": reg.gauge(
            "karpenter_pods_unschedulable",
            "Pods the last scheduling pass could not place.", ()),
        # every pod in exactly one phase (state/cluster.py
        # pod_phase_counts): bound | pending | nominated | deleting —
        # refreshed by the state sync pump and after every provisioning
        # pass, so the /metrics view of pod state matches /debug/statusz
        # the decision-explainability surface (solver/explain.py,
        # docs/reference/explain.md): WHY pods are pending, as bounded
        # taxonomy codes (solver/taxonomy.py), and how many offerings
        # each constraint stage eliminated per pass
        "pods_unschedulable_reasons": reg.counter(
            "karpenter_pods_unschedulable_reasons_total",
            "Unschedulable pod observations per scheduling pass, by "
            "structured reason code (unknown-resource | no-offering | "
            "ice-hold | zone-anti-affinity | no-fit | no-existing-fit | "
            "no-new-node-shape | single-bin-full | affinity-presence | "
            "pool-limits | solve-error | uncoded).", ("code",)),
        "explain_eliminations": reg.counter(
            "karpenter_explain_offering_eliminations_total",
            "Offerings removed from signature groups' candidate sets by "
            "each constraint-elimination stage, summed per pass (stage: "
            "resource-fit | requirements | pools | ice | narrowing).",
            ("stage",)),
        "pods_state": reg.gauge(
            "karpenter_pods_state",
            "Pods tracked by cluster state, by phase (bound | pending | "
            "nominated | deleting).", ("phase",)),
        # info-style gauge (value always 1; the payload is the labels) —
        # the standard *_build_info pattern dashboards join on
        "build_info": reg.gauge(
            "karpenter_build_info",
            "Build/runtime info (constant 1; labels carry the payload).",
            ("version", "jax_version", "backend")),
        # rolling SLO burn against the paper's bars
        # (introspect/slo.py): >1.0 means the window is violating
        # the 200 ms p50 latency / 2% FFD-referee cost budget
        "slo_latency_burn": reg.gauge(
            "karpenter_slo_latency_budget_burn",
            "Rolling-window p50 end-to-end provision latency over the "
            "200 ms budget (burn > 1.0 = out of SLO).", ()),
        "slo_cost_burn": reg.gauge(
            "karpenter_slo_cost_budget_burn",
            "Rolling-window solve cost regression vs the FFD referee "
            "over the 2% budget (burn > 1.0 = out of SLO).", ()),
        # the solver degradation ladder (docs/concepts/degradation.md):
        # device solve → wave-split → host FFD. Operators alarm on the
        # degraded counter; the wave histogram shows how often the group
        # axis overflows; the retry counter separates transient device
        # weather from real fallbacks.
        "solver_degraded": reg.counter(
            "karpenter_solver_degraded_total",
            "Scheduling passes that left the primary device-solve path, "
            "by degradation rung (path: wave-split | host-ffd | none) and "
            "reason (g-overflow | b-exhausted | device-error | "
            "internal-error | solve-error | sidecar-hung | "
            "sidecar-unreachable | pool-exhausted).", ("path", "reason")),
        "solver_device_retries": reg.counter(
            "karpenter_solver_device_retries_total",
            "Transient device-solve failures retried before any fallback "
            "engaged.", ()),
        # the steady-state incremental path (solver/incremental.py +
        # Solver.solve_delta): passes whose problem was patched from the
        # previous build and solved against device-resident input state
        # instead of a from-scratch rebuild + full upload
        "solver_delta_solves": reg.counter(
            "karpenter_solver_delta_solves_total",
            "Provisioning passes carried by the steady-state delta-solve "
            "path (incremental problem build + device-resident input "
            "delta).", ()),
        "solver_dirty_groups": reg.histogram(
            "karpenter_solver_dirty_group_count",
            "Signature groups whose membership changed per delta solve "
            "(the re-tensorized share of the problem).", (),
            buckets=(0, 1, 2, 4, 8, 16, 32, 64)),
        # host↔device link accounting (docs/reference/microloop.md): a
        # LEG is a transfer whose size scales with the problem or plan
        # (fused input uploads, dirty-block scatters, result fetches);
        # O(1) control syncs — the microloop's changed-plan fingerprint
        # — are excluded, because they cannot regress to full
        # re-staging. A steady-state microloop pass pays ≤2 legs (one
        # dirty upload, one CONDITIONAL plan fetch); a pass that
        # silently regresses to full re-staging shows up here without
        # waiting for a bench.
        "solver_link_legs": reg.counter(
            "karpenter_solver_link_legs_total",
            "Host-device link transfers on the solve path (direction: "
            "upload | fetch). Steady-state microloop passes are bounded "
            "at one dirty upload plus one conditional plan fetch.",
            ("direction",)),
        "solver_link_bytes": reg.counter(
            "karpenter_solver_link_bytes_total",
            "Bytes that crossed the host-device link on the solve path "
            "(direction: upload | fetch).", ("direction",)),
        # the mesh production path (parallel/mesh.py + docs/reference/
        # sharding.md): device count of the solver's mesh and the last
        # sharded solve's per-shard load balance. devices == 1 means the
        # single-device passthrough; imbalance is max/mean per-shard pod
        # load (1.0 = perfectly balanced; the round-robin whole-group
        # assignment and shard-0 pinning of need-groups show up here).
        "solver_mesh_devices": reg.gauge(
            "karpenter_solver_mesh_devices",
            "Devices in the solver's production mesh (1 = single-device "
            "path; >1 = the pod-axis sharded solve carries every pass).",
            ()),
        "solver_shard_imbalance": reg.gauge(
            "karpenter_solver_shard_imbalance_ratio",
            "Max/mean per-shard pod load of the last sharded solve's "
            "group split (1.0 = balanced; 0 until a sharded solve runs).",
            ()),
        # the solver failover pool (parallel/pool.py SolverPool;
        # docs/reference/solver-pool.md): endpoint count/health, the
        # cumulative failed-attempt counter, local final-rung solves,
        # and one breaker-state series per endpoint address. All zero /
        # absent without --solver-address.
        "solver_pool_endpoints": reg.gauge(
            "karpenter_solver_pool_endpoints",
            "Solver sidecar endpoints configured in the failover pool "
            "(0 = in-process solver, no pool).", ()),
        "solver_pool_healthy": reg.gauge(
            "karpenter_solver_pool_healthy_endpoints",
            "Pool endpoints whose circuit breaker is closed (routable "
            "for solves).", ()),
        "solver_pool_failovers": reg.gauge(
            "karpenter_solver_pool_failovers",
            "Cumulative failed endpoint attempts that fell through to "
            "another endpoint or the local rung (monotonic; mirrored "
            "from pool stats each gauge pass).", ()),
        "solver_pool_local_solves": reg.gauge(
            "karpenter_solver_pool_local_solves",
            "Cumulative passes the LOCAL solver carried because every "
            "pool endpoint was dark (degraded_reason=pool-exhausted).",
            ()),
        "solver_pool_breaker_state": reg.gauge(
            "karpenter_solver_pool_breaker_state",
            "Per-endpoint circuit breaker state (0 = closed, 1 = "
            "half-open probation, 2 = open).", ("endpoint",)),
        "solver_waves": reg.histogram(
            "karpenter_solver_wave_count",
            "Waves per scheduling solve (1 = one device pass; >1 = the "
            "group axis wave-split).", (),
            buckets=(1, 2, 4, 8, 16, 32, 64)),
        # per-stage share of the device solve (solver/pipeline.py STAGES)
        # — the observable proof that the pipelined path overlaps host
        # work with the in-flight device call: under overlap, "download"
        # (the residual blocking wait) shrinks while "build"/"upload"
        # stay constant (docs/concepts/performance.md "Pipelining & the
        # tunnel link")
        "solver_stage_duration": reg.histogram(
            "karpenter_solver_stage_duration_seconds",
            "Wall-clock share of one scheduling solve per pipeline stage "
            "(stage: build | upload | compute | download | decode).",
            ("stage",)),
        # the API stratum's write/fan-out surface (kube/apiserver.py;
        # docs/reference/watch.md) — set from FakeAPIServer.stats() each
        # gauge pass in API mode. Cumulative values are exposed as
        # gauges because they mirror a snapshot counter, like the other
        # stats()-backed series.
        "api_watchers": reg.gauge(
            "karpenter_api_watchers",
            "Active watch subscriptions on the apiserver's watch hub.", ()),
        "api_watch_queue_depth": reg.gauge(
            "karpenter_api_watch_queue_depth",
            "Queued (undelivered) watch events across all subscribers.", ()),
        "api_watch_max_depth": reg.gauge(
            "karpenter_api_watch_max_queue_depth",
            "Deepest single watcher queue at the last snapshot (the "
            "slow-consumer early-warning before the bound drops it).", ()),
        "api_watch_delivered": reg.gauge(
            "karpenter_api_watch_events_delivered",
            "Watch events delivered to subscriber queues (cumulative; "
            "shared-envelope delivery — no per-watcher copies).", ()),
        "api_watch_bookmarks": reg.gauge(
            "karpenter_api_watch_bookmarks",
            "BOOKMARK events sent to keep idle watchers' resume RVs "
            "fresh (cumulative).", ()),
        "api_watch_drops": reg.gauge(
            "karpenter_api_watch_drops",
            "Watch events discarded because a subscriber overran its "
            "bounded queue and was dropped to 410/relist (cumulative).",
            ()),
        "api_bulk_ops": reg.gauge(
            "karpenter_api_bulk_ops",
            "Write operations applied through the coalescing bulk verb "
            "(cumulative; one lock acquisition per kind per batch).", ()),
        "api_fanout_copies": reg.gauge(
            "karpenter_api_fanout_envelope_copies",
            "Per-watcher envelope copies made on the watch fan-out path "
            "(pinned 0: delivery shares one frozen envelope per RV).", ()),
        # the saturation observatory (introspect/headroom.py;
        # docs/reference/headroom.md): one row per registered bounded
        # resource, emitted via Gauge.replace each gauge pass so a
        # resource that unregisters disappears instead of flatlining
        "headroom_depth": reg.gauge(
            "karpenter_headroom_depth",
            "Current occupancy of a registered bounded resource, by "
            "resource.", ("resource",)),
        "headroom_capacity": reg.gauge(
            "karpenter_headroom_capacity",
            "Configured capacity of a registered bounded resource (0 = "
            "unbounded, forecast-only), by resource.", ("resource",)),
        "headroom_highwater": reg.gauge(
            "karpenter_headroom_highwater",
            "Process-monotonic high-water occupancy of a registered "
            "bounded resource (never resets on read or on structure "
            "churn), by resource.", ("resource",)),
        "headroom_drops": reg.gauge(
            "karpenter_headroom_drops",
            "Cumulative overflow/drop count of a registered bounded "
            "resource (mirrors the structure's own drop counter), by "
            "resource.", ("resource",)),
        "headroom_fill_rate": reg.gauge(
            "karpenter_headroom_fill_rate",
            "EWMA inflow pressure of a registered bounded resource in "
            "items/second (drops count as inflow), by resource.",
            ("resource",)),
        "headroom_tte": reg.gauge(
            "karpenter_headroom_seconds_to_exhaustion",
            "Forecast seconds until a queue-kind resource exhausts its "
            "capacity at the current EWMA net fill (-1 = no exhaustion "
            "in sight), by resource.", ("resource",)),
        # lock contention accounting (introspect/contention.py): wait to
        # acquire a hot control-plane lock, observed ONLY on contention
        # (the uncontended path records nothing). Labeled by lock name —
        # cluster_state, solver_solve, api_server, batcher_bucket,
        # solve_window, writer, flight_recorder, watch_event.
        "lock_wait": reg.histogram(
            "karpenter_lock_wait_seconds",
            "Time a thread blocked acquiring a contended control-plane "
            "lock, by lock.", ("lock",),
            buckets=(0.00005, 0.0002, 0.001, 0.005, 0.02, 0.05, 0.1, 0.5,
                     2.0)),
        # reference metrics.md:62,16,19
        "pods_startup_time": reg.histogram(
            "karpenter_pods_startup_time_seconds",
            "Seconds from pod arrival to its first bind.", (),
            # startup includes node launch + registration: minutes, not
            # the sub-minute default buckets
            buckets=(1.0, 5.0, 15.0, 30.0, 60.0, 120.0, 300.0,
                     600.0, 1800.0)),
        "nodepool_usage": reg.gauge(
            "karpenter_nodepool_usage",
            "Capacity committed per NodePool.",
            ("nodepool", "resource_type")),
        "nodepool_limit": reg.gauge(
            "karpenter_nodepool_limit",
            "The NodePool's spec.limits ceiling.",
            ("nodepool", "resource_type")),
        "nodeclaims_created": reg.counter(
            "karpenter_nodeclaims_created_total", "NodeClaims created.", ("nodepool",)),
        "nodeclaims_launched": reg.counter(
            "karpenter_nodeclaims_launched_total", "NodeClaims launched.", ("nodepool",)),
        "nodeclaims_registered": reg.counter(
            "karpenter_nodeclaims_registered_total", "NodeClaims registered.", ("nodepool",)),
        "nodeclaims_initialized": reg.counter(
            "karpenter_nodeclaims_initialized_total", "NodeClaims initialized.", ("nodepool",)),
        "nodeclaims_terminated": reg.counter(
            "karpenter_nodeclaims_terminated_total", "NodeClaims terminated.", ("nodepool",)),
        "nodeclaims_disrupted": reg.counter(
            "karpenter_nodeclaims_disrupted_total", "NodeClaims voluntarily disrupted.",
            ("nodepool", "reason")),
        # the vmapped consolidation engine (solver/consolidate.py;
        # docs/reference/consolidation.md): batched what-if dispatch,
        # zero-leg cache hits, host-ladder fallbacks, the FFD savings
        # referee, and the coded not-consolidated skip reasons
        "disruption_vmapped_whatifs": reg.counter(
            "karpenter_disruption_vmapped_whatifs_total",
            "Batched consolidation what-if dispatches (one vmapped probe "
            "kernel launch covering a whole candidate batch).", ()),
        "disruption_whatif_candidates": reg.counter(
            "karpenter_disruption_whatif_candidates_total",
            "Candidate removal sets evaluated by batched consolidation "
            "what-if dispatches.", ()),
        "disruption_whatif_cached": reg.counter(
            "karpenter_disruption_whatif_cached_total",
            "Candidate removal sets served from the fingerprint-unchanged "
            "delta cache at zero device sync legs.", ()),
        "disruption_whatif_host_fallbacks": reg.counter(
            "karpenter_disruption_whatif_host_fallbacks_total",
            "Candidate removal sets outside the vmapped envelope "
            "(wave-scale G, pinned groups on a mesh) evaluated on the "
            "host what-if ladder instead.", ()),
        "disruption_consolidation_skips": reg.counter(
            "karpenter_disruption_consolidation_skips_total",
            "Nodes skipped by the consolidation engine, by coded reason "
            "(solver/taxonomy.py: not-consolidatable-pdb | "
            "not-consolidatable-budget | consolidation-no-savings | "
            "consolidation-weather-hold | consolidation-spot-guard).",
            ("code",)),
        "disruption_consolidation_savings": reg.gauge(
            "karpenter_disruption_consolidation_savings_per_hour",
            "Cumulative accepted consolidation savings in $/hr (removed "
            "capacity price minus replacement price, summed over accepted "
            "removals).", ()),
        "interruption_received": reg.counter(
            "karpenter_interruption_received_messages_total",
            "Interruption queue messages received.", ("message_type",)),
        "interruption_deleted": reg.counter(
            "karpenter_interruption_deleted_messages_total",
            "Interruption queue messages deleted.", ()),
        "interruption_actions": reg.counter(
            "karpenter_interruption_actions_performed_total",
            "Node drain actions taken for interruption messages.", ("action",)),
        # robustness surface (interruption/controller.py): every body the
        # controller pulled, by parsed kind — malformed/unknown bodies are
        # counted and dropped, never crash the controller loop (kind:
        # spot-interruption | rebalance-recommendation | scheduled-change |
        # state-change | noop | malformed)
        "interruption_messages": reg.counter(
            "karpenter_interruption_messages_total",
            "Interruption queue messages processed, by parsed kind "
            "(malformed bodies count under kind=\"malformed\" and are "
            "dropped without crashing the controller).", ("kind",)),
        "interruption_queue_depth": reg.gauge(
            "karpenter_interruption_queue_depth",
            "Messages currently in the interruption queue (sent, not yet "
            "deleted) at the last reconcile.", ()),
        # the adversarial weather simulator (weather/; docs/reference/
        # weather.md): live scenario state while a --weather soak or the
        # CI squall smoke drives the control plane
        "weather_storm_active": reg.gauge(
            "karpenter_weather_storm_active",
            "Interruption storms currently active in the weather "
            "scenario (0 = fair weather).", ()),
        "weather_ice_pools": reg.gauge(
            "karpenter_weather_ice_pools",
            "Offerings currently held out of capacity by the weather "
            "simulator's ICE field.", ()),
        "weather_spot_mult_mean": reg.gauge(
            "karpenter_weather_spot_price_multiplier_mean",
            "Mean spot-price multiplier over the base market across all "
            "(family, zone) walks.", ()),
        "weather_spot_mult_max": reg.gauge(
            "karpenter_weather_spot_price_multiplier_max",
            "Worst-case spot-price multiplier over the base market "
            "across all (family, zone) walks.", ()),
        "weather_ticks": reg.gauge(
            "karpenter_weather_ticks",
            "Weather ticks simulated so far (the deterministic timeline "
            "index).", ()),
        "weather_events": reg.counter(
            "karpenter_weather_events_total",
            "Weather timeline events applied, by kind (reprice | regime | "
            "storm-begin | storm-burst | storm-end | ice | ice-thaw | "
            "device).", ("kind",)),
        "cluster_state_synced": reg.gauge(
            "karpenter_cluster_state_synced",
            "1 when cluster state has synced with the cloud (reference "
            "metrics.md:152: readiness of the state mirror).", ()),
        "cluster_state_node_count": reg.gauge(
            "karpenter_cluster_state_node_count", "Nodes tracked by cluster state.", ()),
        "cluster_state_pod_count": reg.gauge(
            "karpenter_cluster_state_pod_count", "Pods tracked by cluster state.", ()),
        "ice_cache_size": reg.gauge(
            "karpenter_ice_cache_size", "Offerings currently marked unavailable.", ()),
        # zero-downtime operator handoff (state/replication.py +
        # operator/leaderelection.py; docs/reference/handoff.md): leader/
        # standby role, the monotonic fencing token, and the replication
        # stream's progress — only exported once wire_handoff() ran
        "operator_leader_state": reg.gauge(
            "karpenter_operator_leader_state",
            "1 while this replica holds the leader lease, 0 on a standby "
            "(mirrors the elector's view; flips on promotion/demotion).", ()),
        "handoff_fence_token": reg.gauge(
            "karpenter_operator_handoff_fence_token",
            "Fencing token under which this replica last held the lease "
            "(monotonic across takeovers; a zombie leader's writes carry "
            "a stale token and are rejected).", ()),
        "handoff_fenced_writes": reg.gauge(
            "karpenter_operator_handoff_fenced_writes",
            "Side-effectful writes rejected by the fence guard because "
            "the lease was lost or the token rotated (each one is a "
            "zombie-leader action that did NOT race the new leader).", ()),
        "handoff_snapshots": reg.gauge(
            "karpenter_operator_handoff_snapshots",
            "Full state snapshots taken over the replication stream "
            "(leader: served; standby: applied).", ()),
        "handoff_deltas": reg.gauge(
            "karpenter_operator_handoff_deltas",
            "Incremental journal deltas streamed over the replication "
            "transport (leader: served; standby: applied).", ()),
        "handoff_rebuilds": reg.gauge(
            "karpenter_operator_handoff_rebuilds",
            "Standby full rebuilds forced by the cutover ladder, by "
            "reason (stale-anchor | snapshot-version-mismatch).",
            ("reason",)),
        "handoff_lease_transitions": reg.gauge(
            "karpenter_operator_handoff_lease_transitions",
            "Leadership transitions this elector observed on itself "
            "(promotions + demotions).", ()),
    }


# The per-instance-type / per-offering gauge surface (reference
# pkg/providers/instancetype/metrics.go:32-79): hardware shape per type,
# availability + price estimate per type×capacity-type×zone offering.
def wire_lattice_metrics(reg: Registry) -> Dict[str, Gauge]:
    return {
        "instance_type_cpu": reg.gauge(
            "karpenter_cloudprovider_instance_type_cpu_cores",
            "VCPUs cores for a given instance type.", ("instance_type",)),
        "instance_type_memory": reg.gauge(
            "karpenter_cloudprovider_instance_type_memory_bytes",
            "Memory, in bytes, for a given instance type.", ("instance_type",)),
        "offering_available": reg.gauge(
            "karpenter_cloudprovider_instance_type_offering_available",
            "Instance type offering availability, based on instance type, "
            "capacity type, and zone.",
            ("instance_type", "capacity_type", "zone")),
        "offering_price": reg.gauge(
            "karpenter_cloudprovider_instance_type_offering_price_estimate",
            "Instance type offering estimated hourly price, based on "
            "instance type, capacity type, and zone.",
            ("instance_type", "capacity_type", "zone")),
    }


# ---- wire-format lint (promtool-style) ------------------------------------

_METRIC_NAME_RE = None   # compiled lazily in lint_exposition
_SAMPLE_RE = None
_LABEL_RE = None


def lint_exposition(text: str) -> List[str]:
    """Promtool-style lint of a classic text-format exposition.

    Returns a list of problem strings (empty = clean). Enforced, in the
    spirit of `promtool check metrics` plus the scrape-safety rules this
    repo's exemplar-comment rendering depends on:

    - every sample's family declares ``# HELP`` then ``# TYPE`` (in that
      order, once each) BEFORE its first sample; TYPE is a known kind
    - family sample blocks are contiguous (no interleaving) — the
      ordering real scrapers rely on for streaming parses
    - sample lines parse: valid metric/label names, correctly escaped
      label values, a float-parseable value; no duplicate series
    - histogram families: ``le`` upper bounds strictly increase, bucket
      counts are monotonically non-decreasing, the ``+Inf`` bucket exists
      and AGREES with ``_count``, and ``_sum``/``_count`` are present
    - comment lines other than HELP/TYPE (e.g. the ``# exemplar`` lines
      tracing attaches after ``+Inf``) must stay scrape-safe: they start
      with ``# `` and never shadow a HELP/TYPE declaration
    """
    import re
    global _METRIC_NAME_RE, _SAMPLE_RE, _LABEL_RE
    if _METRIC_NAME_RE is None:
        _METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
        _SAMPLE_RE = re.compile(
            r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)(?:\s+(-?\d+))?$")
        _LABEL_RE = re.compile(
            r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\["\\n])*)"')
    problems: List[str] = []
    helps: Dict[str, str] = {}
    types: Dict[str, str] = {}
    seen_series: set = set()
    block_order: List[str] = []   # family per contiguous sample block
    # family -> {series key -> (labels, value)} for histogram agreement
    hist_samples: Dict[str, List[Tuple[str, Dict[str, str], float]]] = {}

    def family_of(name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in types:
                return name[: -len(suffix)]
        return name

    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                name = parts[2]
                if not _METRIC_NAME_RE.match(name):
                    problems.append(f"line {ln}: bad metric name {name!r}")
                    continue
                if parts[1] == "HELP":
                    if name in helps:
                        problems.append(f"line {ln}: duplicate HELP {name}")
                    if name in types:
                        problems.append(
                            f"line {ln}: HELP {name} after its TYPE")
                    helps[name] = parts[3] if len(parts) > 3 else ""
                else:
                    kind = parts[3].strip() if len(parts) > 3 else ""
                    if kind not in ("counter", "gauge", "histogram",
                                    "summary", "untyped"):
                        problems.append(
                            f"line {ln}: TYPE {name} unknown kind {kind!r}")
                    if name in types:
                        problems.append(f"line {ln}: duplicate TYPE {name}")
                    if name not in helps:
                        problems.append(f"line {ln}: TYPE {name} has no "
                                        "preceding HELP")
                    types[name] = kind
            elif not line.startswith("# "):
                problems.append(f"line {ln}: comment without '# ' prefix "
                                "is not scrape-safe")
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            problems.append(f"line {ln}: unparseable sample {line!r}")
            continue
        name, labelstr, value = m.group(1), m.group(2), m.group(3)
        labels: Dict[str, str] = {}
        if labelstr:
            matched = _LABEL_RE.findall(labelstr)
            # reconstruction check: every byte of the label block must be
            # consumed by well-formed pairs (catches unescaped quotes /
            # backslashes that a lenient findall would silently skip)
            rebuilt = ",".join(f'{k}="{v}"' for k, v in matched)
            if rebuilt != labelstr.rstrip(","):
                problems.append(
                    f"line {ln}: malformed/unescaped labels {labelstr!r}")
                continue
            labels = dict(matched)
        try:
            val = float(value)
        except ValueError:
            problems.append(f"line {ln}: unparseable value {value!r}")
            continue
        fam = family_of(name)
        if fam not in types:
            problems.append(f"line {ln}: sample {name} has no TYPE")
        elif types[fam] == "histogram":
            if name == fam:
                problems.append(f"line {ln}: histogram {fam} exposes a "
                                "bare sample (want _bucket/_sum/_count)")
            hist_samples.setdefault(fam, []).append((name, labels, val))
        series = (name, tuple(sorted(labels.items())))
        if series in seen_series:
            problems.append(f"line {ln}: duplicate series {name}"
                            f"{dict(labels)}")
        seen_series.add(series)
        if not block_order or block_order[-1] != fam:
            block_order.append(fam)
    for i, fam in enumerate(block_order):
        if fam in block_order[:i]:
            problems.append(f"family {fam}: sample block is not contiguous")
            break
    # histogram agreement per series (labels minus le)
    for fam, samples in hist_samples.items():
        groups: Dict[Tuple, Dict[str, object]] = {}
        for name, labels, val in samples:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            g = groups.setdefault(key, {"buckets": [], "sum": None,
                                        "count": None})
            if name.endswith("_bucket"):
                le = labels.get("le")
                if le is None:
                    problems.append(f"{fam}: bucket without le {labels}")
                    continue
                g["buckets"].append((float(le), val))
            elif name.endswith("_sum"):
                g["sum"] = val
            elif name.endswith("_count"):
                g["count"] = val
        for key, g in groups.items():
            buckets = g["buckets"]
            lbl = dict(key)
            if not buckets:
                continue
            les = [le for le, _ in buckets]
            if les != sorted(les):
                problems.append(f"{fam}{lbl}: le bounds out of order")
            if len(set(les)) != len(les):
                problems.append(f"{fam}{lbl}: duplicate le bounds")
            counts = [c for _, c in sorted(buckets)]
            if any(b > a for a, b in zip(counts[1:], counts)):
                problems.append(f"{fam}{lbl}: bucket counts decrease")
            if not any(le == float("inf") for le in les):
                problems.append(f"{fam}{lbl}: missing +Inf bucket")
            else:
                inf_count = dict(buckets)[float("inf")]
                if g["count"] is not None and inf_count != g["count"]:
                    problems.append(
                        f"{fam}{lbl}: +Inf bucket {inf_count} != _count "
                        f"{g['count']}")
            if g["sum"] is None:
                problems.append(f"{fam}{lbl}: missing _sum")
            if g["count"] is None:
                problems.append(f"{fam}{lbl}: missing _count")
    return problems


def emit_lattice_gauges(gauges: Dict[str, Gauge], lattice,
                        ice_mask=None) -> None:
    """Bulk-refresh the offering gauge surface straight from the lattice
    tensors (price/available are already [T,Z,C] arrays — the whole surface
    is four dict builds, no per-offering provider calls). ``ice_mask`` is
    the UnavailableOfferings mask; ICE'd offerings report available=0 the
    same way the reference folds its unavailableOfferings cache into
    createOfferings (instancetype.go:175-201)."""
    import numpy as np

    gauges["instance_type_cpu"].replace(
        {(s.name,): s.vcpus for s in lattice.specs})
    gauges["instance_type_memory"].replace(
        {(s.name,): s.memory_mib * 1024 * 1024 for s in lattice.specs})
    avail = lattice.available
    if ice_mask is not None:
        avail = avail & ice_mask
    offered = np.argwhere(np.isfinite(lattice.price))
    av: Dict[Tuple[str, ...], float] = {}
    pr: Dict[Tuple[str, ...], float] = {}
    names, zones, caps = lattice.names, lattice.zones, lattice.capacity_types
    for ti, zi, ci in offered:
        key = (names[ti], caps[ci], zones[zi])
        av[key] = 1.0 if avail[ti, zi, ci] else 0.0
        pr[key] = float(lattice.price[ti, zi, ci])
    gauges["offering_available"].replace(av)
    gauges["offering_price"].replace(pr)
