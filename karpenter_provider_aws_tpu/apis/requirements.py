"""Label-requirement algebra.

Host-side equivalent of the core scheduling requirements algebra the
reference uses pervasively (reference pkg/cloudprovider/cloudprovider.go:
246-251 `Requirements.Compatible`, CRD karpenter.sh_nodepools.yaml:338-401
for operators + minValues): label constraints with operators
In / NotIn / Exists / DoesNotExist / Gt / Lt, intersected per key.

Each key's constraint normalizes to:
  (allows_absent, include-set | universe, exclude-set, numeric interval)
which makes intersection and emptiness checks exact and cheap. The device
mask compiler (ops/masks.py) lowers the same normal form to boolean tensors
over the instance-type axis.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

from . import wellknown


class Operator(str, enum.Enum):
    IN = "In"
    NOT_IN = "NotIn"
    EXISTS = "Exists"
    DOES_NOT_EXIST = "DoesNotExist"
    GT = "Gt"
    LT = "Lt"


@dataclass(frozen=True)
class Requirement:
    """One NodeSelectorRequirement (+ optional minValues, CRD nodepools.yaml:338-401)."""

    key: str
    operator: Operator
    values: Tuple[str, ...] = ()
    min_values: Optional[int] = None

    def __post_init__(self):
        op = Operator(self.operator)
        object.__setattr__(self, "operator", op)
        object.__setattr__(self, "values", tuple(str(v) for v in self.values))
        if op in (Operator.EXISTS, Operator.DOES_NOT_EXIST) and self.values:
            raise ValueError(f"{op.value} takes no values (key={self.key})")
        if op in (Operator.GT, Operator.LT):
            if len(self.values) != 1:
                raise ValueError(f"{op.value} takes exactly one value (key={self.key})")
            # k8s NodeSelectorRequirement Gt/Lt compare integers (the
            # reference inherits this); Constraint.is_empty relies on it
            try:
                int(self.values[0])
            except ValueError:
                raise ValueError(
                    f"{op.value} requires an integer value (key={self.key}, got {self.values[0]!r})"
                ) from None
        if op == Operator.IN and not self.values:
            raise ValueError(f"In with empty values matches nothing (key={self.key})")


def _num(v: str) -> Optional[float]:
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


@dataclass
class Constraint:
    """Normalized allowed-value set for a single key."""

    allows_absent: bool = True
    include: Optional[frozenset] = None  # None = universe
    exclude: frozenset = frozenset()
    gt: Optional[float] = None  # value must be > gt
    lt: Optional[float] = None  # value must be < lt

    @staticmethod
    def universe() -> "Constraint":
        return Constraint()

    @staticmethod
    def from_requirement(r: Requirement) -> "Constraint":
        if r.operator == Operator.IN:
            return Constraint(allows_absent=False, include=frozenset(r.values))
        if r.operator == Operator.NOT_IN:
            return Constraint(allows_absent=True, exclude=frozenset(r.values))
        if r.operator == Operator.EXISTS:
            return Constraint(allows_absent=False)
        if r.operator == Operator.DOES_NOT_EXIST:
            return Constraint(allows_absent=True, include=frozenset())
        if r.operator == Operator.GT:
            return Constraint(allows_absent=False, gt=float(r.values[0]))
        if r.operator == Operator.LT:
            return Constraint(allows_absent=False, lt=float(r.values[0]))
        raise ValueError(r.operator)

    def intersect(self, other: "Constraint") -> "Constraint":
        if self.include is None:
            include = other.include
        elif other.include is None:
            include = self.include
        else:
            include = self.include & other.include
        gt = self.gt if other.gt is None else (other.gt if self.gt is None else max(self.gt, other.gt))
        lt = self.lt if other.lt is None else (other.lt if self.lt is None else min(self.lt, other.lt))
        return Constraint(
            allows_absent=self.allows_absent and other.allows_absent,
            include=include,
            exclude=self.exclude | other.exclude,
            gt=gt,
            lt=lt,
        )

    def matches(self, value: str) -> bool:
        """Does a present label value satisfy this constraint?"""
        if value in self.exclude:
            return False
        if self.include is not None and value not in self.include:
            return False
        if self.gt is not None or self.lt is not None:
            n = _num(value)
            if n is None:
                return False
            if self.gt is not None and not (n > self.gt):
                return False
            if self.lt is not None and not (n < self.lt):
                return False
        return True

    def is_empty(self) -> bool:
        """No present value can satisfy (absence may still be allowed)."""
        if self.include is not None:
            return not any(self.matches(v) for v in self.include)
        if self.gt is not None and self.lt is not None:
            # label numerics are integers in practice (reference Gt/Lt semantics)
            return self.lt <= self.gt + 1
        return False

    def intersects(self, other: "Constraint") -> bool:
        """Could any label state (a value, or absence) satisfy both?"""
        both = self.intersect(other)
        if both.allows_absent:
            return True
        return not both.is_empty()


def _requirements_from_node_selector(node_selector: Mapping[str, str]) -> List[Requirement]:
    return [Requirement(k, Operator.IN, (v,)) for k, v in node_selector.items()]


class Requirements:
    """A per-key intersection of requirements, mirroring the core algebra.

    - ``satisfied_by(labels)``: k8s nodeAffinity semantics against a concrete
      label set (In/Exists/Gt/Lt fail on absent key; NotIn/DoesNotExist pass).
    - ``intersects(other)``: Karpenter `Compatible` — per shared key the
      allowed sets must overlap; a key constrained on only one side is fine.
    """

    def __init__(self, reqs: Iterable[Requirement] = ()):  # noqa: D107
        self._constraints: Dict[str, Constraint] = {}
        self._reqs: List[Requirement] = []
        for r in reqs:
            self.add(r)

    @staticmethod
    def from_node_selector(node_selector: Mapping[str, str]) -> "Requirements":
        return Requirements(_requirements_from_node_selector(node_selector))

    @staticmethod
    def from_labels(labels: Mapping[str, str]) -> "Requirements":
        """Labels as requirements (each label pins its key, like NodePool template labels)."""
        return Requirements.from_node_selector(labels)

    def add(self, r: Requirement) -> "Requirements":
        c = Constraint.from_requirement(r)
        prev = self._constraints.get(r.key)
        self._constraints[r.key] = c if prev is None else prev.intersect(c)
        self._reqs.append(r)
        return self

    def merge(self, other: "Requirements") -> "Requirements":
        out = Requirements()
        for r in self._reqs:
            out.add(r)
        for r in other._reqs:
            out.add(r)
        return out

    @property
    def requirements(self) -> Sequence[Requirement]:
        return tuple(self._reqs)

    def keys(self):
        return self._constraints.keys()

    def get(self, key: str) -> Constraint:
        return self._constraints.get(key, Constraint.universe())

    def satisfied_by(self, labels: Mapping[str, str]) -> bool:
        for key, c in self._constraints.items():
            if key in labels:
                if not c.matches(labels[key]):
                    return False
            else:
                if not c.allows_absent:
                    return False
        return True

    def compatible_with(self, other: "Requirements", *,
                        allow_undefined_well_known: bool = True) -> bool:
        """DIRECTIONAL Compatible (reference cloudprovider.go:248 semantics):
        these requirements, evaluated against a node/pool described by
        ``other``. Shared keys must overlap; a key only WE constrain with an
        existence-requiring operator fails unless well-known (the lattice
        always defines well-known keys). Keys only ``other`` defines (e.g.
        NodePool template labels) are values the node will carry — they are
        never demands on us, which is what the symmetric ``intersects``
        would wrongly make them."""
        for key, c in self._constraints.items():
            if key in other._constraints:
                if not c.intersects(other._constraints[key]):
                    return False
            elif not c.allows_absent:
                if not (allow_undefined_well_known and key in wellknown.WELL_KNOWN_KEYS):
                    return False
        return True

    def intersects(self, other: "Requirements", *, allow_undefined_well_known: bool = True) -> bool:
        for key in set(self._constraints) & set(other._constraints):
            if not self._constraints[key].intersects(other._constraints[key]):
                return False
        # Reference semantics (cloudprovider.go:248 Compatible with
        # AllowUndefinedWellKnownLabels): an existence-requiring constraint on
        # a key the other side does not define is incompatible unless the key
        # is well-known (well-known keys are always defined by the lattice).
        for a, b in ((self, other), (other, self)):
            for key, c in a._constraints.items():
                if key in b._constraints or c.allows_absent:
                    continue
                if not (allow_undefined_well_known and key in wellknown.WELL_KNOWN_KEYS):
                    return False
        return True

    def min_values_satisfied(self, key_to_present_values: Mapping[str, Iterable[str]]) -> bool:
        """Per-requirement minValues check against the values actually present
        in a candidate instance-type set (reference instance.go:86-89 skips
        exotic-type filtering when minValues present; the core enforces the
        floor)."""
        for r in self._reqs:
            if r.min_values is None:
                continue
            c = self._constraints[r.key]
            present = key_to_present_values.get(r.key, ())
            n = len({v for v in present if c.matches(v)})
            if n < r.min_values:
                return False
        return True

    def __repr__(self):
        parts = ", ".join(f"{r.key} {r.operator.value} {list(r.values)}" for r in self._reqs)
        return f"Requirements({parts})"
