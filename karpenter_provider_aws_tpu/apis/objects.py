"""CRD-equivalent object model.

Python dataclass mirrors of the API types the reference defines or consumes:
- Pod scheduling surface (requests, nodeSelector/affinity, tolerations,
  topology spread, pod anti-affinity) — core scheduling semantics per
  reference website concepts/scheduling.md:23-35,312-446.
- NodePool (core CRD: pkg/apis/crds/karpenter.sh_nodepools.yaml) — template
  labels/taints/requirements, limits, weight, disruption policy + budgets.
- NodeClass (EC2NodeClass analog: pkg/apis/v1beta1/ec2nodeclass.go:28-119) —
  subnet/SG/AMI selector terms, AMI family, userdata, metadata options.
- NodeClaim (core CRD: karpenter.sh_nodeclaims.yaml) — the launch contract
  between scheduler and cloud provider, with lifecycle status.
- Node — the registered machine mirror used by cluster state.
"""

from __future__ import annotations

import enum
import time
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from .requirements import Operator, Requirement, Requirements
from .resources import resources_to_vec
from . import wellknown


# ---------------------------------------------------------------------------
# Taints / tolerations (k8s semantics, used by scheduling.md:312+ behaviors)
# ---------------------------------------------------------------------------

class TaintEffect(str, enum.Enum):
    NO_SCHEDULE = "NoSchedule"
    PREFER_NO_SCHEDULE = "PreferNoSchedule"
    NO_EXECUTE = "NoExecute"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: TaintEffect = TaintEffect.NO_SCHEDULE


@dataclass(frozen=True)
class Toleration:
    key: str = ""            # empty key + Exists tolerates everything
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: Optional[TaintEffect] = None  # None tolerates all effects

    def tolerates(self, taint: Taint) -> bool:
        if self.effect is not None and self.effect != taint.effect:
            return False
        if self.operator == "Exists":
            return self.key == "" or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


def tolerates_all(tolerations: Sequence[Toleration], taints: Sequence[Taint]) -> bool:
    """A pod schedules onto a node iff every NoSchedule/NoExecute taint is tolerated."""
    for t in taints:
        if t.effect == TaintEffect.PREFER_NO_SCHEDULE:
            continue
        if not any(tol.tolerates(t) for tol in tolerations):
            return False
    return True


# ---------------------------------------------------------------------------
# Pod scheduling surface
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str                      # zone / hostname / capacity-type
    when_unsatisfiable: str = "DoNotSchedule"  # or ScheduleAnyway
    # pods counted toward the spread are those matching these labels
    label_selector: Tuple[Tuple[str, str], ...] = ()


@dataclass(frozen=True)
class PodAffinityTerm:
    topology_key: str
    label_selector: Tuple[Tuple[str, str], ...] = ()
    anti: bool = False


@dataclass
class StorageClass:
    """Storage class with zonal allowedTopologies (reference
    scheduling.md:389-398 'Persistent Volume Topology')."""

    name: str
    zones: Tuple[str, ...] = ()            # allowedTopologies; () = any zone
    binding_mode: str = "WaitForFirstConsumer"   # or Immediate
    # CSI driver name. Deprecated in-tree plugins (kubernetes.io/aws-ebs)
    # publish no CSINode attach limits — the reference logs an error and
    # cannot enforce volume limits for them (troubleshooting.md:290-294)
    provisioner: str = "ebs.csi.aws.com"


IN_TREE_PROVISIONERS = frozenset({
    "kubernetes.io/aws-ebs", "kubernetes.io/gce-pd", "kubernetes.io/azure-disk",
})


@dataclass
class PersistentVolumeClaim:
    """A pod's storage claim. ``bound_zone`` is set once a PersistentVolume
    exists (the CSI driver gives it a zonal node-affinity rule); an unbound
    WaitForFirstConsumer claim restricts scheduling to its StorageClass's
    allowed topologies and binds to the zone the pod lands in."""

    name: str
    storage_class: str = ""
    bound_zone: Optional[str] = None


@dataclass(frozen=True)
class PreferredRequirement:
    """preferredDuringSchedulingIgnoredDuringExecution node-affinity term
    (reference scheduling.md:203-206): a soft rule. The scheduler treats it
    as required while possible and relaxes it — lowest weight first — when
    the pod cannot otherwise schedule (the core's preference relaxation)."""

    requirement: Requirement
    weight: int = 1                        # k8s weight 1-100


@dataclass
class PodDisruptionBudget:
    """Voluntary-eviction budget over a labelled pod set (the Kubernetes
    policy/v1 object the reference's drain respects — reference
    concepts/disruption.md:33 "evicting the pods ... to respect PDBs"
    and :112, the `pdb ... prevents pod evictions` Unconsolidatable
    event). Exactly one of max_unavailable / min_available must be set —
    the admission webhook (webhooks.validate_pdb) rejects anything else,
    as Kubernetes does; ClusterState still evaluates the tighter rule
    defensively if an unvalidated object carries both."""

    name: str
    label_selector: Dict[str, str] = field(default_factory=dict)
    max_unavailable: Optional[int] = None
    min_available: Optional[int] = None
    namespace: str = "default"

    def matches(self, pod: "Pod") -> bool:
        if pod.namespace != self.namespace:
            return False
        return all(pod.labels.get(k) == v
                   for k, v in self.label_selector.items())


@dataclass
class Pod:
    name: str
    namespace: str = "default"
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    requests: Dict[str, "str | int | float"] = field(default_factory=dict)
    node_selector: Dict[str, str] = field(default_factory=dict)
    required_affinity: List[Requirement] = field(default_factory=list)  # nodeAffinity required terms
    preferred_affinity: List[PreferredRequirement] = field(default_factory=list)
    tolerations: List[Toleration] = field(default_factory=list)
    topology_spread: List[TopologySpreadConstraint] = field(default_factory=list)
    pod_affinity: List[PodAffinityTerm] = field(default_factory=list)
    volume_claims: List[str] = field(default_factory=list)  # PVC names
    node_name: Optional[str] = None        # bound node (None = pending)
    owner: Optional[str] = None            # controller owner (daemonset detection etc.)
    is_daemonset: bool = False
    priority: int = 0
    deletion_timestamp: Optional[float] = None

    # fields that feed the scheduling-signature cache the solver stores on
    # the pod (solver/problem.py); reassigning any of them drops the cache.
    # In-place mutation of a field's container (pod.requests["cpu"] = ...)
    # is out of contract, as in k8s where pod specs are immutable.
    _SIG_FIELDS = frozenset({
        "labels", "requests", "node_selector", "required_affinity",
        "preferred_affinity", "tolerations", "topology_spread",
        "pod_affinity", "volume_claims"})

    def __setattr__(self, name, value):
        if name in Pod._SIG_FIELDS:
            self.__dict__.pop("_kpat_sig", None)
            self.__dict__.pop("_kpat_selkeys", None)
        object.__setattr__(self, name, value)

    def hard_scheduling_requirements(self) -> Requirements:
        """Required rules only — what can never be relaxed away."""
        reqs = Requirements.from_node_selector(self.node_selector)
        for r in self.required_affinity:
            reqs.add(r)
        return reqs

    def scheduling_requirements(self) -> Requirements:
        reqs = self.hard_scheduling_requirements()
        # preferences are treated as required while possible; the relaxation
        # loop (Solver.solve_relaxed) hands in relaxed Pod copies with the
        # weakest ones removed when the pod cannot otherwise schedule
        for p in self.preferred_affinity:
            reqs.add(p.requirement)
        return reqs

    def request_vec(self) -> np.ndarray:
        return resources_to_vec(self.requests, implicit_pod=True)


def _relax_sequence(pod: "Pod") -> List[Tuple[str, int]]:
    """Droppable soft constraints in drop order: preferred node-affinity
    terms lowest-weight-first (scheduling.md:203-206), then ScheduleAnyway
    topology spreads (advisory skew, scheduling.md:322-334)."""
    prefs = sorted(range(len(pod.preferred_affinity)),
                   key=lambda i: (pod.preferred_affinity[i].weight, i))
    seq: List[Tuple[str, int]] = [("pref", i) for i in prefs]
    seq += [("spread", i) for i, c in enumerate(pod.topology_spread)
            if c.when_unsatisfiable == "ScheduleAnyway"]
    return seq


def relaxation_depth(pod: Pod) -> int:
    """How many relaxation steps this pod supports (0 = nothing soft)."""
    return len(_relax_sequence(pod))


def relax_pod(pod: Pod, level: int) -> Pod:
    """Pod copy with its ``level`` weakest soft constraints removed."""
    if level <= 0:
        return pod
    import dataclasses
    dropped = _relax_sequence(pod)[:level]
    dp = {i for kind, i in dropped if kind == "pref"}
    ds = {i for kind, i in dropped if kind == "spread"}
    return dataclasses.replace(
        pod,
        preferred_affinity=[p for i, p in enumerate(pod.preferred_affinity)
                            if i not in dp],
        topology_spread=[c for i, c in enumerate(pod.topology_spread)
                         if i not in ds])


# ---------------------------------------------------------------------------
# NodePool (core CRD)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class DisruptionBudget:
    """Rate limit on concurrent voluntary disruptions
    (CRD karpenter.sh_nodepools.yaml:55-100)."""
    nodes: str = "10%"                      # count or percentage
    schedule: Optional[str] = None          # cron; None = always active
    duration: Optional[float] = None        # seconds the schedule window lasts
    reasons: Tuple[str, ...] = ()           # empty = all reasons


@dataclass
class NodePoolDisruption:
    consolidation_policy: str = "WhenUnderutilized"  # or WhenEmpty
    consolidate_after: Optional[float] = None        # seconds; None = Never
    expire_after: Optional[float] = None             # seconds; None = Never
    budgets: List[DisruptionBudget] = field(default_factory=lambda: [DisruptionBudget()])


@dataclass
class KubeletSpec:
    """The NodePool kubelet block (reference nodepools CRD
    spec.template.spec.kubelet): per-pool kubelet knobs that change node
    allocatable. ``max_pods`` caps the pods axis below the ENI-derived
    density (the reference's pod-dense scale test pins maxPods: 110).

    Three consumers apply the cap and must stay in lockstep when a knob
    is added here: the solve tensors (problem.np_alloc_cap), limit
    accounting (provisioning _enforce_limits via ``clamp_pods``), and
    the claim fill (cloudprovider.create via NodeClaim.max_pods)."""

    max_pods: Optional[int] = None
    # kubelet --cluster-dns override (the reference ipv6 suite sets an
    # IPv6 kube-dns here; discovery is the operator-side default,
    # reference operator.go:125-132)
    cluster_dns: Optional[str] = None

    def clamp_pods(self, pods_value: float) -> float:
        if self.max_pods is None:
            return pods_value
        return min(pods_value, float(self.max_pods))


@dataclass
class NodePool:
    name: str
    weight: int = 0                                   # higher tried first (nodepools.md:161-163)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    startup_taints: List[Taint] = field(default_factory=list)
    requirements: List[Requirement] = field(default_factory=list)
    node_class_ref: str = "default"
    limits: Dict[str, "str | int | float"] = field(default_factory=dict)  # cpu/memory ceilings
    disruption: NodePoolDisruption = field(default_factory=NodePoolDisruption)
    kubelet: Optional[KubeletSpec] = None  # per-pool allocatable knobs
    # set only on VIRTUAL pools the problem builder materializes for
    # custom-key label assignments (reference scheduling.md:536-556, the
    # Exists-operator workload-segregation technique): ``base_name`` is
    # the real pool (limits/budgets/hash roll up there) and
    # ``custom_labels`` the label values this variant's nodes carry.
    base_name: Optional[str] = None
    custom_labels: Dict[str, str] = field(default_factory=dict)
    # status: live committed usage (registered nodes + in-flight claims),
    # quantity strings per axis — the reference NodePool's
    # status.resources. Controller-owned; outside the template hash
    # (controllers/provisioning.py nodepool_hash) so status refreshes
    # never read as drift.
    status_resources: Dict[str, str] = field(default_factory=dict)

    def scheduling_requirements(self) -> Requirements:
        reqs = Requirements.from_labels(self.labels)
        for r in self.requirements:
            reqs.add(r)
        # a virtual variant's nodes still carry the REAL pool's name label
        reqs.add(Requirement(wellknown.LABEL_NODEPOOL, Operator.IN,
                             (self.base_name or self.name,)))
        return reqs

    def limits_vec(self) -> Optional[np.ndarray]:
        if not self.limits:
            return None
        return resources_to_vec(self.limits)


# ---------------------------------------------------------------------------
# NodeClass (provider CRD analog)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class NodeClassSelectorTerm:
    """Tag/id/name selector term (ec2nodeclass.go subnet/SG/AMI selector terms)."""
    tags: Tuple[Tuple[str, str], ...] = ()
    id: Optional[str] = None
    name: Optional[str] = None


@dataclass
class MetadataOptions:
    http_endpoint: str = "enabled"
    http_protocol_ipv6: str = "disabled"
    http_put_response_hop_limit: int = 2
    http_tokens: str = "required"


@dataclass
class NodeClass:
    name: str
    ami_family: str = "AL2023"   # AL2 | AL2023 | Bottlerocket | Ubuntu | Windows | Custom
    subnet_selector_terms: List[NodeClassSelectorTerm] = field(default_factory=list)
    security_group_selector_terms: List[NodeClassSelectorTerm] = field(default_factory=list)
    ami_selector_terms: List[NodeClassSelectorTerm] = field(default_factory=list)
    user_data: Optional[str] = None
    role: Optional[str] = None
    instance_profile: Optional[str] = None
    tags: Dict[str, str] = field(default_factory=dict)
    # BDM dicts: {"device_name": str, "root_volume": bool,
    # "volume_size_mib": float} (reference ec2nodeclass.go BlockDeviceMapping)
    block_device_mappings: List[Dict] = field(default_factory=list)
    # None (default: instance-store disks unused) | "RAID0" (local NVMe
    # becomes node ephemeral-storage; reference ec2nodeclass.go:92-94)
    instance_store_policy: Optional[str] = None
    metadata_options: MetadataOptions = field(default_factory=MetadataOptions)
    detailed_monitoring: bool = False
    associate_public_ip: Optional[bool] = None
    annotations: Dict[str, str] = field(default_factory=dict)
    # status (hydrated by the nodeclass controller, reference nodeclass/controller.go:150-233)
    status_subnets: List[Dict] = field(default_factory=list)
    status_security_groups: List[Dict] = field(default_factory=list)
    status_amis: List[Dict] = field(default_factory=list)
    status_instance_profile: Optional[str] = None
    status_conditions: Dict[str, bool] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# NodeClaim lifecycle (core CRD + state machine)
# ---------------------------------------------------------------------------

# the Windows Server 2022 EKS-optimized AMI's build number — the value of
# the well-known node.kubernetes.io/windows-build label every node of a
# windows pool carries (reference labels.go registers v1.LabelWindowsBuild)
WINDOWS_BUILD = "10.0.20348"


def pool_os(pool: "NodePool") -> str:
    """The OS every node of this pool boots (its AMI family's OS,
    surfaced through the pool's os requirement OR its template label —
    scheduling_requirements() folds both). Admission validates the
    requirement to a single-valued In; unvalidated multi-value input
    resolves deterministically (first sorted value). Default: linux."""
    c = pool.scheduling_requirements().get(wellknown.LABEL_OS)
    if c.include:
        return sorted(c.include)[0]
    return "linux"


@dataclass
class Lease:
    """A kube-node-lease Lease (coordination.k8s.io). The kubelet creates
    one per node with an owner reference; orphaned leases (no owner, or an
    owner that no longer exists) are garbage collected by the controller —
    reference test/suites/integration/lease_garbagecollection_test.go."""

    name: str
    owner_node: Optional[str] = None
    created_at: float = field(default_factory=time.time)


class NodeClaimPhase(str, enum.Enum):
    PENDING = "Pending"         # created by scheduler, not yet launched
    LAUNCHED = "Launched"       # cloud capacity created (providerID set)
    REGISTERED = "Registered"   # node joined the cluster
    INITIALIZED = "Initialized" # node ready + startup taints cleared
    TERMINATING = "Terminating"
    TERMINATED = "Terminated"


@dataclass
class NodeClaim:
    name: str
    node_pool: str
    requirements: List[Requirement] = field(default_factory=list)
    resource_requests: Dict[str, float] = field(default_factory=dict)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    node_class_ref: str = "default"
    # status
    phase: NodeClaimPhase = NodeClaimPhase.PENDING
    # kubelet maxPods from the owning pool's template: CloudProvider.create
    # clamps the pods axis of capacity/allocatable at fill time, so no
    # concurrent solve ever observes the unclamped ENI-derived density
    max_pods: Optional[int] = None
    cluster_dns: Optional[str] = None  # kubelet ClusterDNS from the pool
    provider_id: Optional[str] = None
    internal_ip: Optional[str] = None  # instance private IP (v4 or v6)
    instance_type: Optional[str] = None
    zone: Optional[str] = None
    capacity_type: Optional[str] = None
    image_id: Optional[str] = None
    capacity: Dict[str, float] = field(default_factory=dict)
    allocatable: Dict[str, float] = field(default_factory=dict)
    created_at: float = field(default_factory=time.time)
    launched_at: Optional[float] = None
    registered_at: Optional[float] = None
    initialized_at: Optional[float] = None
    deletion_timestamp: Optional[float] = None

    def scheduling_requirements(self) -> Requirements:
        return Requirements(self.requirements)


@dataclass
class Node:
    name: str
    provider_id: str
    internal_ip: Optional[str] = None  # InternalIP address (v4 or v6)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    capacity: Dict[str, float] = field(default_factory=dict)
    allocatable: Dict[str, float] = field(default_factory=dict)
    ready: bool = False
    created_at: float = field(default_factory=time.time)
    node_pool: Optional[str] = None
    node_claim: Optional[str] = None
