"""Well-known scheduling label keys.

Mirrors the label surface the reference registers into the core scheduler
(reference pkg/apis/v1beta1/labels.go:27-116): the k8s topology/arch/os
labels, karpenter.sh pool/capacity-type labels, and the karpenter.k8s.aws
instance-description labels that make requirements like
"karpenter.k8s.aws/instance-cpu Gt 16" work.

``NUMERIC_KEYS`` are the keys whose values compare as numbers (Gt/Lt work);
everything else is categorical. The device mask compiler (ops/masks.py) uses
this split: categorical keys become vocab-id membership tests, numeric keys
become interval tests.
"""

# Domain prefixes (ours, but kept API-compatible in spirit with the reference)
KARPENTER_PREFIX = "karpenter.sh"
PROVIDER_PREFIX = "karpenter.k8s.aws"

# Core well-known keys
LABEL_NODEPOOL = f"{KARPENTER_PREFIX}/nodepool"
LABEL_CAPACITY_TYPE = f"{KARPENTER_PREFIX}/capacity-type"   # on-demand | spot
LABEL_ZONE = "topology.kubernetes.io/zone"
LABEL_REGION = "topology.kubernetes.io/region"
LABEL_INSTANCE_TYPE = "node.kubernetes.io/instance-type"
LABEL_ARCH = "kubernetes.io/arch"                            # amd64 | arm64
LABEL_OS = "kubernetes.io/os"                                # linux | windows
LABEL_WINDOWS_BUILD = "node.kubernetes.io/windows-build"     # e.g. 10.0.20348
LABEL_HOSTNAME = "kubernetes.io/hostname"

# Provider instance-description keys (reference labels.go:27-50)
LABEL_INSTANCE_CATEGORY = f"{PROVIDER_PREFIX}/instance-category"          # c, m, r, t, p, g, inf, trn, ...
LABEL_INSTANCE_FAMILY = f"{PROVIDER_PREFIX}/instance-family"              # c5, m6g, ...
LABEL_INSTANCE_GENERATION = f"{PROVIDER_PREFIX}/instance-generation"      # numeric
LABEL_INSTANCE_SIZE = f"{PROVIDER_PREFIX}/instance-size"                  # large, 2xlarge, metal, ...
LABEL_INSTANCE_CPU = f"{PROVIDER_PREFIX}/instance-cpu"                    # numeric (vCPU)
LABEL_INSTANCE_CPU_MANUFACTURER = f"{PROVIDER_PREFIX}/instance-cpu-manufacturer"  # intel|amd|aws
LABEL_INSTANCE_MEMORY = f"{PROVIDER_PREFIX}/instance-memory"              # numeric (MiB)
LABEL_INSTANCE_NETWORK_BANDWIDTH = f"{PROVIDER_PREFIX}/instance-network-bandwidth"  # numeric (Mbps)
LABEL_INSTANCE_HYPERVISOR = f"{PROVIDER_PREFIX}/instance-hypervisor"      # nitro | xen | '' (metal)
LABEL_INSTANCE_ENCRYPTION_IN_TRANSIT = f"{PROVIDER_PREFIX}/instance-encryption-in-transit-supported"
LABEL_INSTANCE_LOCAL_NVME = f"{PROVIDER_PREFIX}/instance-local-nvme"      # numeric (GiB)
LABEL_INSTANCE_GPU_NAME = f"{PROVIDER_PREFIX}/instance-gpu-name"          # t4, a100, v100, ...
LABEL_INSTANCE_GPU_MANUFACTURER = f"{PROVIDER_PREFIX}/instance-gpu-manufacturer"  # nvidia | habana
LABEL_INSTANCE_GPU_COUNT = f"{PROVIDER_PREFIX}/instance-gpu-count"        # numeric
LABEL_INSTANCE_GPU_MEMORY = f"{PROVIDER_PREFIX}/instance-gpu-memory"      # numeric (MiB)
LABEL_INSTANCE_ACCELERATOR_NAME = f"{PROVIDER_PREFIX}/instance-accelerator-name"        # inferentia, ...
LABEL_INSTANCE_ACCELERATOR_MANUFACTURER = f"{PROVIDER_PREFIX}/instance-accelerator-manufacturer"
LABEL_INSTANCE_ACCELERATOR_COUNT = f"{PROVIDER_PREFIX}/instance-accelerator-count"      # numeric

CAPACITY_TYPE_ON_DEMAND = "on-demand"
CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_RESERVED = "reserved"

# Taint key the disruption controller uses to cordon candidates
# (reference: karpenter.sh/disruption taint, website concepts/disruption.md)
DISRUPTION_TAINT_KEY = f"{KARPENTER_PREFIX}/disruption"
DISRUPTED_TAINT_VALUE = "disrupting"

# Annotation keys for drift hashing (reference pkg/apis/v1beta1/ec2nodeclass.go Hash)
ANNOTATION_NODECLASS_HASH = f"{PROVIDER_PREFIX}/nodeclass-hash"
ANNOTATION_NODECLASS_HASH_VERSION = f"{PROVIDER_PREFIX}/nodeclass-hash-version"
ANNOTATION_NODEPOOL_HASH = f"{KARPENTER_PREFIX}/nodepool-hash"
ANNOTATION_NODEPOOL_HASH_VERSION = f"{KARPENTER_PREFIX}/nodepool-hash-version"
ANNOTATION_INSTANCE_TAGGED = f"{KARPENTER_PREFIX}/instance-tagged"
# pod/node/NodePool opt-out from voluntary disruption (reference
# website concepts/disruption.md:253,282,294)
ANNOTATION_DO_NOT_DISRUPT = f"{KARPENTER_PREFIX}/do-not-disrupt"
# Tracing & solver-provenance annotations (docs/reference/tracing.md).
# The REST apiserver stamps an incoming request's W3C traceparent onto
# created pods so the provisioning pass that later drains them can join
# the SAME trace (tail of the REST→operator causal chain); the
# provisioner stamps each NodeClaim with the traceparent of the pass
# that planned it plus the solve's provenance (path / degradation /
# per-stage ms / pipelined flag), which `kpctl describe nodeclaims`
# renders so an operator sees WHY a claim's solve was slow or degraded.
ANNOTATION_TRACEPARENT = f"{KARPENTER_PREFIX}/traceparent"
ANNOTATION_SOLVER_PATH = f"{KARPENTER_PREFIX}/solver-path"
ANNOTATION_SOLVER_DEGRADED_REASON = f"{KARPENTER_PREFIX}/solver-degraded-reason"
ANNOTATION_SOLVER_PIPELINED = f"{KARPENTER_PREFIX}/solver-pipelined"
ANNOTATION_SOLVER_WAVES = f"{KARPENTER_PREFIX}/solver-waves"
ANNOTATION_SOLVER_STAGE_MS = f"{KARPENTER_PREFIX}/solver-stage-ms"
ANNOTATION_SOLVER_MESH_DEVICES = f"{KARPENTER_PREFIX}/solver-mesh-devices"
TAG_NAME = "Name"
TAG_NODECLAIM = f"{KARPENTER_PREFIX}/nodeclaim"

# Well-known label keys. Requirements.intersects mirrors the reference's
# `Compatible(..., AllowUndefinedWellKnownLabels)` (cloudprovider.go:248):
# an existence-requiring requirement (In/Exists/Gt/Lt) on a key UNDEFINED on
# the other side is incompatible unless the key is well-known (the lattice
# will define well-known keys for every instance type, so undefined merely
# means "not constrained yet").
WELL_KNOWN_KEYS = frozenset({
    LABEL_NODEPOOL, LABEL_CAPACITY_TYPE, LABEL_ZONE, LABEL_REGION,
    LABEL_INSTANCE_TYPE, LABEL_ARCH, LABEL_OS, LABEL_HOSTNAME,
    LABEL_INSTANCE_CATEGORY, LABEL_INSTANCE_FAMILY, LABEL_INSTANCE_GENERATION,
    LABEL_INSTANCE_SIZE, LABEL_INSTANCE_CPU, LABEL_INSTANCE_CPU_MANUFACTURER,
    LABEL_INSTANCE_MEMORY, LABEL_INSTANCE_NETWORK_BANDWIDTH,
    LABEL_INSTANCE_HYPERVISOR, LABEL_INSTANCE_ENCRYPTION_IN_TRANSIT,
    LABEL_INSTANCE_LOCAL_NVME, LABEL_INSTANCE_GPU_NAME,
    LABEL_INSTANCE_GPU_MANUFACTURER, LABEL_INSTANCE_GPU_COUNT,
    LABEL_INSTANCE_GPU_MEMORY, LABEL_INSTANCE_ACCELERATOR_NAME,
    LABEL_INSTANCE_ACCELERATOR_MANUFACTURER, LABEL_INSTANCE_ACCELERATOR_COUNT,
    # registered like the reference's v1.LabelWindowsBuild (labels.go:48):
    # resolved per pool — every windows pool's nodes carry the build
    LABEL_WINDOWS_BUILD,
})

NUMERIC_KEYS = frozenset({
    LABEL_INSTANCE_GENERATION,
    LABEL_INSTANCE_CPU,
    LABEL_INSTANCE_MEMORY,
    LABEL_INSTANCE_NETWORK_BANDWIDTH,
    LABEL_INSTANCE_LOCAL_NVME,
    LABEL_INSTANCE_GPU_COUNT,
    LABEL_INSTANCE_GPU_MEMORY,
    LABEL_INSTANCE_ACCELERATOR_COUNT,
})

# Keys that participate in the device constraint lattice, in a stable order.
# (hostname is handled structurally — each bin IS a hostname; nodepool is a
# dedicated axis; zone and capacity-type are dedicated offering axes.)
DEVICE_CATEGORICAL_KEYS = (
    LABEL_INSTANCE_TYPE,
    LABEL_ARCH,
    LABEL_INSTANCE_CATEGORY,
    LABEL_INSTANCE_FAMILY,
    LABEL_INSTANCE_SIZE,
    LABEL_INSTANCE_CPU_MANUFACTURER,
    LABEL_INSTANCE_HYPERVISOR,
    LABEL_INSTANCE_ENCRYPTION_IN_TRANSIT,
    LABEL_INSTANCE_GPU_NAME,
    LABEL_INSTANCE_GPU_MANUFACTURER,
    LABEL_INSTANCE_ACCELERATOR_NAME,
    LABEL_INSTANCE_ACCELERATOR_MANUFACTURER,
)
DEVICE_NUMERIC_KEYS = (
    LABEL_INSTANCE_GENERATION,
    LABEL_INSTANCE_CPU,
    LABEL_INSTANCE_MEMORY,
    LABEL_INSTANCE_NETWORK_BANDWIDTH,
    LABEL_INSTANCE_LOCAL_NVME,
    LABEL_INSTANCE_GPU_COUNT,
    LABEL_INSTANCE_GPU_MEMORY,
    LABEL_INSTANCE_ACCELERATOR_COUNT,
)
