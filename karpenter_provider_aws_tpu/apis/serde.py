"""Wire (de)serialization for the API objects.

The solver sidecar (parallel/sidecar.py) ships Pods/NodePools/cluster
state across a process boundary the way the reference's controller ships
kube objects over the API server watch stream (SURVEY §2.3 communication
backend). JSON keeps the wire format language-neutral: a non-Python
controller can build these payloads directly.

Round-trip contract: ``pod_from_dict(pod_to_dict(p))`` produces a Pod that
schedules identically (same scheduling signature), and likewise for every
other object here.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional

import numpy as np

from .objects import (
    DisruptionBudget, KubeletSpec, NodePool, NodePoolDisruption,
    PersistentVolumeClaim, Pod, PodAffinityTerm, PreferredRequirement,
    StorageClass, Taint, TaintEffect, Toleration, TopologySpreadConstraint,
)
from .requirements import Operator, Requirement

# ---- requirements ----------------------------------------------------------


def requirement_to_dict(r: Requirement) -> Dict:
    out = {"key": r.key, "operator": r.operator.value,
           "values": list(r.values)}
    if r.min_values is not None:
        out["minValues"] = r.min_values
    return out


def requirement_from_dict(d: Mapping) -> Requirement:
    return Requirement(key=d["key"], operator=Operator(d["operator"]),
                       values=tuple(d.get("values", ())),
                       min_values=d.get("minValues"))


# ---- pod -------------------------------------------------------------------


def pod_to_dict(p: Pod) -> Dict:
    return {
        "name": p.name,
        "namespace": p.namespace,
        "labels": dict(p.labels),
        "annotations": dict(p.annotations),
        "requests": {k: str(v) for k, v in p.requests.items()},
        "nodeSelector": dict(p.node_selector),
        "requiredAffinity": [requirement_to_dict(r) for r in p.required_affinity],
        "preferredAffinity": [
            {"requirement": requirement_to_dict(pr.requirement),
             "weight": pr.weight} for pr in p.preferred_affinity],
        "tolerations": [
            {"key": t.key, "operator": t.operator, "value": t.value,
             "effect": t.effect.value if t.effect is not None else None}
            for t in p.tolerations],
        "topologySpread": [
            {"maxSkew": c.max_skew, "topologyKey": c.topology_key,
             "whenUnsatisfiable": c.when_unsatisfiable,
             "labelSelector": [list(kv) for kv in c.label_selector]}
            for c in p.topology_spread],
        "podAffinity": [
            {"topologyKey": t.topology_key,
             "labelSelector": [list(kv) for kv in t.label_selector],
             "anti": t.anti} for t in p.pod_affinity],
        "volumeClaims": list(p.volume_claims),
        "nodeName": p.node_name,
        "owner": p.owner,
        "isDaemonset": p.is_daemonset,
        "priority": p.priority,
        "deletionTimestamp": p.deletion_timestamp,
    }


def pod_from_dict(d: Mapping) -> Pod:
    return Pod(
        name=d["name"],
        namespace=d.get("namespace", "default"),
        labels=dict(d.get("labels", {})),
        annotations=dict(d.get("annotations", {})),
        requests=dict(d.get("requests", {})),
        node_selector=dict(d.get("nodeSelector", {})),
        required_affinity=[requirement_from_dict(r)
                           for r in d.get("requiredAffinity", ())],
        preferred_affinity=[
            PreferredRequirement(
                requirement=requirement_from_dict(pr["requirement"]),
                weight=pr.get("weight", 1))
            for pr in d.get("preferredAffinity", ())],
        tolerations=[
            Toleration(key=t.get("key", ""), operator=t.get("operator", "Equal"),
                       value=t.get("value", ""),
                       effect=(TaintEffect(t["effect"])
                               if t.get("effect") else None))
            for t in d.get("tolerations", ())],
        topology_spread=[
            TopologySpreadConstraint(
                max_skew=c["maxSkew"], topology_key=c["topologyKey"],
                when_unsatisfiable=c.get("whenUnsatisfiable", "DoNotSchedule"),
                label_selector=tuple(tuple(kv) for kv in c.get("labelSelector", ())))
            for c in d.get("topologySpread", ())],
        pod_affinity=[
            PodAffinityTerm(
                topology_key=t["topologyKey"],
                label_selector=tuple(tuple(kv) for kv in t.get("labelSelector", ())),
                anti=t.get("anti", False))
            for t in d.get("podAffinity", ())],
        volume_claims=list(d.get("volumeClaims", ())),
        node_name=d.get("nodeName"),
        owner=d.get("owner"),
        is_daemonset=d.get("isDaemonset", False),
        priority=d.get("priority", 0),
        deletion_timestamp=d.get("deletionTimestamp"),
    )


# ---- nodepool --------------------------------------------------------------


def nodepool_to_dict(p: NodePool) -> Dict:
    return {
        "name": p.name,
        "weight": p.weight,
        "labels": dict(p.labels),
        "annotations": dict(p.annotations),
        "requirements": [requirement_to_dict(r) for r in p.requirements],
        "taints": [{"key": t.key, "value": t.value, "effect": t.effect.value}
                   for t in p.taints],
        "startupTaints": [{"key": t.key, "value": t.value,
                           "effect": t.effect.value}
                          for t in p.startup_taints],
        "limits": {k: str(v) for k, v in p.limits.items()},
        "disruption": {
            "consolidationPolicy": p.disruption.consolidation_policy,
            "consolidateAfter": p.disruption.consolidate_after,
            "expireAfter": p.disruption.expire_after,
            "budgets": [
                {"nodes": b.nodes, "schedule": b.schedule,
                 "duration": b.duration, "reasons": list(b.reasons)}
                for b in p.disruption.budgets],
        },
        "nodeClassRef": p.node_class_ref,
        "kubelet": ({"maxPods": p.kubelet.max_pods,
                     "clusterDNS": p.kubelet.cluster_dns}
                    if p.kubelet is not None else None),
        # NOTE: status_resources deliberately does NOT ride the spec —
        # it is controller-owned live usage (the reference NodePool's
        # status.resources) and lives in the envelope's status sub-map
        # (nodepool_status_to_dict), so a `kpctl get -o yaml | kpctl
        # apply` round-trip can never re-submit stale controller status
        # as user intent.
    }


def nodepool_status_to_dict(p: NodePool) -> Dict:
    """The controller-owned status sub-map of a NodePool envelope —
    the reference's spec/status split. User applies never carry it; the
    apiserver preserves the stored status across spec updates."""
    return {"resources": dict(p.status_resources)}


def nodepool_apply_status(p: NodePool, status: Optional[Mapping]) -> NodePool:
    """Hydrate a deserialized NodePool with its envelope status (the
    inverse of nodepool_status_to_dict); tolerates a missing map."""
    if status:
        p.status_resources = dict(status.get("resources", {}))
    return p


def nodepool_from_dict(d: Mapping) -> NodePool:
    dis = d.get("disruption", {})
    return NodePool(
        name=d["name"],
        weight=d.get("weight", 0),
        labels=dict(d.get("labels", {})),
        annotations=dict(d.get("annotations", {})),
        requirements=[requirement_from_dict(r)
                      for r in d.get("requirements", ())],
        taints=[Taint(key=t["key"], value=t.get("value", ""),
                      effect=TaintEffect(t.get("effect", "NoSchedule")))
                for t in d.get("taints", ())],
        startup_taints=[Taint(key=t["key"], value=t.get("value", ""),
                              effect=TaintEffect(t.get("effect", "NoSchedule")))
                        for t in d.get("startupTaints", ())],
        limits=dict(d.get("limits", {})),
        disruption=NodePoolDisruption(
            consolidation_policy=dis.get("consolidationPolicy",
                                         "WhenUnderutilized"),
            consolidate_after=dis.get("consolidateAfter"),
            expire_after=dis.get("expireAfter"),
            budgets=[DisruptionBudget(
                nodes=b.get("nodes", "10%"), schedule=b.get("schedule"),
                duration=b.get("duration"),
                reasons=tuple(b.get("reasons", ())))
                for b in dis.get("budgets", [{}])]),
        node_class_ref=d.get("nodeClassRef", "default"),
        kubelet=(KubeletSpec(max_pods=d["kubelet"].get("maxPods"),
                             cluster_dns=d["kubelet"].get("clusterDNS"))
                 if d.get("kubelet") else None),
        # legacy payloads carried status in the spec; accept it on read
        # (admission normalization strips it on the next write)
        status_resources=dict(d.get("statusResources", {})),
    )


# ---- volumes ---------------------------------------------------------------


def pvc_to_dict(c: PersistentVolumeClaim) -> Dict:
    return {"name": c.name, "storageClass": c.storage_class,
            "boundZone": c.bound_zone}


def pvc_from_dict(d: Mapping) -> PersistentVolumeClaim:
    return PersistentVolumeClaim(name=d["name"],
                                 storage_class=d.get("storageClass", ""),
                                 bound_zone=d.get("boundZone"))


def storage_class_to_dict(s: StorageClass) -> Dict:
    return {"name": s.name, "zones": list(s.zones),
            "bindingMode": s.binding_mode, "provisioner": s.provisioner}


def storage_class_from_dict(d: Mapping) -> StorageClass:
    return StorageClass(name=d["name"], zones=tuple(d.get("zones", ())),
                        binding_mode=d.get("bindingMode",
                                           "WaitForFirstConsumer"),
                        provisioner=d.get("provisioner", "ebs.csi.aws.com"))


# ---- solver-side objects ---------------------------------------------------


def existing_bin_to_dict(b) -> Dict:
    return {
        "name": b.name, "nodePool": b.node_pool,
        "instanceType": b.instance_type, "zone": b.zone,
        "capacityType": b.capacity_type,
        "used": np.asarray(b.used, dtype=float).tolist(),
        # per-element null = axis the node did not report (NaN sentinel);
        # NaN itself is not representable in strict RFC 8259 JSON and the
        # wire must stay cross-language
        "allocOverride": ([None if np.isnan(x) else x
                           for x in np.asarray(b.alloc_override, dtype=float)]
                          if b.alloc_override is not None else None),
        "labels": dict(b.labels),
    }


def existing_bin_from_dict(d: Mapping):
    from ..solver.problem import ExistingBin
    return ExistingBin(
        name=d["name"], node_pool=d["nodePool"],
        instance_type=d["instanceType"], zone=d["zone"],
        capacity_type=d["capacityType"],
        used=np.asarray(d["used"], dtype=np.float32),
        alloc_override=(np.asarray(
            [np.nan if x is None else x for x in d["allocOverride"]],
            dtype=np.float32)
            if d.get("allocOverride") is not None else None),
        labels=dict(d.get("labels", {})),
    )


def plan_to_dict(plan) -> Dict:
    return {
        "newNodes": [
            {"nodePool": n.node_pool, "instanceType": n.instance_type,
             "zone": n.zone, "capacityType": n.capacity_type,
             "pricePerHour": n.price_per_hour, "pods": list(n.pods),
             "feasibleTypes": list(n.feasible_types),
             "feasibleZones": list(n.feasible_zones),
             "feasibleCapacityTypes": list(n.feasible_capacity_types),
             "extraLabels": dict(n.extra_labels)}
            for n in plan.new_nodes],
        "existingAssignments": {k: list(v)
                                for k, v in plan.existing_assignments.items()},
        "unschedulable": dict(plan.unschedulable),
        "newNodeCost": plan.new_node_cost,
        "solveSeconds": plan.solve_seconds,
        "deviceSeconds": plan.device_seconds,
        "warnings": list(plan.warnings),
        # degradation provenance crosses the wire so a sidecar client's
        # controller observes degraded mode exactly like an in-process one
        "degraded": plan.degraded,
        "degradedReason": plan.degraded_reason,
        "solverPath": plan.solver_path,
        "waves": plan.waves,
        "deviceRetries": plan.device_retries,
        # per-stage wall-clock of the solve (solver/pipeline.py STAGES)
        # and whether the overlapped path produced it — a sidecar client
        # sees the same pipelining evidence as an in-process controller
        "stageMs": {k: round(float(v), 3)
                    for k, v in plan.stage_ms.items()},
        "pipelined": plan.pipelined,
        # the mesh that produced this plan (1 = single-device) and its
        # split's load balance: a RemoteSolver caller sees whether the
        # sidecar's mesh engaged — and how evenly — exactly like an
        # in-process controller (docs/reference/sharding.md)
        "meshDevices": plan.mesh_devices,
        "shardImbalance": round(float(plan.shard_imbalance), 4),
    }


# wire keys that carry timing/provenance rather than plan CONTENT: the
# byte-identity surface parity checks (mesh-vs-single-device,
# pipelined-vs-sequential, bench parity rows) compare plans with these
# stripped. ONE list — a new provenance field added to plan_to_dict
# joins it here, and every parity site stays in sync automatically.
# NOTE "warnings" stays IN the compared surface: both sides of every
# parity pair derive warnings from the same problem, so a path that
# drops or duplicates them is a real regression the parity must catch.
_PLAN_PROVENANCE_KEYS = ("solveSeconds", "deviceSeconds", "stageMs",
                         "pipelined", "deviceRetries", "meshDevices",
                         "shardImbalance")


def plan_semantic_dict(plan) -> Dict:
    """``plan_to_dict`` minus timing/provenance — the canonical content
    two solves of the same problem must agree on byte-for-byte."""
    d = plan_to_dict(plan)
    for k in _PLAN_PROVENANCE_KEYS:
        d.pop(k, None)
    return d


def plan_from_dict(d: Mapping):
    from ..solver.solve import NodePlan, PlannedNode
    return NodePlan(
        new_nodes=[
            PlannedNode(
                node_pool=n["nodePool"], instance_type=n["instanceType"],
                zone=n["zone"], capacity_type=n["capacityType"],
                price_per_hour=n["pricePerHour"], pods=list(n["pods"]),
                feasible_types=list(n.get("feasibleTypes", ())),
                feasible_zones=list(n.get("feasibleZones", ())),
                feasible_capacity_types=list(n.get("feasibleCapacityTypes", ())),
                extra_labels=dict(n.get("extraLabels", {})))
            for n in d.get("newNodes", ())],
        existing_assignments={k: list(v) for k, v in
                              d.get("existingAssignments", {}).items()},
        unschedulable=dict(d.get("unschedulable", {})),
        new_node_cost=d.get("newNodeCost", 0.0),
        solve_seconds=d.get("solveSeconds", 0.0),
        device_seconds=d.get("deviceSeconds", 0.0),
        warnings=list(d.get("warnings", ())),
        degraded=bool(d.get("degraded", False)),
        degraded_reason=d.get("degradedReason", ""),
        solver_path=d.get("solverPath", "device"),
        waves=int(d.get("waves", 1)),
        device_retries=int(d.get("deviceRetries", 0)),
        stage_ms={k: float(v) for k, v in d.get("stageMs", {}).items()},
        pipelined=bool(d.get("pipelined", False)),
        mesh_devices=int(d.get("meshDevices", 1)),
        shard_imbalance=float(d.get("shardImbalance", 0.0)),
    )

# ---- node / nodeclaim / nodeclass / pdb / lease (apiserver wire) -----------
# These ride the kube seam (kube/apiserver.py): everything the controllers
# read or write crosses the watch/list protocol as these dicts, the way the
# reference's objects cross the apiserver (SURVEY §2.1 #23 API types).


def _taint_to_dict(t: Taint) -> Dict:
    return {"key": t.key, "value": t.value, "effect": t.effect.value}


def _taint_from_dict(d: Mapping) -> Taint:
    return Taint(key=d["key"], value=d.get("value", ""),
                 effect=TaintEffect(d.get("effect", "NoSchedule")))


def node_to_dict(n) -> Dict:
    return {
        "name": n.name,
        "providerID": n.provider_id,
        "internalIP": n.internal_ip,
        "labels": dict(n.labels),
        "annotations": dict(n.annotations),
        "taints": [_taint_to_dict(t) for t in n.taints],
        "capacity": dict(n.capacity),
        "allocatable": dict(n.allocatable),
        "ready": n.ready,
        "createdAt": n.created_at,
        "nodePool": n.node_pool,
        "nodeClaim": n.node_claim,
    }


def node_from_dict(d: Mapping):
    from .objects import Node
    return Node(
        name=d["name"], provider_id=d.get("providerID", ""),
        internal_ip=d.get("internalIP"),
        labels=dict(d.get("labels", {})),
        annotations=dict(d.get("annotations", {})),
        taints=[_taint_from_dict(t) for t in d.get("taints", ())],
        capacity=dict(d.get("capacity", {})),
        allocatable=dict(d.get("allocatable", {})),
        ready=d.get("ready", False),
        created_at=d.get("createdAt", 0.0),
        node_pool=d.get("nodePool"),
        node_claim=d.get("nodeClaim"),
    )


def nodeclaim_to_dict(c) -> Dict:
    return {
        "name": c.name,
        "nodePool": c.node_pool,
        "requirements": [requirement_to_dict(r) for r in c.requirements],
        "resourceRequests": dict(c.resource_requests),
        "labels": dict(c.labels),
        "annotations": dict(c.annotations),
        "taints": [_taint_to_dict(t) for t in c.taints],
        "nodeClassRef": c.node_class_ref,
        "phase": c.phase.value,
        "maxPods": c.max_pods,
        "clusterDNS": c.cluster_dns,
        "providerID": c.provider_id,
        "internalIP": c.internal_ip,
        "instanceType": c.instance_type,
        "zone": c.zone,
        "capacityType": c.capacity_type,
        "imageID": c.image_id,
        "capacity": dict(c.capacity),
        "allocatable": dict(c.allocatable),
        "createdAt": c.created_at,
        "launchedAt": c.launched_at,
        "registeredAt": c.registered_at,
        "initializedAt": c.initialized_at,
        "deletionTimestamp": c.deletion_timestamp,
    }


def nodeclaim_from_dict(d: Mapping):
    from .objects import NodeClaim, NodeClaimPhase
    return NodeClaim(
        name=d["name"], node_pool=d.get("nodePool", ""),
        requirements=[requirement_from_dict(r)
                      for r in d.get("requirements", ())],
        resource_requests=dict(d.get("resourceRequests", {})),
        labels=dict(d.get("labels", {})),
        annotations=dict(d.get("annotations", {})),
        taints=[_taint_from_dict(t) for t in d.get("taints", ())],
        node_class_ref=d.get("nodeClassRef", "default"),
        phase=NodeClaimPhase(d.get("phase", "Pending")),
        max_pods=d.get("maxPods"),
        cluster_dns=d.get("clusterDNS"),
        provider_id=d.get("providerID"),
        internal_ip=d.get("internalIP"),
        instance_type=d.get("instanceType"),
        zone=d.get("zone"),
        capacity_type=d.get("capacityType"),
        image_id=d.get("imageID"),
        capacity=dict(d.get("capacity", {})),
        allocatable=dict(d.get("allocatable", {})),
        created_at=d.get("createdAt", 0.0),
        launched_at=d.get("launchedAt"),
        registered_at=d.get("registeredAt"),
        initialized_at=d.get("initializedAt"),
        deletion_timestamp=d.get("deletionTimestamp"),
    )


def _selector_term_to_dict(t) -> Dict:
    return {"tags": [list(kv) for kv in t.tags], "id": t.id, "name": t.name}


def _selector_term_from_dict(d: Mapping):
    from .objects import NodeClassSelectorTerm
    return NodeClassSelectorTerm(
        tags=tuple(tuple(kv) for kv in d.get("tags", ())),
        id=d.get("id"), name=d.get("name"))


def nodeclass_to_dict(nc) -> Dict:
    return {
        "name": nc.name,
        "amiFamily": nc.ami_family,
        "subnetSelectorTerms": [_selector_term_to_dict(t)
                                for t in nc.subnet_selector_terms],
        "securityGroupSelectorTerms": [_selector_term_to_dict(t)
                                       for t in nc.security_group_selector_terms],
        "amiSelectorTerms": [_selector_term_to_dict(t)
                             for t in nc.ami_selector_terms],
        "userData": nc.user_data,
        "role": nc.role,
        "instanceProfile": nc.instance_profile,
        "tags": dict(nc.tags),
        "blockDeviceMappings": [dict(b) for b in nc.block_device_mappings],
        "instanceStorePolicy": nc.instance_store_policy,
        "metadataOptions": {
            "httpEndpoint": nc.metadata_options.http_endpoint,
            "httpProtocolIPv6": nc.metadata_options.http_protocol_ipv6,
            "httpPutResponseHopLimit": nc.metadata_options.http_put_response_hop_limit,
            "httpTokens": nc.metadata_options.http_tokens,
        },
        "detailedMonitoring": nc.detailed_monitoring,
        "associatePublicIP": nc.associate_public_ip,
        "annotations": dict(nc.annotations),
        "statusSubnets": [dict(s) for s in nc.status_subnets],
        "statusSecurityGroups": [dict(s) for s in nc.status_security_groups],
        "statusAMIs": [dict(s) for s in nc.status_amis],
        "statusInstanceProfile": nc.status_instance_profile,
        "statusConditions": dict(nc.status_conditions),
    }


def nodeclass_from_dict(d: Mapping):
    from .objects import MetadataOptions, NodeClass
    mo = d.get("metadataOptions") or {}
    return NodeClass(
        name=d["name"],
        ami_family=d.get("amiFamily", "AL2023"),
        subnet_selector_terms=[_selector_term_from_dict(t)
                               for t in d.get("subnetSelectorTerms", ())],
        security_group_selector_terms=[
            _selector_term_from_dict(t)
            for t in d.get("securityGroupSelectorTerms", ())],
        ami_selector_terms=[_selector_term_from_dict(t)
                            for t in d.get("amiSelectorTerms", ())],
        user_data=d.get("userData"),
        role=d.get("role"),
        instance_profile=d.get("instanceProfile"),
        tags=dict(d.get("tags", {})),
        block_device_mappings=[dict(b)
                               for b in d.get("blockDeviceMappings", ())],
        instance_store_policy=d.get("instanceStorePolicy"),
        metadata_options=MetadataOptions(
            http_endpoint=mo.get("httpEndpoint", "enabled"),
            http_protocol_ipv6=mo.get("httpProtocolIPv6", "disabled"),
            http_put_response_hop_limit=mo.get("httpPutResponseHopLimit", 2),
            http_tokens=mo.get("httpTokens", "required")),
        detailed_monitoring=d.get("detailedMonitoring", False),
        associate_public_ip=d.get("associatePublicIP"),
        annotations=dict(d.get("annotations", {})),
        status_subnets=[dict(s) for s in d.get("statusSubnets", ())],
        status_security_groups=[dict(s)
                                for s in d.get("statusSecurityGroups", ())],
        status_amis=[dict(s) for s in d.get("statusAMIs", ())],
        status_instance_profile=d.get("statusInstanceProfile"),
        status_conditions=dict(d.get("statusConditions", {})),
    )


def pdb_to_dict(p) -> Dict:
    return {
        "name": p.name,
        "namespace": p.namespace,
        "labelSelector": dict(p.label_selector),
        "maxUnavailable": p.max_unavailable,
        "minAvailable": p.min_available,
    }


def pdb_from_dict(d: Mapping):
    from .objects import PodDisruptionBudget
    return PodDisruptionBudget(
        name=d["name"], namespace=d.get("namespace", "default"),
        label_selector=dict(d.get("labelSelector", {})),
        max_unavailable=d.get("maxUnavailable"),
        min_available=d.get("minAvailable"))


def lease_to_dict(l) -> Dict:
    return {"name": l.name, "ownerNode": l.owner_node,
            "createdAt": l.created_at}


def lease_from_dict(d: Mapping):
    from .objects import Lease
    return Lease(name=d["name"], owner_node=d.get("ownerNode"),
                 created_at=d.get("createdAt", 0.0))
