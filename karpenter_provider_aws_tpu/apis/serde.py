"""Wire (de)serialization for the API objects.

The solver sidecar (parallel/sidecar.py) ships Pods/NodePools/cluster
state across a process boundary the way the reference's controller ships
kube objects over the API server watch stream (SURVEY §2.3 communication
backend). JSON keeps the wire format language-neutral: a non-Python
controller can build these payloads directly.

Round-trip contract: ``pod_from_dict(pod_to_dict(p))`` produces a Pod that
schedules identically (same scheduling signature), and likewise for every
other object here.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional

import numpy as np

from .objects import (
    DisruptionBudget, KubeletSpec, NodePool, NodePoolDisruption,
    PersistentVolumeClaim, Pod, PodAffinityTerm, PreferredRequirement,
    StorageClass, Taint, TaintEffect, Toleration, TopologySpreadConstraint,
)
from .requirements import Operator, Requirement

# ---- requirements ----------------------------------------------------------


def requirement_to_dict(r: Requirement) -> Dict:
    out = {"key": r.key, "operator": r.operator.value,
           "values": list(r.values)}
    if r.min_values is not None:
        out["minValues"] = r.min_values
    return out


def requirement_from_dict(d: Mapping) -> Requirement:
    return Requirement(key=d["key"], operator=Operator(d["operator"]),
                       values=tuple(d.get("values", ())),
                       min_values=d.get("minValues"))


# ---- pod -------------------------------------------------------------------


def pod_to_dict(p: Pod) -> Dict:
    return {
        "name": p.name,
        "namespace": p.namespace,
        "labels": dict(p.labels),
        "annotations": dict(p.annotations),
        "requests": {k: str(v) for k, v in p.requests.items()},
        "nodeSelector": dict(p.node_selector),
        "requiredAffinity": [requirement_to_dict(r) for r in p.required_affinity],
        "preferredAffinity": [
            {"requirement": requirement_to_dict(pr.requirement),
             "weight": pr.weight} for pr in p.preferred_affinity],
        "tolerations": [
            {"key": t.key, "operator": t.operator, "value": t.value,
             "effect": t.effect.value if t.effect is not None else None}
            for t in p.tolerations],
        "topologySpread": [
            {"maxSkew": c.max_skew, "topologyKey": c.topology_key,
             "whenUnsatisfiable": c.when_unsatisfiable,
             "labelSelector": [list(kv) for kv in c.label_selector]}
            for c in p.topology_spread],
        "podAffinity": [
            {"topologyKey": t.topology_key,
             "labelSelector": [list(kv) for kv in t.label_selector],
             "anti": t.anti} for t in p.pod_affinity],
        "volumeClaims": list(p.volume_claims),
        "nodeName": p.node_name,
        "owner": p.owner,
        "isDaemonset": p.is_daemonset,
        "priority": p.priority,
    }


def pod_from_dict(d: Mapping) -> Pod:
    return Pod(
        name=d["name"],
        namespace=d.get("namespace", "default"),
        labels=dict(d.get("labels", {})),
        annotations=dict(d.get("annotations", {})),
        requests=dict(d.get("requests", {})),
        node_selector=dict(d.get("nodeSelector", {})),
        required_affinity=[requirement_from_dict(r)
                           for r in d.get("requiredAffinity", ())],
        preferred_affinity=[
            PreferredRequirement(
                requirement=requirement_from_dict(pr["requirement"]),
                weight=pr.get("weight", 1))
            for pr in d.get("preferredAffinity", ())],
        tolerations=[
            Toleration(key=t.get("key", ""), operator=t.get("operator", "Equal"),
                       value=t.get("value", ""),
                       effect=(TaintEffect(t["effect"])
                               if t.get("effect") else None))
            for t in d.get("tolerations", ())],
        topology_spread=[
            TopologySpreadConstraint(
                max_skew=c["maxSkew"], topology_key=c["topologyKey"],
                when_unsatisfiable=c.get("whenUnsatisfiable", "DoNotSchedule"),
                label_selector=tuple(tuple(kv) for kv in c.get("labelSelector", ())))
            for c in d.get("topologySpread", ())],
        pod_affinity=[
            PodAffinityTerm(
                topology_key=t["topologyKey"],
                label_selector=tuple(tuple(kv) for kv in t.get("labelSelector", ())),
                anti=t.get("anti", False))
            for t in d.get("podAffinity", ())],
        volume_claims=list(d.get("volumeClaims", ())),
        node_name=d.get("nodeName"),
        owner=d.get("owner"),
        is_daemonset=d.get("isDaemonset", False),
        priority=d.get("priority", 0),
    )


# ---- nodepool --------------------------------------------------------------


def nodepool_to_dict(p: NodePool) -> Dict:
    return {
        "name": p.name,
        "weight": p.weight,
        "labels": dict(p.labels),
        "annotations": dict(p.annotations),
        "requirements": [requirement_to_dict(r) for r in p.requirements],
        "taints": [{"key": t.key, "value": t.value, "effect": t.effect.value}
                   for t in p.taints],
        "startupTaints": [{"key": t.key, "value": t.value,
                           "effect": t.effect.value}
                          for t in p.startup_taints],
        "limits": {k: str(v) for k, v in p.limits.items()},
        "disruption": {
            "consolidationPolicy": p.disruption.consolidation_policy,
            "consolidateAfter": p.disruption.consolidate_after,
            "expireAfter": p.disruption.expire_after,
            "budgets": [
                {"nodes": b.nodes, "schedule": b.schedule,
                 "duration": b.duration, "reasons": list(b.reasons)}
                for b in p.disruption.budgets],
        },
        "nodeClassRef": p.node_class_ref,
        "kubelet": ({"maxPods": p.kubelet.max_pods,
                     "clusterDNS": p.kubelet.cluster_dns}
                    if p.kubelet is not None else None),
    }


def nodepool_from_dict(d: Mapping) -> NodePool:
    dis = d.get("disruption", {})
    return NodePool(
        name=d["name"],
        weight=d.get("weight", 0),
        labels=dict(d.get("labels", {})),
        annotations=dict(d.get("annotations", {})),
        requirements=[requirement_from_dict(r)
                      for r in d.get("requirements", ())],
        taints=[Taint(key=t["key"], value=t.get("value", ""),
                      effect=TaintEffect(t.get("effect", "NoSchedule")))
                for t in d.get("taints", ())],
        startup_taints=[Taint(key=t["key"], value=t.get("value", ""),
                              effect=TaintEffect(t.get("effect", "NoSchedule")))
                        for t in d.get("startupTaints", ())],
        limits=dict(d.get("limits", {})),
        disruption=NodePoolDisruption(
            consolidation_policy=dis.get("consolidationPolicy",
                                         "WhenUnderutilized"),
            consolidate_after=dis.get("consolidateAfter"),
            expire_after=dis.get("expireAfter"),
            budgets=[DisruptionBudget(
                nodes=b.get("nodes", "10%"), schedule=b.get("schedule"),
                duration=b.get("duration"),
                reasons=tuple(b.get("reasons", ())))
                for b in dis.get("budgets", [{}])]),
        node_class_ref=d.get("nodeClassRef", "default"),
        kubelet=(KubeletSpec(max_pods=d["kubelet"].get("maxPods"),
                             cluster_dns=d["kubelet"].get("clusterDNS"))
                 if d.get("kubelet") else None),
    )


# ---- volumes ---------------------------------------------------------------


def pvc_to_dict(c: PersistentVolumeClaim) -> Dict:
    return {"name": c.name, "storageClass": c.storage_class,
            "boundZone": c.bound_zone}


def pvc_from_dict(d: Mapping) -> PersistentVolumeClaim:
    return PersistentVolumeClaim(name=d["name"],
                                 storage_class=d.get("storageClass", ""),
                                 bound_zone=d.get("boundZone"))


def storage_class_to_dict(s: StorageClass) -> Dict:
    return {"name": s.name, "zones": list(s.zones),
            "bindingMode": s.binding_mode, "provisioner": s.provisioner}


def storage_class_from_dict(d: Mapping) -> StorageClass:
    return StorageClass(name=d["name"], zones=tuple(d.get("zones", ())),
                        binding_mode=d.get("bindingMode",
                                           "WaitForFirstConsumer"),
                        provisioner=d.get("provisioner", "ebs.csi.aws.com"))


# ---- solver-side objects ---------------------------------------------------


def existing_bin_to_dict(b) -> Dict:
    return {
        "name": b.name, "nodePool": b.node_pool,
        "instanceType": b.instance_type, "zone": b.zone,
        "capacityType": b.capacity_type,
        "used": np.asarray(b.used, dtype=float).tolist(),
        # per-element null = axis the node did not report (NaN sentinel);
        # NaN itself is not representable in strict RFC 8259 JSON and the
        # wire must stay cross-language
        "allocOverride": ([None if np.isnan(x) else x
                           for x in np.asarray(b.alloc_override, dtype=float)]
                          if b.alloc_override is not None else None),
        "labels": dict(b.labels),
    }


def existing_bin_from_dict(d: Mapping):
    from ..solver.problem import ExistingBin
    return ExistingBin(
        name=d["name"], node_pool=d["nodePool"],
        instance_type=d["instanceType"], zone=d["zone"],
        capacity_type=d["capacityType"],
        used=np.asarray(d["used"], dtype=np.float32),
        alloc_override=(np.asarray(
            [np.nan if x is None else x for x in d["allocOverride"]],
            dtype=np.float32)
            if d.get("allocOverride") is not None else None),
        labels=dict(d.get("labels", {})),
    )


def plan_to_dict(plan) -> Dict:
    return {
        "newNodes": [
            {"nodePool": n.node_pool, "instanceType": n.instance_type,
             "zone": n.zone, "capacityType": n.capacity_type,
             "pricePerHour": n.price_per_hour, "pods": list(n.pods),
             "feasibleTypes": list(n.feasible_types),
             "feasibleZones": list(n.feasible_zones),
             "feasibleCapacityTypes": list(n.feasible_capacity_types),
             "extraLabels": dict(n.extra_labels)}
            for n in plan.new_nodes],
        "existingAssignments": {k: list(v)
                                for k, v in plan.existing_assignments.items()},
        "unschedulable": dict(plan.unschedulable),
        "newNodeCost": plan.new_node_cost,
        "solveSeconds": plan.solve_seconds,
        "deviceSeconds": plan.device_seconds,
        "warnings": list(plan.warnings),
    }


def plan_from_dict(d: Mapping):
    from ..solver.solve import NodePlan, PlannedNode
    return NodePlan(
        new_nodes=[
            PlannedNode(
                node_pool=n["nodePool"], instance_type=n["instanceType"],
                zone=n["zone"], capacity_type=n["capacityType"],
                price_per_hour=n["pricePerHour"], pods=list(n["pods"]),
                feasible_types=list(n.get("feasibleTypes", ())),
                feasible_zones=list(n.get("feasibleZones", ())),
                feasible_capacity_types=list(n.get("feasibleCapacityTypes", ())),
                extra_labels=dict(n.get("extraLabels", {})))
            for n in d.get("newNodes", ())],
        existing_assignments={k: list(v) for k, v in
                              d.get("existingAssignments", {}).items()},
        unschedulable=dict(d.get("unschedulable", {})),
        new_node_cost=d.get("newNodeCost", 0.0),
        solve_seconds=d.get("solveSeconds", 0.0),
        device_seconds=d.get("deviceSeconds", 0.0),
        warnings=list(d.get("warnings", ())),
    )
