from .resources import RESOURCE_AXES, R, resources_to_vec, resources_to_vec_checked, vec_to_resources
from .requirements import Operator, Requirement, Requirements
from .objects import (
    Taint,
    TaintEffect,
    Toleration,
    tolerates_all,
    TopologySpreadConstraint,
    PodAffinityTerm,
    PreferredRequirement,
    relax_pod,
    relaxation_depth,
    Pod,
    NodePoolDisruption,
    DisruptionBudget,
    NodePool,
    NodeClassSelectorTerm,
    PersistentVolumeClaim,
    StorageClass,
    PodDisruptionBudget,
    NodeClass,
    NodeClaim,
    Node,
)

__all__ = [
    "RESOURCE_AXES", "R", "resources_to_vec", "resources_to_vec_checked", "vec_to_resources",
    "Operator", "Requirement", "Requirements",
    "Taint", "TaintEffect", "Toleration", "tolerates_all",
    "TopologySpreadConstraint", "PodAffinityTerm", "PreferredRequirement",
    "relax_pod", "relaxation_depth", "Pod",
    "NodePoolDisruption", "DisruptionBudget", "NodePool",
    "NodeClassSelectorTerm", "NodeClass", "NodeClaim", "Node",
    "PersistentVolumeClaim", "StorageClass", "PodDisruptionBudget",
]
