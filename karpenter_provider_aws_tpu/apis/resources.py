"""Canonical resource axes for the solver's dense resource vectors.

The reference models resources as open string->Quantity maps
(corev1.ResourceList); the device solver needs a fixed dense axis, so we pin
the resource kinds the reference actually schedules on: cpu/memory/pods/
ephemeral-storage plus the AWS extended resources the instance-type provider
registers (reference pkg/providers/instancetype/types.go:176-192 — GPU,
Neuron, EFA, pod-ENI; pkg/apis/v1beta1/labels.go:89-116).

Canonical units (see utils.units): cpu millicores, memory/storage MiB,
everything else plain counts. All vectors are float32 on device.
"""

from __future__ import annotations

from typing import Dict, Mapping

import numpy as np

from ..utils.units import parse_cpu_millis, parse_mem_mib, parse_quantity

RESOURCE_AXES = (
    "cpu",                       # millicores
    "memory",                    # MiB
    "pods",                      # count (ENI-limited density lives here)
    "ephemeral-storage",         # MiB
    "nvidia.com/gpu",            # count
    "amd.com/gpu",               # count (reference types.go:176-192 maps
    "habana.ai/gaudi",           #   GPUs per manufacturer: nvidia/amd/habana)
    "aws.amazon.com/neuron",     # count
    "vpc.amazonaws.com/efa",     # count
    "vpc.amazonaws.com/pod-eni", # count
    "attachable-volumes",        # count: CSI volume attach slots (EBS shares
                                 # the instance's attachment slots with ENIs;
                                 # the reference discovers per-node limits
                                 # from CSINode at runtime —
                                 # website/…/troubleshooting.md:277-299)
)
R = len(RESOURCE_AXES)

_AXIS_INDEX: Dict[str, int] = {name: i for i, name in enumerate(RESOURCE_AXES)}

_PARSERS = {
    "cpu": parse_cpu_millis,
    "memory": parse_mem_mib,
    "ephemeral-storage": parse_mem_mib,
}


def resources_to_vec(resources: Mapping[str, "str | int | float"], *, implicit_pod: bool = False) -> np.ndarray:
    """Convert a resource map to the canonical float32 vector.

    Unknown resource names raise (better to fail loudly than silently drop a
    constraint); batch callers that must degrade per-pod instead of aborting
    the whole solve use ``resources_to_vec_checked``. ``implicit_pod=True``
    adds the 1-pod occupancy every real pod consumes (the density constraint
    the reference enforces via maxPods).
    """
    vec, unknown = resources_to_vec_checked(resources, implicit_pod=implicit_pod)
    if unknown:
        raise ValueError(f"unknown resource(s) {unknown}; known axes: {RESOURCE_AXES}")
    return vec


def resources_to_vec_checked(
    resources: Mapping[str, "str | int | float"], *, implicit_pod: bool = False
) -> "tuple[np.ndarray, tuple[str, ...]]":
    """Like resources_to_vec but returns ``(vec, unknown_names)`` so a batch
    solve can mark just the offending pod unschedulable (the reference treats
    an unregistered extended resource as an incompatibility for that pod only,
    never a scheduler abort)."""
    vec = np.zeros((R,), dtype=np.float32)
    unknown = []
    for name, qty in resources.items():
        idx = _AXIS_INDEX.get(name)
        if idx is None:
            unknown.append(name)
            continue
        vec[idx] = _PARSERS.get(name, parse_quantity)(qty)
    if implicit_pod:
        vec[_AXIS_INDEX["pods"]] = max(vec[_AXIS_INDEX["pods"]], 1.0)
    return vec, tuple(unknown)


def vec_to_resources(vec: np.ndarray) -> Dict[str, float]:
    """Inverse of resources_to_vec (values stay in canonical units)."""
    return {name: float(vec[i]) for i, name in enumerate(RESOURCE_AXES) if vec[i] != 0}


def vec_to_quantities(vec: np.ndarray) -> Dict[str, str]:
    """Canonical vector → k8s quantity strings, for status surfaces
    (the reference NodePool's status.resources): cpu in millicores
    ("12000m"), memory/ephemeral-storage in Mi, counts plain. Zero axes
    are omitted, like a resource list."""
    out: Dict[str, str] = {}
    for i, name in enumerate(RESOURCE_AXES):
        v = float(vec[i])
        if v == 0:
            continue
        if name == "cpu":
            out[name] = f"{int(round(v))}m"
        elif name in ("memory", "ephemeral-storage"):
            out[name] = f"{int(round(v))}Mi"
        else:
            out[name] = f"{int(round(v))}"
    return out


def canonical_to_vec(resources: Mapping[str, float],
                     missing: float = 0.0) -> np.ndarray:
    """Canonical-unit map (cpu millicores, memory MiB — e.g. a NodeClaim's
    status.capacity round-tripped through vec_to_resources) → vector.
    No quantity parsing: values are already in axis units. ``missing``
    fills axes the map does not mention — pass NaN when the caller wants
    to distinguish "not reported" from "zero" (a node's status rarely
    reports every axis; e.g. attachable-volumes comes from CSINode, which
    may not have registered yet)."""
    vec = np.full((R,), missing, dtype=np.float32)
    for name, qty in resources.items():
        idx = _AXIS_INDEX.get(name)
        if idx is not None:
            vec[idx] = float(qty)
    return vec


def axis(name: str) -> int:
    return _AXIS_INDEX[name]
