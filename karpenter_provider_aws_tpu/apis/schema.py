"""Machine-readable schemas for the API wire format + validation.

The analog of the reference's checked-in, CEL-validated CRDs
(reference pkg/apis/crds/karpenter.sh_nodepools.yaml:338-401 —
per-requirement ``minValues``, label-domain restrictions, operator
enums; :55-100 — disruption-budget node-count/duration patterns;
pkg/apis/v1beta1/ec2nodeclass.go:321-330 — inline CEL like
"role XOR instanceProfile"). Three artifacts come from ONE source of
truth here:

1. ``SCHEMAS[kind]`` — JSON Schema (2020-12) over the apis/serde wire
   dicts, with patterns/enums/bounds lifted from the reference CRDs.
2. ``CROSS_FIELD_RULES[kind]`` — the x-kubernetes-validations analog:
   (message, predicate) pairs for rules JSON Schema cannot express
   (CEL in the reference). Each carries its CEL-style text so the
   generated CRD documents the same contract machine-readably.
3. ``crd_document(kind)`` — a CRD-style YAML document embedding (1) as
   ``openAPIV3Schema`` and (2) as ``x-kubernetes-validations``;
   tools/gen_crds.py checks these into deploy/crds/.

``validate(kind, spec)`` runs both layers and returns error strings —
the apiserver admission chain (kube/client.py install_admission) runs it
BEFORE the semantic webhooks, so no invalid object crosses the seam.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Mapping, Optional, Tuple

# patterns lifted from the reference CRDs
LABEL_KEY_PATTERN = (r"^([a-z0-9]([-a-z0-9]*[a-z0-9])?"
                     r"(\.[a-z0-9]([-a-z0-9]*[a-z0-9])?)*(\/))?"
                     r"([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9]$")
LABEL_VALUE_PATTERN = r"^(([A-Za-z0-9][-A-Za-z0-9_.]*)?[A-Za-z0-9])?$"
BUDGET_NODES_PATTERN = r"^((100|[0-9]{1,2})%|[0-9]+)$"       # nodepools.yaml:96
QUANTITY_PATTERN = r"^[0-9]+(\.[0-9]+)?(m|k|Ki|Mi|Gi|Ti|M|G|T)?$"

_REQUIREMENT = {
    "type": "object",
    "properties": {
        "key": {"type": "string", "maxLength": 316,
                "pattern": LABEL_KEY_PATTERN},
        "operator": {"type": "string",
                     "enum": ["In", "NotIn", "Exists", "DoesNotExist",
                              "Gt", "Lt"]},
        "values": {"type": "array",
                   "items": {"type": "string", "maxLength": 63,
                             "pattern": LABEL_VALUE_PATTERN}},
        # ALPHA in the reference; 1..50 (nodepools.yaml:363-368)
        "minValues": {"type": ["integer", "null"],
                      "minimum": 1, "maximum": 50},
    },
    "required": ["key", "operator"],
    "additionalProperties": False,
}

_TAINT = {
    "type": "object",
    "properties": {
        "key": {"type": "string", "pattern": LABEL_KEY_PATTERN},
        "value": {"type": "string"},
        "effect": {"type": "string",
                   "enum": ["NoSchedule", "PreferNoSchedule", "NoExecute"]},
    },
    "required": ["key", "effect"],
    "additionalProperties": False,
}

_BUDGET = {
    "type": "object",
    "properties": {
        "nodes": {"type": "string", "pattern": BUDGET_NODES_PATTERN},
        "schedule": {"type": ["string", "null"]},
        # deviation from the reference CRD (nodepools.yaml:83 Go-duration
        # strings): OUR wire format carries canonical seconds — numeric,
        # like every other duration on this wire (consolidateAfter,
        # expireAfter). The x-kubernetes-validations budget rule still
        # enforces schedule↔duration pairing.
        "duration": {"type": ["number", "null"], "exclusiveMinimum": 0},
        "reasons": {"type": "array",
                    "items": {"type": "string",
                              "enum": ["Underutilized", "Empty", "Drifted",
                                       "Expired"]}},
    },
    "additionalProperties": False,
}

NODEPOOL_SCHEMA = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "properties": {
        "name": {"type": "string", "minLength": 1, "maxLength": 63},
        "weight": {"type": "integer", "minimum": 0, "maximum": 100},
        "labels": {"type": "object",
                   "propertyNames": {"pattern": LABEL_KEY_PATTERN,
                                     "maxLength": 316},
                   "additionalProperties": {"type": "string",
                                            "maxLength": 63,
                                            "pattern": LABEL_VALUE_PATTERN}},
        "annotations": {"type": "object",
                        "additionalProperties": {"type": "string"}},
        "requirements": {"type": "array", "items": _REQUIREMENT,
                         "maxItems": 30},             # nodepools.yaml:391
        "taints": {"type": "array", "items": _TAINT},
        "startupTaints": {"type": "array", "items": _TAINT},
        # serde stringifies limits on the wire; bare integers are also
        # accepted (hand-built specs). Fractional NUMBERS are not — write
        # "1.5" as a quantity string — which makes the CRD projection to
        # x-kubernetes-int-or-string exact, not just approximate.
        "limits": {"type": "object",
                   "additionalProperties": {
                       "anyOf": [
                           {"type": "integer", "minimum": 0},
                           {"type": "string",
                            "pattern": QUANTITY_PATTERN}]}},
        "disruption": {
            "type": "object",
            "properties": {
                "consolidationPolicy": {
                    "type": "string",
                    "enum": ["WhenUnderutilized", "WhenEmpty"]},
                "consolidateAfter": {"type": ["number", "string", "null"]},
                "expireAfter": {"type": ["number", "string", "null"]},
                "budgets": {"type": "array", "items": _BUDGET,
                            "maxItems": 50},
            },
            "additionalProperties": False,
        },
        "nodeClassRef": {"type": "string", "minLength": 1},
        "kubelet": {
            "type": ["object", "null"],
            "properties": {
                "maxPods": {"type": ["integer", "null"],
                            "minimum": 1, "maximum": 110},
                "clusterDNS": {"type": ["string", "null"]},
            },
            "additionalProperties": False,
        },
        # LEGACY location of controller-owned live usage: current servers
        # keep status.resources in the envelope's status sub-map
        # (spec/status split, kube/apiserver.py), and admission
        # normalization strips this key from any applied spec — it is
        # accepted here only so old exported YAML still applies cleanly
        "statusResources": {"type": "object",
                            "additionalProperties": {
                                "type": "string",
                                "pattern": QUANTITY_PATTERN}},
    },
    "required": ["name"],
    "additionalProperties": False,
}

_SELECTOR_TERM = {
    "type": "object",
    "properties": {
        "tags": {"type": "array",
                 "items": {"type": "array",
                           "prefixItems": [{"type": "string"},
                                           {"type": "string"}],
                           "minItems": 2, "maxItems": 2}},
        "id": {"type": ["string", "null"]},
        "name": {"type": ["string", "null"]},
    },
    "additionalProperties": False,
}

NODECLASS_SCHEMA = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "properties": {
        "name": {"type": "string", "minLength": 1, "maxLength": 63},
        "amiFamily": {"type": "string",
                      "enum": ["AL2", "AL2023", "Bottlerocket", "Ubuntu",
                               "Windows", "Custom"]},
        "subnetSelectorTerms": {"type": "array", "items": _SELECTOR_TERM,
                                "maxItems": 30},
        "securityGroupSelectorTerms": {"type": "array",
                                       "items": _SELECTOR_TERM,
                                       "maxItems": 30},
        "amiSelectorTerms": {"type": "array", "items": _SELECTOR_TERM,
                             "maxItems": 30},
        "userData": {"type": ["string", "null"]},
        "role": {"type": ["string", "null"]},
        "instanceProfile": {"type": ["string", "null"]},
        "tags": {"type": "object",
                 "additionalProperties": {"type": "string"}},
        "blockDeviceMappings": {
            "type": "array", "maxItems": 50,
            "items": {
                "type": "object",
                "properties": {
                    "device_name": {"type": "string"},
                    "root_volume": {"type": "boolean"},
                    "volume_size_mib": {"type": "number",
                                        "exclusiveMinimum": 0},
                },
                "additionalProperties": True,
            }},
        "instanceStorePolicy": {"type": ["string", "null"],
                                "enum": ["RAID0", None]},
        "metadataOptions": {
            "type": "object",
            "properties": {
                "httpEndpoint": {"type": "string",
                                 "enum": ["enabled", "disabled"]},
                "httpProtocolIPv6": {"type": "string",
                                     "enum": ["enabled", "disabled"]},
                "httpPutResponseHopLimit": {"type": "integer",
                                            "minimum": 1, "maximum": 64},
                "httpTokens": {"type": "string",
                               "enum": ["required", "optional"]},
            },
            "additionalProperties": False,
        },
        "detailedMonitoring": {"type": "boolean"},
        "associatePublicIP": {"type": ["boolean", "null"]},
        "annotations": {"type": "object",
                        "additionalProperties": {"type": "string"}},
        # status (controller-owned; accepted on the wire like a CRD's)
        "statusSubnets": {"type": "array",
                          "items": {"type": "object"}},
        "statusSecurityGroups": {"type": "array",
                                 "items": {"type": "object"}},
        "statusAMIs": {"type": "array", "items": {"type": "object"}},
        "statusInstanceProfile": {"type": ["string", "null"]},
        "statusConditions": {"type": "object",
                             "additionalProperties": {"type": "boolean"}},
    },
    "required": ["name"],
    "additionalProperties": False,
}

NODECLAIM_SCHEMA = {
    "$schema": "https://json-schema.org/draft/2020-12/schema",
    "type": "object",
    "properties": {
        "name": {"type": "string", "minLength": 1, "maxLength": 63},
        "nodePool": {"type": "string"},
        "requirements": {"type": "array", "items": _REQUIREMENT,
                         "maxItems": 100},
        "resourceRequests": {"type": "object",
                             "additionalProperties": {
                                 "type": ["string", "number"]}},
        "labels": {"type": "object",
                   "additionalProperties": {"type": "string"}},
        "annotations": {"type": "object",
                        "additionalProperties": {"type": "string"}},
        "taints": {"type": "array", "items": _TAINT},
        "nodeClassRef": {"type": "string"},
        "phase": {"type": "string",
                  "enum": ["Pending", "Launched", "Registered",
                           "Initialized", "Terminating", "Terminated"]},
        "maxPods": {"type": ["integer", "null"], "minimum": 1},
        "clusterDNS": {"type": ["string", "null"]},
        "providerID": {"type": ["string", "null"]},
        "internalIP": {"type": ["string", "null"]},
        "instanceType": {"type": ["string", "null"]},
        "zone": {"type": ["string", "null"]},
        "capacityType": {"type": ["string", "null"],
                         "enum": ["on-demand", "spot", None]},
        "imageID": {"type": ["string", "null"]},
        "capacity": {"type": "object",
                     "additionalProperties": {"type": "number"}},
        "allocatable": {"type": "object",
                        "additionalProperties": {"type": "number"}},
        "createdAt": {"type": "number"},
        "launchedAt": {"type": ["number", "null"]},
        "registeredAt": {"type": ["number", "null"]},
        "initializedAt": {"type": ["number", "null"]},
        "deletionTimestamp": {"type": ["number", "null"]},
    },
    "required": ["name", "nodePool"],
    "additionalProperties": False,
}

SCHEMAS: Dict[str, dict] = {
    "nodepools": NODEPOOL_SCHEMA,
    "nodeclasses": NODECLASS_SCHEMA,
    "nodeclaims": NODECLAIM_SCHEMA,
}


# ---------------------------------------------------------------------------
# Cross-field rules — the x-kubernetes-validations (CEL) analog.
# Each: (cel_text, message, predicate(spec) -> bool[valid]).
# ---------------------------------------------------------------------------


def _rule_in_has_values(spec: Mapping) -> bool:
    return all(r.get("values") for r in spec.get("requirements", ())
               if r.get("operator") == "In")


def _rule_gt_lt_single_int(spec: Mapping) -> bool:
    for r in spec.get("requirements", ()):
        if r.get("operator") in ("Gt", "Lt"):
            vals = r.get("values", ())
            if len(vals) != 1:
                return False
            try:
                if int(vals[0]) < 0:
                    return False
            except (TypeError, ValueError):
                return False
    return True


def _rule_min_values_coverage(spec: Mapping) -> bool:
    return all(len(r.get("values", ())) >= r["minValues"]
               for r in spec.get("requirements", ())
               if r.get("operator") == "In" and r.get("minValues"))


def _rule_exists_no_values(spec: Mapping) -> bool:
    return all(not r.get("values")
               for r in spec.get("requirements", ())
               if r.get("operator") in ("Exists", "DoesNotExist"))


def _rule_role_xor_profile(spec: Mapping) -> bool:
    return bool(spec.get("role")) != bool(spec.get("instanceProfile"))


def _rule_schedule_requires_duration(spec: Mapping) -> bool:
    return all(not b.get("schedule") or b.get("duration")
               for b in spec.get("disruption", {}).get("budgets", ()))


CROSS_FIELD_RULES: Dict[str, List[Tuple[str, str, Callable]]] = {
    "nodepools": [
        ("!has(self.requirements) || self.requirements.all(x, "
         "x.operator == 'In' ? x.values.size() != 0 : true)",
         "requirements with operator 'In' must have a value defined",
         _rule_in_has_values),
        ("!has(self.requirements) || self.requirements.all(x, "
         "(x.operator == 'Gt' || "
         "x.operator == 'Lt') ? (x.values.size() == 1 && "
         "int(x.values[0]) >= 0) : true)",
         "requirements operator 'Gt' or 'Lt' must have a single positive "
         "integer value",
         _rule_gt_lt_single_int),
        ("!has(self.requirements) || self.requirements.all(x, "
         "(x.operator == 'In' && "
         "has(x.minValues)) ? x.values.size() >= x.minValues : true)",
         "requirements with 'minValues' must have at least that many "
         "values specified in the 'values' field",
         _rule_min_values_coverage),
        ("!has(self.requirements) || self.requirements.all(x, "
         "(x.operator == 'Exists' || "
         "x.operator == 'DoesNotExist') ? x.values.size() == 0 : true)",
         "requirements with operator 'Exists' or 'DoesNotExist' must not "
         "have values",
         _rule_exists_no_values),
        ("!has(self.disruption) || !has(self.disruption.budgets) || "
         "self.disruption.budgets.all(b, has(b.schedule) ? "
         "has(b.duration) : true)",
         "budgets with a schedule must set a duration",
         _rule_schedule_requires_duration),
    ],
    "nodeclasses": [
        ("(has(self.role) && !has(self.instanceProfile)) || "
         "(!has(self.role) && has(self.instanceProfile))",
         "exactly one of role or instanceProfile is required",
         _rule_role_xor_profile),
    ],
    "nodeclaims": [
        ("!has(self.requirements) || self.requirements.all(x, "
         "x.operator == 'In' ? x.values.size() != 0 : true)",
         "requirements with operator 'In' must have a value defined",
         _rule_in_has_values),
    ],
}


# ---------------------------------------------------------------------------
# Validation entrypoint
# ---------------------------------------------------------------------------

_validators: Dict[str, object] = {}


def validate(kind: str, spec: Mapping) -> List[str]:
    """Schema + cross-field validation; returns error strings (empty =
    valid). The apiserver admission chain runs this before the semantic
    webhooks so nothing structurally invalid crosses the API seam."""
    schema = SCHEMAS.get(kind)
    if schema is None:
        return []
    import jsonschema
    v = _validators.get(kind)
    if v is None:
        v = jsonschema.Draft202012Validator(schema)
        _validators[kind] = v
    errs = [f"{'.'.join(str(p) for p in e.path) or '<root>'}: {e.message}"
            for e in v.iter_errors(dict(spec))]
    if errs:
        return errs   # cross-field rules assume structural validity
    for _cel, message, pred in CROSS_FIELD_RULES.get(kind, ()):
        try:
            if not pred(spec):
                errs.append(message)
        except Exception as e:
            errs.append(f"{message} (rule error: {e})")
    return errs


# ---------------------------------------------------------------------------
# CRD document generation (tools/gen_crds.py → deploy/crds/)
# ---------------------------------------------------------------------------

_KIND_META = {
    "nodepools": ("NodePool", "karpenter.tpu", "nodepools", "np"),
    "nodeclasses": ("TPUNodeClass", "karpenter.tpu", "nodeclasses", "tnc"),
    "nodeclaims": ("NodeClaim", "karpenter.tpu", "nodeclaims", "nc"),
}


def _to_structural(node):
    """JSON-Schema 2020-12 → Kubernetes *structural* schema: apiextensions
    v1 forbids type arrays (use ``nullable: true``), ``prefixItems``,
    ``propertyNames``, ``anyOf`` at value positions, and null enum
    members. Validation still runs the richer 2020-12 form; this lossy
    projection only shapes the deployable artifact."""
    if isinstance(node, list):
        return [_to_structural(x) for x in node]
    if not isinstance(node, dict):
        return node
    out = {}
    for k, v in node.items():
        if k == "propertyNames":
            continue   # inexpressible structurally; admission enforces it
        if k == "prefixItems":
            # tuple form -> plain item schema (bounds stay via min/maxItems)
            merged = {}
            for sub in v:
                merged.update(_to_structural(sub))
            out["items"] = merged
            continue
        if k == "anyOf":
            branches = [_to_structural(b) for b in v]
            types = {b.get("type") for b in branches}
            if types <= {"number", "integer", "string"} and len(types) > 1:
                # the k8s-native projection of a number-or-quantity-string
                # union (the reference CRDs use the same marker for
                # IntOrString fields)
                out["x-kubernetes-int-or-string"] = True
            elif branches:
                # otherwise keep the FIRST branch (schemas list the
                # widest branch first); admission still enforces the
                # full union
                out.update(branches[0])
            continue
        out[k] = _to_structural(v)
    t = out.get("type")
    if isinstance(t, list):
        non_null = [x for x in t if x != "null"]
        out["type"] = non_null[0] if non_null else "string"
        if "null" in t:
            out["nullable"] = True
    if isinstance(out.get("enum"), list) and None in out["enum"]:
        out["enum"] = [x for x in out["enum"] if x is not None]
        out["nullable"] = True
    if "exclusiveMinimum" in out and isinstance(out["exclusiveMinimum"],
                                                (int, float)):
        # draft-2020 numeric form -> OpenAPI v3 boolean form
        out["minimum"] = out.pop("exclusiveMinimum")
        out["exclusiveMinimum"] = True
    return out


def crd_document(kind: str) -> dict:
    """A CustomResourceDefinition-style document for the kind: the wire
    schema as openAPIV3Schema plus the cross-field rules as
    x-kubernetes-validations — byte-stable for check-in (reference checks
    in pkg/apis/crds/*.yaml the same way)."""
    kind_name, group, plural, short = _KIND_META[kind]
    schema = _to_structural(
        {k: v for k, v in SCHEMAS[kind].items() if k != "$schema"})
    schema["x-kubernetes-validations"] = [
        {"message": message, "rule": cel}
        for cel, message, _ in CROSS_FIELD_RULES.get(kind, ())]
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{group}"},
        "spec": {
            "group": group,
            "names": {"kind": kind_name, "plural": plural,
                      "shortNames": [short]},
            "scope": "Cluster",
            "versions": [{
                "name": "v1",
                "served": True,
                "storage": True,
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "properties": {"spec": schema},
                    "required": ["spec"],
                }},
            }],
        },
    }
