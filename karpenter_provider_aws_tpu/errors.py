"""Cloud error taxonomy.

Mirror of the reference's AWS error classification
(reference pkg/errors/errors.go:29-37 region: not-found, already-exists,
unfulfillable-capacity/ICE, launch-template-not-found) recast for the
framework's pluggable cloud backend. The solver feedback loop hangs off
``UnfulfillableCapacityError``: each (capacity_type, instance_type, zone)
offering it names is masked out of the next solve via the
UnavailableOfferings cache (reference pkg/providers/instance/instance.go:348-354).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

Offering = Tuple[str, str, str]  # (capacity_type, instance_type, zone)


class CloudError(Exception):
    """Base class for cloud backend errors."""


class NotFoundError(CloudError):
    pass


class AlreadyExistsError(CloudError):
    pass


@dataclass
class UnfulfillableCapacityError(CloudError):
    """Insufficient capacity for every offering attempted (the ICE case)."""

    offerings: List[Offering]

    def __post_init__(self):
        super().__init__(f"insufficient capacity for {len(self.offerings)} offering(s)")


class RateLimitedError(CloudError):
    pass


# ---- solver degradation taxonomy -------------------------------------------
#
# The device solve path can fail in ways the cloud taxonomy above never
# names: a problem whose group axis exceeds the largest compiled bucket,
# a bin table that cannot grow past its top bucket, an XLA compile error
# or device OOM on the pack call. Each is classified here so the solve
# ladder (solver/solve.py) can decide mechanically: capacity errors are
# NEVER retryable on the same path (the same input will exceed the same
# ceiling again) and route straight to the next degradation tier;
# device errors are presumed transient and earn a bounded retry before
# the host-FFD fallback engages.


class SolverError(Exception):
    """Base class for solver-path failures. ``retryable`` says whether
    re-running the SAME path with the SAME input could succeed."""

    retryable = False


class SolverCapacityError(SolverError):
    """The problem exceeds a structural ceiling of the device path (group
    bucket, bin-table growth exhausted). Terminal for that path: retrying
    cannot help, only degrading to wave-split or host FFD can."""

    retryable = False

    def __init__(self, message: str, axis: str = ""):
        super().__init__(message)
        self.axis = axis   # "G" | "B" | "" — which ceiling was hit


class SolverDeviceError(SolverError):
    """The device call itself failed (XLA compile error, device OOM,
    transfer failure). Presumed transient: the ladder retries once with
    backoff before falling back to the host path."""

    retryable = True

    def __init__(self, message: str, cause: BaseException = None):
        super().__init__(message)
        self.cause = cause


def is_retryable_solver_error(err: BaseException) -> bool:
    return isinstance(err, SolverError) and err.retryable


def is_not_found(err: BaseException) -> bool:
    return isinstance(err, NotFoundError)


def is_already_exists(err: BaseException) -> bool:
    return isinstance(err, AlreadyExistsError)


def is_unfulfillable_capacity(err: BaseException) -> bool:
    return isinstance(err, UnfulfillableCapacityError)
