"""Cloud error taxonomy.

Mirror of the reference's AWS error classification
(reference pkg/errors/errors.go:29-37 region: not-found, already-exists,
unfulfillable-capacity/ICE, launch-template-not-found) recast for the
framework's pluggable cloud backend. The solver feedback loop hangs off
``UnfulfillableCapacityError``: each (capacity_type, instance_type, zone)
offering it names is masked out of the next solve via the
UnavailableOfferings cache (reference pkg/providers/instance/instance.go:348-354).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

Offering = Tuple[str, str, str]  # (capacity_type, instance_type, zone)


class CloudError(Exception):
    """Base class for cloud backend errors."""


class NotFoundError(CloudError):
    pass


class AlreadyExistsError(CloudError):
    pass


@dataclass
class UnfulfillableCapacityError(CloudError):
    """Insufficient capacity for every offering attempted (the ICE case)."""

    offerings: List[Offering]

    def __post_init__(self):
        super().__init__(f"insufficient capacity for {len(self.offerings)} offering(s)")


class RateLimitedError(CloudError):
    pass


def is_not_found(err: BaseException) -> bool:
    return isinstance(err, NotFoundError)


def is_already_exists(err: BaseException) -> bool:
    return isinstance(err, AlreadyExistsError)


def is_unfulfillable_capacity(err: BaseException) -> bool:
    return isinstance(err, UnfulfillableCapacityError)
