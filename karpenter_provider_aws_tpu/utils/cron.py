"""Minimal 5-field cron matching for disruption-budget schedules.

The reference's NodePool disruption budgets take a crontab ``schedule``
plus a ``duration``; the budget only constrains disruptions while inside
an active window (reference website concepts/disruption.md:193-222; CRD
karpenter.sh_nodepools.yaml:97-112 requires schedule and duration
together). Supported field syntax: ``*``, numbers, comma lists, ranges
(``a-b``) and steps (``*/n``, ``a-b/n``) — the subset the reference's
docs exercise (e.g. ``@ 0 9 * * 1-5`` style windows written as
``0 9 * * 1-5``). Times are UTC, like the reference.
"""

from __future__ import annotations

import time
from typing import Sequence, Set

_FIELD_RANGES = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 6))


def _parse_field(spec: str, lo: int, hi: int) -> Set[int]:
    out: Set[int] = set()
    for part in spec.split(","):
        step, stepped = 1, False
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
            stepped = True
            if step < 1:
                raise ValueError(f"bad cron step {step_s!r}")
        if part == "*":
            start, end = lo, hi
        elif part == "":
            # a bare empty part is a typo ('0, 0 * * *'); silently
            # expanding it to match-all would widen the window 60x
            raise ValueError("empty cron field part (stray comma?)")
        elif "-" in part:
            a, b = part.split("-", 1)
            start, end = int(a), int(b)
        elif stepped:
            # 'N/step' means N through max stepped (vixie/robfig
            # semantics: '0/6' in the hour field = 0,6,12,18 — and
            # '0/1' every hour, NOT just hour 0)
            start, end = int(part), hi
        else:
            start = end = int(part)
        if not (lo <= start <= hi and lo <= end <= hi and start <= end):
            raise ValueError(f"cron field value out of range: {part!r}")
        out.update(range(start, end + 1, step))
    return out


class Cron:
    """A parsed 5-field crontab expression; ``matches(ts)`` tests a UTC
    epoch timestamp against minute/hour/dom/month/dow."""

    def __init__(self, expr: str):
        fields: Sequence[str] = expr.split()
        if len(fields) != 5:
            raise ValueError(f"cron needs 5 fields, got {expr!r}")
        self.minute, self.hour, self.dom, self.month, self.dow = (
            _parse_field(f, lo, hi)
            for f, (lo, hi) in zip(fields, _FIELD_RANGES))
        # like standard cron: when BOTH day fields are restricted the
        # match is an OR; the reference's windows use one or the other,
        # and the simple AND is what its docs' examples imply — keep AND
        # unless both are restricted, then OR (vixie-cron behavior)
        self._dom_star = fields[2] == "*"
        self._dow_star = fields[4] == "*"

    def matches(self, ts: float) -> bool:
        t = time.gmtime(ts)
        if t.tm_min not in self.minute or t.tm_hour not in self.hour \
                or t.tm_mon not in self.month:
            return False
        wday = (t.tm_wday + 1) % 7  # gmtime: Mon=0; cron: Sun=0
        dom_ok = t.tm_mday in self.dom
        dow_ok = wday in self.dow
        if self._dom_star or self._dow_star:
            return dom_ok and dow_ok
        return dom_ok or dow_ok

    def in_window(self, ts: float, duration: float) -> bool:
        """Is ``ts`` inside a window opened by a matching minute and
        lasting ``duration`` seconds? (cron fires at whole minutes; scan
        back over every minute the window could have opened at)."""
        m = int(ts) // 60 * 60
        lookback = int(max(duration, 0.0) + 59) // 60
        for k in range(lookback + 1):
            occ = m - k * 60
            if occ <= ts < occ + duration and self.matches(occ):
                return True
        return False
