"""Structured logging + change-noise suppression.

Mirror of the reference's logging surface (SURVEY §5): zap-style
structured logs (knative ``logging.FromContext``) and the
``pretty.ChangeMonitor`` idiom — controllers that reconcile every few
seconds log a fact only when it CHANGES, not on every pass (reference
pkg/providers/instancetype/instancetype.go:150-152 logs the discovered
instance-type count only on delta).

Python side: stdlib logging with a key=value structured formatter, one
logger per component under the "karpenter" root, and a ChangeMonitor
whose entries expire so a steady state is re-asserted once per TTL (the
reference expires entries after 24h).
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional, Tuple

from .clock import Clock

_ROOT = "karpenter"
_configured = False
_configure_lock = threading.Lock()


class _KVFormatter(logging.Formatter):
    """`ts level logger message key=value ...` — grep-friendly, one line."""

    def format(self, record: logging.LogRecord) -> str:
        base = (f"{self.formatTime(record, '%Y-%m-%dT%H:%M:%S')} "
                f"{record.levelname} {record.name} {record.getMessage()}")
        extra = getattr(record, "kv", None)
        if extra:
            base += " " + " ".join(f"{k}={v}" for k, v in sorted(extra.items()))
        return base


def configure(level: str = "INFO") -> None:
    """Install the structured handler on the karpenter root (idempotent;
    re-invocation only adjusts the level — the CLI's --log-level)."""
    global _configured
    with _configure_lock:
        root = logging.getLogger(_ROOT)
        root.setLevel(getattr(logging, level.upper(), logging.INFO))
        if not _configured:
            h = logging.StreamHandler()
            h.setFormatter(_KVFormatter())
            root.addHandler(h)
            root.propagate = False
            _configured = True


def get_logger(component: str) -> "StructuredLogger":
    return StructuredLogger(logging.getLogger(f"{_ROOT}.{component}"))


def _current_span():
    """The ambient trace span, or None. Imported lazily (and cached) so
    this module stays importable before/without the trace package."""
    global _trace_current
    if _trace_current is None:
        try:
            from ..trace import current as _trace_current
        except Exception:
            def _trace_current():
                return None
    return _trace_current()


_trace_current = None


class StructuredLogger:
    """Thin facade adding key=value fields: log.info("msg", key=val).

    A line emitted inside an active trace span carries ``trace=<id>``
    automatically, so grep output correlates with ``/debug/traces``
    (`kpctl trace show <id>`) and with burn-triggered profile captures —
    the log line, the span tree, and the profile snapshot of one slow
    pass all share the id. Free when tracing is off (one attribute
    read, trace/span.py's disabled fast path)."""

    def __init__(self, logger: logging.Logger):
        self._logger = logger

    def _log(self, level: int, msg: str, kv: dict) -> None:
        if self._logger.isEnabledFor(level):
            sp = _current_span()
            if sp is not None and "trace" not in kv:
                kv["trace"] = sp.trace_id
            self._logger.log(level, msg, extra={"kv": kv})

    def debug(self, msg: str, **kv) -> None:
        self._log(logging.DEBUG, msg, kv)

    def info(self, msg: str, **kv) -> None:
        self._log(logging.INFO, msg, kv)

    def warning(self, msg: str, **kv) -> None:
        self._log(logging.WARNING, msg, kv)

    def error(self, msg: str, **kv) -> None:
        self._log(logging.ERROR, msg, kv)


class ChangeMonitor:
    """Log-on-delta gate (reference pretty.ChangeMonitor): ``has_changed``
    returns True the first time a key is seen, whenever its value
    differs from the last observation, or after the TTL re-arms it — so
    a 10 s reconcile loop states a steady fact once per TTL instead of
    8,640 times a day."""

    def __init__(self, clock: Optional[Clock] = None, ttl: float = 24 * 3600.0):
        self._clock = clock or Clock()
        self._ttl = ttl
        self._lock = threading.Lock()
        self._seen: Dict[str, Tuple[object, float]] = {}

    def has_changed(self, key: str, value: object) -> bool:
        now = self._clock.now()
        with self._lock:
            prev = self._seen.get(key)
            if prev is not None and prev[0] == value and now - prev[1] < self._ttl:
                return False
            self._seen[key] = (value, now)
            return True
