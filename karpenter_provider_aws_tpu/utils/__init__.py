from .units import parse_quantity, format_quantity, parse_cpu_millis, parse_mem_mib

__all__ = ["parse_quantity", "format_quantity", "parse_cpu_millis", "parse_mem_mib"]
