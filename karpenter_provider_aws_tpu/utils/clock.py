"""Injectable clock (real + fake) for controllers, caches, and batchers.

The reference threads a `clock.Clock` through every controller
(reference cmd/controller/main.go:48) so tests can step time; same here.
"""

from __future__ import annotations

import threading
import time


class Clock:
    """Wall clock."""

    def now(self) -> float:
        return time.time()

    def monotonic(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


# The shared wall-clock instance for fallback paths: subsystems that
# accept an injected clock but default to wall time (sampler, profiler,
# apiserver) fall back to THIS rather than calling time.time() raw, so
# the clock-discipline lint (tools/lint, docs/reference/linting.md) can
# verify every time read in the package flows through a Clock.
WALL = Clock()


class FakeClock(Clock):
    """Deterministic clock for tests: time moves only via step()."""

    def __init__(self, start: float = 1_000_000.0):
        self._t = start
        self._lock = threading.Lock()

    def now(self) -> float:
        with self._lock:
            return self._t

    def monotonic(self) -> float:
        return self.now()

    def sleep(self, seconds: float) -> None:
        self.step(seconds)

    def step(self, seconds: float) -> None:
        with self._lock:
            self._t += seconds
