"""Bounded parallel fan-out — the workqueue.ParallelizeUntil analog.

The reference fans interruption messages 10-way
(pkg/controllers/interruption/controller.go:104) and garbage-collection
existence checks 100-way
(pkg/controllers/nodeclaim/garbagecollection/controller.go:78). Host-side
work here is I/O-shaped (cloud API calls), so threads are the right
primitive; device work never goes through this path.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, List, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")


def parallelize(workers: int, items: Sequence[T],
                fn: Callable[[T], R]) -> List[R]:
    """Apply ``fn`` to every item with at most ``workers`` concurrent
    calls; results keep item order. Exceptions propagate after all
    submitted work drains (first one wins), matching ParallelizeUntil's
    fail-late behavior for a finite work list."""
    if not items:
        return []
    if workers <= 1 or len(items) == 1:
        return [fn(i) for i in items]
    with ThreadPoolExecutor(max_workers=min(workers, len(items))) as pool:
        return list(pool.map(fn, items))
