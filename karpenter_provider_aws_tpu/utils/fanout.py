"""Bounded parallel fan-out — the workqueue.ParallelizeUntil analog.

The reference fans interruption messages 10-way
(pkg/controllers/interruption/controller.go:104) and garbage-collection
existence checks 100-way
(pkg/controllers/nodeclaim/garbagecollection/controller.go:78). Host-side
work here is I/O-shaped (cloud API calls), so threads are the right
primitive; device work never goes through this path.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional, Sequence, TypeVar

T = TypeVar("T")
R = TypeVar("R")


def parallelize(workers: int, items: Sequence[T], fn: Callable[[T], R],
                pool: Optional[ThreadPoolExecutor] = None) -> List[R]:
    """Apply ``fn`` to every item with at most ``workers`` concurrent
    calls; results keep item order. Exceptions propagate after all
    submitted work drains (first one wins), matching ParallelizeUntil's
    fail-late behavior for a finite work list.

    Pass a persistent ``pool`` (see :class:`LazyPool`) from per-pass
    callers — spinning up a fresh executor every reconcile tick costs more
    than the fan-out saves against fast backends."""
    if not items:
        return []
    if workers <= 1 or len(items) == 1:
        return [fn(i) for i in items]
    if pool is not None:
        return list(pool.map(fn, items))
    with ThreadPoolExecutor(max_workers=min(workers, len(items))) as ex:
        return list(ex.map(fn, items))


class LazyPool:
    """A lazily-created, reused ThreadPoolExecutor for a controller's
    per-reconcile fan-out (the reference's workqueue holds its goroutine
    pool for the controller's lifetime the same way)."""

    def __init__(self, workers: int, name: str = "fanout"):
        self.workers = workers
        self.name = name
        self._pool: Optional[ThreadPoolExecutor] = None
        self._lock = threading.Lock()

    def get(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=self.workers,
                    thread_name_prefix=self.name)
            return self._pool

    def run(self, items: Sequence[T], fn: Callable[[T], R]) -> List[R]:
        if not items or len(items) == 1:
            return [fn(i) for i in items]
        return parallelize(self.workers, items, fn, pool=self.get())
