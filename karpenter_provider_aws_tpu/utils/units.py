"""Kubernetes resource-quantity parsing.

Mirrors the semantics the reference gets from apimachinery's
``resource.Quantity`` (used pervasively, e.g. reference
pkg/providers/instancetype/types.go for capacity/overhead math), implemented
from scratch: plain numbers, decimal SI suffixes (k, M, G, T, P, E, m for
milli) and binary suffixes (Ki, Mi, Gi, Ti, Pi, Ei).

Internal canonical units for the solver's resource vectors (chosen so float32
device tensors stay exact for realistic magnitudes):

- cpu:                millicores   (``parse_cpu_millis``)
- memory / storage:   MiB          (``parse_mem_mib``)
- counted resources:  plain counts
"""

from __future__ import annotations

import re

_BINARY = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}
_DECIMAL = {"k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18, "m": 1e-3, "": 1.0}

_QTY_RE = re.compile(r"^\s*([+-]?[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)\s*([A-Za-z]*)\s*$")


def parse_quantity(s: "str | int | float") -> float:
    """Parse a k8s-style quantity string to a float in base units."""
    if isinstance(s, (int, float)):
        return float(s)
    m = _QTY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity: {s!r}")
    num, suffix = m.groups()
    value = float(num)
    if suffix in _BINARY:
        return value * _BINARY[suffix]
    if suffix in _DECIMAL:
        return value * _DECIMAL[suffix]
    raise ValueError(f"invalid quantity suffix: {s!r}")


def parse_cpu_millis(s: "str | int | float") -> float:
    """CPU quantity -> millicores. '1' -> 1000, '100m' -> 100, '2.5' -> 2500."""
    return parse_quantity(s) * 1000.0


def parse_mem_mib(s: "str | int | float") -> float:
    """Memory/storage quantity -> MiB. '1Gi' -> 1024, '512Mi' -> 512, 1073741824 -> 1024."""
    return parse_quantity(s) / float(2**20)


def format_quantity(v: float) -> str:
    """Best-effort human format (for logs/events only — not round-trippable)."""
    for suffix, mult in (("Ei", 2**60), ("Pi", 2**50), ("Ti", 2**40), ("Gi", 2**30), ("Mi", 2**20), ("Ki", 2**10)):
        if v >= mult and (v / mult) == int(v / mult):
            return f"{int(v / mult)}{suffix}"
    if v == int(v):
        return str(int(v))
    return str(v)
