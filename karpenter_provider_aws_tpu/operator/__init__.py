from .operator import Operator
from .options import Options

__all__ = ["Operator", "Options"]
