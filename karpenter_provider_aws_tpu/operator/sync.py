"""StateSync: informers → ClusterState mirror (+ watch-driven config).

The analog of the core's cluster-state controller consuming informer
events (reference cmd/controller/main.go:50 ``state.NewCluster`` over the
manager's client; metrics.md:150-157 karpenter_cluster_state_synced).
Every kind the controllers read is watched:

- pods/nodes/nodeclaims/pvcs/storageclasses/pdbs/leases apply into the
  ClusterState mirror — the SAME object the deterministic stratum mutates
  directly, so controller read paths are identical across strata.
- nodepools/nodeclasses apply into the operator's config dicts: creating
  a NodePool through the API makes the provisioner see it on the next
  pass — watch-driven configuration, like the reference.

Appliers are deliberately tolerant of ordering (a pod can arrive before
its node; a claim after its node) because watch streams are per-kind.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..apis import serde
from ..apis.objects import NodeClaimPhase, NodePool
from ..kube.apiserver import FakeAPIServer
from ..kube.client import KubeClient
from ..kube.informer import InformerSet
from ..state.cluster import ClusterState
from ..utils.clock import Clock, WALL


class StateSync:
    def __init__(self, server: FakeAPIServer, cluster: ClusterState,
                 node_pools: Dict[str, NodePool],
                 node_classes: Dict[str, object],
                 synced_gauge=None, config_guard=None, recorder=None,
                 pods_state_gauge=None, clock: Clock = None):
        """``config_guard(pool, node_classes) -> Optional[str]`` runs the
        operator's CROSS-object config validations (os-vs-amiFamily,
        storage-config-vs-lattice) on watch-delivered NodePools — per-
        object admission cannot see across objects. A violating pool is
        NOT installed (and an InvalidConfig warning event publishes), the
        watch-stream analog of Operator.__init__ raising for
        programmatically-passed config."""
        self.cluster = cluster
        self.node_pools = node_pools
        self.node_classes = node_classes
        self._synced_gauge = synced_gauge
        self._config_guard = config_guard
        self._recorder = recorder
        self._pods_state_gauge = pods_state_gauge
        self._clock = clock if clock is not None else WALL
        self._pods_state_last = float("-inf")   # clock-driven throttle
        self.informers = InformerSet(server)
        # referents before dependents: config kinds, then volumes/budgets,
        # then claims/nodes, then PODS LAST — apply_pod_spec replays
        # bind_pod whose WaitForFirstConsumer zone pin needs the bound
        # node already in the mirror
        self.informers.add("nodepools", self._on_nodepool)
        self.informers.add("nodeclasses", self._on_nodeclass)
        self.informers.add("storageclasses", self._on_storage_class)
        self.informers.add("pvcs", self._on_pvc)
        self.informers.add("pdbs", self._on_pdb)
        self.informers.add("nodeclaims", self._on_claim)
        self.informers.add("nodes", self._on_node)
        self.informers.add("pods", self._on_pod)
        self.informers.add("leases", self._on_lease)

    # ---- drive -------------------------------------------------------------

    def sync_once(self) -> int:
        """Deterministic pump; returns events applied. Flips the synced
        gauge once every informer has listed (cluster_state_synced)."""
        n = self.informers.sync_once()
        if self._synced_gauge is not None and self.informers.has_synced:
            self._synced_gauge.set(1.0)
        if n and self._pods_state_gauge is not None:
            # pod phases just moved through the watch stream: re-render
            # karpenter_pods_state. Throttled on the INJECTED clock (the
            # pump runs at 20 Hz in the async runtime; the phase scan is
            # O(pods)) — under FakeClock the refresh cadence is
            # deterministic instead of leaking wall time
            now = self._clock.monotonic()
            if now - self._pods_state_last >= 0.5:
                self._pods_state_last = now
                self._pods_state_gauge.replace(
                    {(k,): float(v)
                     for k, v in self.cluster.pod_phase_counts().items()})
        return n

    def start(self) -> "StateSync":
        self.informers.start()
        return self

    def stop(self) -> None:
        self.informers.stop()

    @property
    def has_synced(self) -> bool:
        return self.informers.has_synced

    # ---- appliers ----------------------------------------------------------

    def _on_pod(self, type_, name, obj, old) -> None:
        if type_ == "DELETED":
            self.cluster.delete_pod(name)
            return
        self.cluster.apply_pod_spec(serde.pod_from_dict(obj["spec"]))

    def _on_node(self, type_, name, obj, old) -> None:
        if type_ == "DELETED":
            self.cluster.delete_node(name)
            return
        self.cluster.apply_node(serde.node_from_dict(obj["spec"]))

    def _on_claim(self, type_, name, obj, old) -> None:
        if type_ == "DELETED":
            self.cluster.delete_claim(name)
            return
        self.cluster.apply_claim(KubeClient.claim_from_envelope(obj))

    def _on_pvc(self, type_, name, obj, old) -> None:
        if type_ == "DELETED":
            self.cluster.delete_pvc(name)
            return
        self.cluster.apply_pvc(serde.pvc_from_dict(obj["spec"]))

    def _on_storage_class(self, type_, name, obj, old) -> None:
        if type_ == "DELETED":
            self.cluster.delete_storage_class(name)
            return
        self.cluster.add_storage_class(
            serde.storage_class_from_dict(obj["spec"]))

    def _on_pdb(self, type_, name, obj, old) -> None:
        if type_ == "DELETED":
            self.cluster.delete_pdb(name)
            return
        self.cluster.add_pdb(serde.pdb_from_dict(obj["spec"]))

    def _on_lease(self, type_, name, obj, old) -> None:
        if type_ == "DELETED":
            self.cluster.delete_lease(name)
            return
        if obj["spec"].get("election"):
            # leader-election leases are coordination state, not
            # kube-node-leases: keeping them out of the mirror keeps the
            # ownerless-lease GC off them (the real cluster separates
            # them by namespace)
            return
        self.cluster.add_lease(serde.lease_from_dict(obj["spec"]))

    def _install_pool(self, pool: NodePool) -> None:
        if self._config_guard is not None:
            err = self._config_guard(pool, self.node_classes)
            if err:
                if self._recorder is not None:
                    self._recorder.publish("Warning", "InvalidConfig",
                                           "NodePool", pool.name, err)
                self.node_pools.pop(pool.name, None)
                return
        self.node_pools[pool.name] = pool

    def _on_nodepool(self, type_, name, obj, old) -> None:
        if type_ == "DELETED":
            self.node_pools.pop(name, None)
            return
        # hydrate controller-owned status from the envelope (spec/status
        # split) so a watch re-delivery doesn't zero the typed pool's
        # live usage and trigger a spurious re-patch
        self._install_pool(serde.nodepool_apply_status(
            serde.nodepool_from_dict(obj["spec"]), obj.get("status")))

    def _on_nodeclass(self, type_, name, obj, old) -> None:
        if type_ == "DELETED":
            self.node_classes.pop(name, None)
            return
        self.node_classes[name] = serde.nodeclass_from_dict(obj["spec"])
        # a class change can invalidate (or cure) pools referencing it:
        # re-run the cross-object guard over the server's pool set
        pools_inf = self.informers.informers.get("nodepools")
        if pools_inf is not None:
            for pname, spec in pools_inf.specs().items():
                pool = serde.nodepool_from_dict(spec)
                if pool.node_class_ref == name:
                    self._install_pool(pool)
