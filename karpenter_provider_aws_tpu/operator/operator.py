"""Operator: dependency wiring + the controller run loop.

Mirror of the reference operator (reference pkg/operator/operator.go:92-186
builds the session and all providers; cmd/controller/main.go:32-72 wires
cloudprovider → core+provider controllers → manager). Here the "session"
is the pluggable cloud backend, the providers are the lattice/ICE-cache/
cloudprovider stack, and the manager is a deterministic `run_once()` /
`run(duration)` loop over the controllers — clock-driven so the whole
control plane is simulable in tests (the reference's envtest stratum).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..apis.objects import NodeClass, NodePool
from ..cache.unavailable import UnavailableOfferings
from ..cloud.fake import FakeCloud
from ..cloudprovider.cloudprovider import CloudProvider
from ..controllers.disruption import DisruptionController
from ..controllers.garbagecollection import GarbageCollectionController
from ..controllers.lifecycle import LifecycleController
from ..controllers.provisioning import Provisioner
from ..controllers.termination import TerminationController
from ..events import Recorder
from ..lattice.tensors import Lattice, build_lattice
from ..solver.solve import Solver
from ..state.cluster import ClusterState
from ..utils.clock import Clock, FakeClock
from .options import Options


class Operator:
    def __init__(self, options: Optional[Options] = None,
                 lattice: Optional[Lattice] = None,
                 cloud: Optional[FakeCloud] = None,
                 clock: Optional[Clock] = None,
                 node_pools: Optional[Sequence[NodePool]] = None,
                 node_classes: Optional[Dict[str, NodeClass]] = None):
        self.options = options or Options()
        self.options.validate()
        self.clock = clock or Clock()
        self.lattice = lattice if lattice is not None else build_lattice(
            vm_memory_overhead_percent=self.options.vm_memory_overhead_percent,
            reserved_enis=self.options.reserved_enis)
        self.cloud = cloud or FakeCloud(self.clock)
        # connectivity probe before anything else (operator.go:115-117)
        self.cloud.list_instances()
        self.recorder = Recorder(self.clock)
        self.unavailable = UnavailableOfferings(self.clock)
        self.cluster = ClusterState(self.clock)
        self.node_pools: Dict[str, NodePool] = {p.name: p for p in (node_pools or [NodePool(name="default")])}
        self.node_classes: Dict[str, NodeClass] = node_classes or {"default": NodeClass(name="default")}
        self.cloud_provider = CloudProvider(
            self.lattice, self.cloud, self.unavailable, self.recorder, self.clock,
            node_classes=self.node_classes)
        self.solver = Solver(self.lattice)
        self.provisioner = Provisioner(
            self.cluster, self.solver, self.node_pools, self.cloud_provider,
            self.unavailable, self.recorder, self.clock,
            batch_idle_seconds=self.options.batch_idle_duration,
            batch_max_seconds=self.options.batch_max_duration)
        self.lifecycle = LifecycleController(
            self.cluster, self.cloud_provider, self.recorder, self.clock,
            registration_delay=self.options.registration_delay)
        self.termination = TerminationController(
            self.cluster, self.cloud_provider, self.recorder, self.clock)
        self.gc = GarbageCollectionController(
            self.cluster, self.cloud_provider, self.recorder, self.clock)
        self.disruption = DisruptionController(
            self.cluster, self.solver, self.node_pools, self.cloud_provider,
            self.provisioner, self.termination, self.unavailable, self.recorder,
            self.clock, drift_enabled=self.options.drift_enabled,
            spot_to_spot_consolidation=self.options.spot_to_spot_consolidation)
        self._last_cache_cleanup = 0.0

    # ---- run loop --------------------------------------------------------

    def run_once(self, force_provision: bool = False) -> None:
        """One deterministic reconcile pass over every controller."""
        if force_provision or self.provisioner.batch_ready():
            self.provisioner.provision_once()
        self.lifecycle.reconcile()
        self.disruption.reconcile()
        self.termination.reconcile()
        self.gc.reconcile()
        now = self.clock.now()
        if now - self._last_cache_cleanup >= 10.0:  # ICE cleanup cadence (cache.go:39-42)
            self.unavailable.cleanup()
            self._last_cache_cleanup = now

    def run(self, duration: float, step: float = 1.0) -> None:
        """Drive the control plane for `duration` simulated (FakeClock) or
        real seconds."""
        end = self.clock.now() + duration
        while self.clock.now() < end:
            self.run_once()
            if isinstance(self.clock, FakeClock):
                self.clock.step(step)
            else:
                self.clock.sleep(step)

    def settle(self, max_rounds: int = 50, step: float = 1.0) -> int:
        """Run until no pending pods and no in-flight claims (or the round
        budget runs out). Returns rounds used. FakeClock only."""
        assert isinstance(self.clock, FakeClock)
        for i in range(max_rounds):
            self.run_once(force_provision=bool(self.cluster.pending_pods()))
            if not self.cluster.pending_pods() and all(
                    self.cluster.node_for_claim(c.name) is not None
                    for c in self.cluster.claims.values() if not c.deletion_timestamp):
                return i + 1
            self.clock.step(step)
        return max_rounds
