"""Operator: dependency wiring + the controller run loop.

Mirror of the reference operator (reference pkg/operator/operator.go:92-186
builds the session and all providers; cmd/controller/main.go:32-72 wires
cloudprovider → core+provider controllers → manager). Here the "session"
is the pluggable cloud backend, the providers are the lattice/ICE-cache/
cloudprovider stack, and the manager is a deterministic `run_once()` /
`run(duration)` loop over the controllers — clock-driven so the whole
control plane is simulable in tests (the reference's envtest stratum).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..apis.objects import NodeClaimPhase, NodeClass, NodePool
from ..cache.unavailable import UnavailableOfferings
from ..cloud.fake import FakeCloud
from ..cloudprovider.cloudprovider import CloudProvider
from ..controllers.disruption import DisruptionController
from ..controllers.garbagecollection import GarbageCollectionController
from ..controllers.lifecycle import LifecycleController
from ..controllers.provisioning import Provisioner
from ..controllers.tagging import TaggingController
from ..controllers.termination import TerminationController
from ..events import Recorder
from ..interruption.controller import InterruptionController
from ..interruption.queue import FakeQueue
from ..lattice.tensors import Lattice, build_lattice
from ..controllers.nodeclass import NodeClassController
from ..metrics import (Registry, emit_lattice_gauges, wire_core_metrics,
                       wire_lattice_metrics)
from ..providers import (
    AMIProvider, InstanceProfileProvider, LaunchTemplateProvider,
    PricingProvider, SecurityGroupProvider, SubnetProvider, VersionProvider,
)
from ..providers.amifamily import storage_config
from ..providers.pricing import PricingController
from ..solver.solve import Solver
from ..state.cluster import ClusterState
from ..utils.clock import Clock, FakeClock
from .options import Options

# ICE cleanup cadence: expired offerings re-enter the market at this
# tick (the reference sweeps its unavailable-offerings cache on the
# same interval, cache.go:39-42). docs/concepts/performance.md cites
# this as the staleness bound of the versioned masked-view memo.
ICE_CLEANUP_INTERVAL = 10.0


class Operator:
    def __init__(self, options: Optional[Options] = None,
                 lattice: Optional[Lattice] = None,
                 cloud: Optional[FakeCloud] = None,
                 clock: Optional[Clock] = None,
                 node_pools: Optional[Sequence[NodePool]] = None,
                 node_classes: Optional[Dict[str, NodeClass]] = None,
                 interruption_queue: Optional[FakeQueue] = None,
                 api_server=None):
        """``api_server`` (kube.FakeAPIServer) switches the operator into
        API mode: controllers write through the apiserver client and the
        ClusterState mirror is fed ONLY by informers (operator/sync.py) —
        the reference's wiring (cmd/controller/main.go:47-53). Without
        it, writes go straight to the mirror (deterministic simulation
        stratum). NodePools/NodeClasses passed here are seeded INTO the
        apiserver in API mode; later API writes supersede them
        (watch-driven config)."""
        self.options = options or Options()
        self.options.validate()
        if self.options.compile_cache_dir:
            # BEFORE any jit tracing (the Solver's Pallas probe below is
            # the first): a restarted operator loads its bucket-ladder
            # executables from the on-disk cache instead of re-paying
            # first-trace XLA compilation — the cold-start burn killer
            # (docs/concepts/performance.md "Steady-state reconciles &
            # the compile cache")
            from ..solver.solve import enable_persistent_compile_cache
            enable_persistent_compile_cache(self.options.compile_cache_dir)
        self.clock = clock or Clock()
        self.node_classes: Dict[str, NodeClass] = node_classes or {
            "default": NodeClass(name="default",
                                 role=f"KarpenterNodeRole-{self.options.cluster_name}")}
        pool_list = list(node_pools) if node_pools else [NodePool(name="default")]
        self._lattice_storage = None   # unknown when a lattice is passed in
        if lattice is not None:
            self.lattice = lattice
        else:
            # the reference computes instance types per NodeClass
            # (types.go:210-240 ephemeralStorage reads instanceStorePolicy +
            # blockDeviceMappings); the lattice carries ONE storage config —
            # the default NodeClass's. Reject wiring where a NodeClass a
            # pool actually REFERENCES would resolve different
            # ephemeral-storage capacities (the solver would silently
            # mis-state storage for that pool's nodes); merely-present
            # unreferenced classes are harmless.
            default_nc = (self.node_classes.get("default")
                          or next(iter(self.node_classes.values())))
            default_storage = storage_config(default_nc)
            self.lattice = build_lattice(
                vm_memory_overhead_percent=self.options.vm_memory_overhead_percent,
                reserved_enis=self.options.reserved_enis,
                storage=default_storage)
            self._lattice_storage = default_storage
        self.cloud = cloud or FakeCloud(self.clock, cluster_name=self.options.cluster_name)
        # connectivity probe before anything else (operator.go:115-117)
        self.cloud.list_instances()
        from ..utils.logging import get_logger
        self.log = get_logger("operator")
        # startup discovery, logged once (the reference logs kube-dns and
        # endpoint discovery at operator build, operator.go:125-132); a
        # configured CLUSTER_ENDPOINT wins over discovery
        # (operator.go:224-236), and an assume-role ARN layers the cloud
        # session (operator.go:93-107)
        endpoint = (self.options.cluster_endpoint
                    or self.cloud.network.cluster_endpoint)
        self.log.info("discovered cluster network",
                      endpoint=endpoint,
                      endpoint_source=("configured"
                                       if self.options.cluster_endpoint
                                       else "discovered"),
                      kube_dns=self.cloud.network.kube_dns_ip,
                      zones=self.lattice.Z, instance_types=self.lattice.T)
        if self.options.assume_role_arn:
            self.cloud.assume_role(self.options.assume_role_arn)
            self.log.info("assuming role for cloud session",
                          role_arn=self.options.assume_role_arn)
        self.recorder = Recorder(self.clock)
        self.metrics = Registry()
        wire_core_metrics(self.metrics)
        self._lattice_gauges = wire_lattice_metrics(self.metrics)
        self._lattice_gauge_state = None
        self._pool_gauge_rev = -1
        self._pool_status_cache: Dict[str, Dict[str, str]] = {}
        self.unavailable = UnavailableOfferings(self.clock)
        self.cluster = ClusterState(self.clock)
        # SLO burn tracking against the paper's bars (introspect/slo.py):
        # the provisioner records pass latencies + sampled FFD-referee
        # cost ratios; emit_gauges drives the rolling-window decision
        from ..introspect import SloTracker
        self.slo = SloTracker(self.clock, recorder=self.recorder,
                              metrics=self.metrics)
        self.node_pools: Dict[str, NodePool] = {p.name: p for p in pool_list}
        # cross-object config validation (single-valued os, os-vs-ami-
        # family, storage-config-vs-lattice): programmatically-passed
        # config fails LOUD here; watch-delivered config runs the same
        # guard in StateSync (a violating pool is skipped + event)
        for p in self.node_pools.values():
            err = self._validate_pool_config(p, self.node_classes)
            if err:
                raise ValueError(f"NodePool/{p.name}: {err}")
        # ---- the kube seam: apiserver client + writer + state sync ------
        # (reference operator.go:92-186 manager/client/indexers; the
        # DirectWriter keeps the deterministic stratum byte-identical)
        self.api_server = api_server
        self.kube = None
        self.sync = None
        if api_server is not None:
            from ..kube import (KubeClient, install_admission,
                                install_default_indexes)
            from ..kube.apiserver import AlreadyExistsError
            from ..kube.writer import ApiWriter
            from .sync import StateSync
            install_default_indexes(api_server)
            install_admission(api_server)
            if api_server._clock is None:
                api_server._clock = self.clock
            # watch hub tuning from options (bounded subscriber queues +
            # bookmark cadence; docs/reference/watch.md). Constructor
            # wins: a caller that built FakeAPIServer(watch_queue_bound=
            # ...) already tuned it (cli.py does — its surface serves
            # before this build), so options only fill defaults.
            from ..kube.apiserver import BOOKMARK_EVERY, WATCH_QUEUE_BOUND
            if api_server.watch_queue_bound == WATCH_QUEUE_BOUND:
                api_server.watch_queue_bound = \
                    self.options.api_watch_queue_bound
            if api_server.bookmark_every == BOOKMARK_EVERY:
                api_server.bookmark_every = self.options.api_bookmark_every
            self.kube = KubeClient(api_server)
            # seed programmatically-passed config into the server (tests
            # may also have pre-created objects there — first write wins)
            for pool in self.node_pools.values():
                try:
                    self.kube.create_nodepool(pool)
                except AlreadyExistsError:
                    pass
            for nc in self.node_classes.values():
                try:
                    self.kube.create_nodeclass(nc)
                except AlreadyExistsError:
                    pass
            self.writer = ApiWriter(self.kube, self.cluster, self.clock)
            # events mirror into the apiserver so `kpctl get events` /
            # GET /apis/events see what a kubectl user would
            from ..kube.eventsink import ApiEventSink
            self.recorder.sink = ApiEventSink(api_server)
            self.sync = StateSync(
                api_server, self.cluster, self.node_pools, self.node_classes,
                synced_gauge=self.metrics.gauge(
                    "karpenter_cluster_state_synced"),
                config_guard=self._validate_pool_config,
                recorder=self.recorder,
                pods_state_gauge=self.metrics.get("karpenter_pods_state"),
                clock=self.clock)
            self.sync.sync_once()   # initial list: config + state hydrated
        else:
            from ..kube.writer import DirectWriter
            self.writer = DirectWriter(self.cluster, self.clock)
        # domain providers (reference operator.go:135-178 builds all 11)
        self.subnet_provider = SubnetProvider(self.cloud, self.clock,
            cluster_name=self.options.cluster_name)
        self.security_group_provider = SecurityGroupProvider(self.cloud, self.clock,
            cluster_name=self.options.cluster_name)
        self.instance_profile_provider = InstanceProfileProvider(self.cloud, self.clock)
        self.ami_provider = AMIProvider(
            self.cloud, self.clock,
            cluster_name=self.options.cluster_name,
            cluster_endpoint=self.options.cluster_endpoint or None)
        self.launch_template_provider = LaunchTemplateProvider(
            self.cloud, self.security_group_provider, self.instance_profile_provider,
            self.ami_provider, self.clock, cluster_name=self.options.cluster_name)
        self.version_provider = VersionProvider(self.cloud, self.clock)
        self.pricing_provider = PricingProvider(
            self.lattice, self.clock,
            isolated_vpc=self.options.isolated_vpc)
        from ..cloudprovider.decorator import decorate
        self.cloud_provider = decorate(CloudProvider(
            self.lattice, self.cloud, self.unavailable, self.recorder, self.clock,
            node_classes=self.node_classes,
            subnets=self.subnet_provider,
            launch_templates=self.launch_template_provider,
            version=self.version_provider), self.metrics)
        # the mesh decision, once, at boot (parallel/mesh.py plan_mesh;
        # docs/reference/sharding.md): a real multi-chip backend
        # auto-meshes, --mesh/SOLVER_MESH forces a shape (the virtual-CPU
        # dry-run / CI path), and single-device stays the byte-identical
        # passthrough. The solver then runs EVERY solve — full,
        # wave-split, and the steady-state delta — over this mesh.
        from ..parallel.mesh import plan_mesh
        self.mesh_plan = plan_mesh(self.options.mesh or "auto")
        if self.mesh_plan.devices > 1:
            self.log.info("solver mesh planned",
                          devices=self.mesh_plan.devices,
                          axis=self.mesh_plan.axis,
                          source=self.mesh_plan.source)
        if self.options.solver_address:
            # delegate provisioning solves to the failover POOL of
            # accelerator-resident sidecar processes (parallel/pool.py;
            # docs/reference/solver-pool.md): per-endpoint circuit
            # breakers on THIS operator's injected clock, solve/health
            # deadlines split by purpose, least-outstanding failover
            # routing. probe_batch and the degradation ladder's local
            # fallback stay on this (fully functional) local Solver —
            # the fallback rides the same planned mesh, and it solves
            # only when the whole pool is dark (pool-exhausted).
            from ..parallel.pool import SolverPool
            self.solver = SolverPool(
                self.lattice, self.options.solver_address,
                clock=self.clock, mesh=self.mesh_plan.mesh,
                solve_deadline=self.options.solver_solve_deadline or None,
                health_deadline=self.options.solver_health_deadline,
                latency_budget_seconds=self.slo.latency_budget_seconds)
            self.log.info("solver pool configured",
                          endpoints=len(self.solver.endpoints),
                          solve_deadline_s=self.solver.solve_deadline,
                          health_deadline_s=self.solver.health_deadline)
        else:
            self.solver = Solver(self.lattice, clock=self.clock,
                                 mesh=self.mesh_plan.mesh)
        self.provisioner = Provisioner(
            self.cluster, self.solver, self.node_pools, self.cloud_provider,
            self.unavailable, self.recorder, self.clock,
            batch_idle_seconds=self.options.batch_idle_duration,
            batch_max_seconds=self.options.batch_max_duration,
            metrics=self.metrics, writer=self.writer, slo=self.slo)
        self.lifecycle = LifecycleController(
            self.cluster, self.cloud_provider, self.recorder, self.clock,
            registration_delay=self.options.registration_delay,
            metrics=self.metrics, writer=self.writer)
        self.termination = TerminationController(
            self.cluster, self.cloud_provider, self.recorder, self.clock,
            metrics=self.metrics,
            termination_grace_period=self.options.termination_grace_period,
            writer=self.writer)
        # NodePool-deletion cascade source of truth: in API mode the
        # nodepools INFORMER store (an invalid-config pool is absent from
        # the guarded active dict but still exists — its nodes must
        # survive a config hiccup; the store has always completed its
        # initial list by now: sync_once() ran above), in direct mode
        # the operator's pool dict itself
        if self.sync is not None:
            pools_inf = self.sync.informers.informers["nodepools"]

            def pool_exists(name: str) -> bool:
                return name in pools_inf.store
        else:
            def pool_exists(name: str) -> bool:
                return name in self.node_pools
        self.gc = GarbageCollectionController(
            self.cluster, self.cloud_provider, self.recorder, self.clock,
            writer=self.writer, pool_exists=pool_exists)
        self.tagging = TaggingController(
            self.cluster, self.cloud, self.recorder, self.clock)
        self.disruption = DisruptionController(
            self.cluster, self.solver, self.node_pools, self.cloud_provider,
            self.provisioner, self.termination, self.unavailable, self.recorder,
            self.clock, drift_enabled=self.options.drift_enabled,
            spot_to_spot_consolidation=self.options.spot_to_spot_consolidation,
            metrics=self.metrics, writer=self.writer)
        self.nodeclass_controller = NodeClassController(
            self.node_classes, self.cluster, self.subnet_provider,
            self.security_group_provider, self.ami_provider,
            self.instance_profile_provider, self.launch_template_provider,
            self.version_provider, self.recorder, self.clock)
        self.pricing_controller = PricingController(self.pricing_provider, self.clock)
        # interruption controller runs iff a queue is configured
        # (reference controllers.go:60-62)
        self.interruption_queue = interruption_queue
        if interruption_queue is None and self.options.interruption_queue:
            self.interruption_queue = FakeQueue(self.options.interruption_queue)
        self.interruption = None
        if self.interruption_queue is not None:
            self.interruption = InterruptionController(
                self.interruption_queue, self.cluster, self.termination,
                self.unavailable, self.recorder, self.clock, self.metrics)
        self._last_cache_cleanup = 0.0
        # handoff wiring (wire_handoff): unarmed by default — a single
        # operator pays one None check per write verb and no gauges
        self.elector = None
        self.handoff_replica = None
        self.handoff_source = None
        self._fence_guard = None
        self._wire_introspection()

    def wire_handoff(self, elector, replica=None, source=None) -> None:
        """Arm the operator-handoff surfaces (docs/reference/handoff.md):
        thread the elector's fence guard through the write seam, register
        the ``handoff`` introspection provider, and hook promotion side
        effects — the orphaned-lease sweep (holders that died in the
        blackout window) and the introspection re-wire (two in-process
        operators share the replace-by-name registry; the one now in
        charge re-asserts its providers). ``replica`` is this operator's
        StandbyReplica when it runs warm behind a leader; ``source`` its
        ReplicationSource when it serves one."""
        self.elector = elector
        self.handoff_replica = replica
        self.handoff_source = source
        self._fence_guard = elector.fence_guard()
        self.writer.set_fence(self._fence_guard)
        prev_promote = elector.on_promote

        def _promoted():
            self.cluster.sweep_orphaned_leases(self.writer.delete_lease)
            self._wire_introspection()
            self._register_handoff_provider()
            if prev_promote is not None:
                prev_promote()

        elector.on_promote = _promoted
        self._register_handoff_provider()
        if source is not None:
            # the replication journal window joins the observatory: a
            # standby falling behind the journal is a forecastable break
            self.headroom.register_probe("replication_window",
                                         source.headroom_probe)

    def _register_handoff_provider(self) -> None:
        from .. import introspect
        introspect.registry().register("handoff", self.handoff_stats)

    def handoff_stats(self) -> Dict[str, object]:
        """The ``handoff`` introspection provider: leadership, fencing,
        and replication counters — the LEADER row in kpctl top and the
        karpenter_operator_handoff_* gauges read this."""
        el = self.elector
        if el is None:
            return {"wired": False}
        out: Dict[str, object] = {
            "wired": True,
            "leader": bool(el.is_leader),
            "identity": el.identity,
            "fence": el.fence,
            "transitions": el.transitions,
            "promotions_blocked": el.promotions_blocked,
            "leases_swept": self.cluster.leases_swept,
        }
        if self._fence_guard is not None:
            out["fence_checks"] = self._fence_guard.checks
            out["fenced_rejections"] = self._fence_guard.rejections
        if hasattr(el.store, "corrupt_reads"):
            out["lease_corrupt_reads"] = el.store.corrupt_reads
        if self.handoff_replica is not None:
            out.update({f"replica_{k}": v
                        for k, v in self.handoff_replica.stats().items()})
        if self.handoff_source is not None:
            out.update({f"source_{k}": v
                        for k, v in self.handoff_source.stats().items()})
        return out

    def _wire_introspection(self) -> None:
        """Register every stateful subsystem's stats() with the
        process-wide introspection registry (docs/reference/
        introspection.md) and publish this operator's sampler for the
        /debug/statusz + /debug/vars surfaces. Registration is
        replace-by-name, so rebuilding an Operator in the same process
        (every test does) swaps the providers instead of leaking them."""
        from .. import introspect, trace
        reg = introspect.registry()
        reg.register("cluster", self.cluster.stats)
        reg.register("solver", self.solver.stats)
        if hasattr(self.solver, "pool_stats"):
            # the solver-pool surface (docs/reference/solver-pool.md):
            # per-endpoint breaker states, failovers, deadlines — the
            # POOL row in kpctl top and the karpenter_solver_pool_*
            # gauges read this provider
            reg.register("solver_pool", self.solver.pool_stats)
        reg.register("provisioner", self.provisioner.stats)
        # the decision-audit ring (solver/explain.py; docs/reference/
        # explain.md): per-pass reason-code histogram + elimination
        # counters ride the sampler into soak artifacts, and the ring
        # itself serves /debug/explain on both HTTP servers
        reg.register("explain", self.provisioner.explain.stats)
        introspect.set_explain_ring(self.provisioner.explain)
        # the vmapped consolidation engine (solver/consolidate.py;
        # docs/reference/consolidation.md): batched what-if dispatches,
        # zero-leg cache hits, host fallbacks, referee verdicts, skip
        # codes, and the savings-per-hour tally — the CONSOLIDATION row
        # in kpctl top and the soak savings trajectory read this
        reg.register("consolidation", self.disruption.engine.stats)
        reg.register("ice_cache", self.unavailable.stats)
        reg.register("writer", self.writer.stats)
        reg.register("events", self.recorder.stats)
        cp = self.cloud_provider
        reg.register("cloud_batcher", lambda: {
            **{"launch_" + k: v
               for k, v in cp._launch_batcher.stats().items()},
            **{"terminate_" + k: v
               for k, v in cp._terminate_batcher.stats().items()}})
        # the domain providers' TTL caches, one combined residency view
        caches = {
            "subnet": self.subnet_provider._cache,
            "security_group": self.security_group_provider._cache,
            "instance_profile": self.instance_profile_provider._cache,
            "ami": self.ami_provider._cache,
            "launch_template": self.launch_template_provider._cache,
            "version": self.version_provider._cache,
        }
        reg.register("provider_caches", lambda: {
            f"{name}_{k}": v
            for name, c in caches.items()
            for k, v in c.stats().items() if k != "ttl_seconds"})
        if self.api_server is not None:
            reg.register("watch_hub", self.api_server.stats)
        if self.interruption is not None:
            reg.register("interruption", self.interruption.stats)
        reg.register("flight_recorder", lambda: (
            trace.recorder().introspect_stats()
            if trace.recorder() is not None else {"enabled": False}))
        reg.register("slo", self.slo.stats)
        # the attribution layer (docs/reference/profiling.md): lock/queue
        # contention accounting, the whole-process sampling profiler
        # (a disabled marker until --profile publishes one), the device
        # cost model, and burn-triggered capture retention
        from ..introspect import contention
        from ..solver import costmodel
        contention.attach_metrics(
            self.metrics.get("karpenter_lock_wait_seconds"))
        reg.register("contention", contention.stats)
        # the lock-order witness (docs/reference/linting.md): the
        # acquisition-order graph's edge/cycle counts — cycles must stay
        # 0 (a standing invariant soak + the weather smoke assert)
        reg.register("lockorder", contention.lockorder_stats)
        reg.register("profiler", introspect.profiler_stats)
        reg.register("device", costmodel.model().stats)
        # burn-triggered capture: the SLO tracker's exactly-once-per-
        # episode sustained edge (and its per-pass slow-pass trigger)
        # snapshot profile+contention+device evidence into a bounded ring
        self.burn_capture = introspect.BurnCapture(
            self.clock,
            latency_budget_seconds=self.slo.latency_budget_seconds)
        self.slo.attach_capture(self.burn_capture)
        introspect.set_burn_capture(self.burn_capture)
        reg.register("burn_captures", self.burn_capture.stats)
        # build info: the constant-1 info gauge dashboards join on
        try:
            import jax
            self.metrics.get("karpenter_build_info").set(
                1.0, version=__import__(
                    "karpenter_provider_aws_tpu").__version__,
                jax_version=jax.__version__,
                backend=jax.default_backend())
        except Exception:
            pass   # an uninitializable backend must not fail construction
        # wall-clock sampler (not the sim clock): the rings feed soak
        # artifacts and kpctl top, both wall-time consumers. Started by
        # the CLI / soak harness; sample_once() serves the deterministic
        # stratum.
        self.sampler = introspect.Sampler(reg)
        introspect.set_sampler(self.sampler)
        self._wire_headroom(reg)

    def _wire_headroom(self, reg) -> None:
        """Register every bounded structure's cheap probe with the
        saturation observatory (introspect/headroom.py; docs/reference/
        headroom.md) and publish it for /debug/headroom + kpctl. The
        registry itself is per-operator (its monotonic high-water marks
        live exactly as long as the structures they watch) and survives
        a promotion re-wire; probes are replace-by-name like the
        introspection providers."""
        from .. import introspect
        from ..introspect import profiler as _prof
        hr = getattr(self, "headroom", None)
        if hr is None:
            hr = self.headroom = introspect.HeadroomRegistry(
                self.clock,
                high_water_fraction=(
                    self.options.headroom_high_water_fraction))
        # a queue-kind resource crossing the high-water fraction fires
        # the same capture machinery the SLO burn episodes feed
        hr.attach_capture(self.burn_capture)
        hr.register_probe("journal_ring", self.cluster.headroom_probe)
        hr.register_probe("journal_coalescer",
                          self.provisioner.journal_coalescer.headroom_probe)
        hr.register_probe("decision_audit_ring",
                          self.provisioner.explain.headroom_probe)
        hr.register_probe("consolidation_probe_cache",
                          self.disruption.engine.headroom_probe)
        hr.register_probe("events_ring", self.recorder.headroom_probe)
        hr.register_probe("slo_rings", self.slo.headroom_probe)
        hr.register_probe("burn_captures", self.burn_capture.headroom_probe)
        hr.register_probe("sampler_rings", self.sampler.headroom_probe)
        cp = self.cloud_provider
        hr.register_probe("cloud_launch_batcher",
                          cp._launch_batcher.headroom_probe)
        hr.register_probe("cloud_terminate_batcher",
                          cp._terminate_batcher.headroom_probe)
        resident = getattr(self.solver, "_resident", None)
        if resident is not None:
            hr.register_probe("solver_resident_cache",
                              resident.headroom_probe)
        if hasattr(self.solver, "pool_stats"):
            hr.register_probe("pool_outstanding",
                              self.solver.headroom_probe)
        if self.api_server is not None:
            hr.register_probe("api_watch_queues",
                              self.api_server.headroom_probe)
            hr.register_probe("api_publish_queues",
                              self.api_server.headroom_probe_publish)
        if self.interruption is not None:
            hr.register_probe("interruption_queue",
                              self.interruption.headroom_probe)

        def _profiler_probe():
            # the profiler is published lazily (--profile); until then
            # the bound exists with nothing in it
            p = introspect.profiler_instance()
            if p is None:
                return {"depth": 0.0,
                        "capacity": float(_prof.MAX_UNIQUE_STACKS)}
            return p.headroom_probe()

        hr.register_probe("profiler_stacks", _profiler_probe)
        reg.register("headroom", hr.stats)
        introspect.set_headroom(hr)

    def _validate_pool_config(self, pool: NodePool,
                              node_classes: Dict[str, NodeClass]):
        """Cross-object config checks a single-object admission webhook
        cannot perform. Returns an error string, or None when valid.

        - os requirement must resolve to exactly ONE os (pool_os would
          silently pin sorted()[0] for multi-valued or contradictory
          input and mis-type the pool for the solver/label path)
        - the pool's os must match its NodeClass amiFamily's (the solver
          would otherwise schedule pods the booted AMI can never run)
        - the NodeClass's storage config must match the lattice's (one
          lattice carries ONE ephemeral-storage resolution; a differing
          class would silently mis-state storage for the pool's nodes)
        """
        from ..apis.objects import pool_os
        from ..apis import wellknown as _wk
        os_c = pool.scheduling_requirements().get(_wk.LABEL_OS)
        if os_c.include is not None and len(os_c.include) != 1:
            return (f"os requirement must resolve to exactly one OS (a "
                    f"pool's nodes boot one OS), got {sorted(os_c.include)}")
        nc = node_classes.get(pool.node_class_ref)
        if nc is None:
            return None
        family_os = "windows" if nc.ami_family == "Windows" else "linux"
        if pool_os(pool) != family_os:
            return (f"os requirement {pool_os(pool)!r} contradicts "
                    f"NodeClass/{nc.name} amiFamily {nc.ami_family!r} "
                    f"({family_os})")
        if (self._lattice_storage is not None
                and storage_config(nc) != self._lattice_storage):
            return (f"NodeClass/{nc.name} storage config (instanceStore"
                    f"Policy/blockDeviceMappings/amiFamily root device) "
                    f"differs from the lattice's; the lattice carries one "
                    f"storage config — pass a per-config lattice explicitly")
        return None

    # ---- run loop --------------------------------------------------------

    def sync_once(self) -> int:
        """Pump the informers into the mirror (API mode; no-op direct)."""
        return self.sync.sync_once() if self.sync is not None else 0

    def run_once(self, force_provision: bool = False) -> None:
        """One deterministic reconcile pass over every controller. In API
        mode the informer pump runs between controllers so each observes
        its predecessors' writes within the pass — the deterministic
        analog of the threaded runtime's continuous watch delivery."""
        self.sync_once()
        if force_provision or self.provisioner.batch_ready():
            self.provisioner.provision_once()
        self.sync_once()
        self.nodeclass_controller.reconcile()
        self.pricing_controller.reconcile()
        self.lifecycle.reconcile()
        self.sync_once()
        self.tagging.reconcile()
        if self.interruption is not None:
            self.interruption.reconcile()
            # disruption must observe interruption's claim deletions (a
            # doomed claim must neither be a candidate nor landing space)
            self.sync_once()
        self.disruption.reconcile()
        self.sync_once()
        self.termination.reconcile()
        self.sync_once()
        self.gc.reconcile()
        self.sync_once()
        self.emit_gauges()
        now = self.clock.now()
        if now - self._last_cache_cleanup >= ICE_CLEANUP_INTERVAL:
            self.unavailable.cleanup()
            self._last_cache_cleanup = now

    def emit_gauges(self) -> None:
        """Refresh the state + offering gauge surfaces (run_once calls this
        every pass; the async runtime registers it as its own controller)."""
        # synced = the mirror is internally consistent: every registered
        # claim has its node and every node's owning claim exists (the
        # core's karpenter_cluster_state_synced reports state-hydration
        # readiness; it is NOT a cloud poll — the GC controller owns
        # cloud reconciliation). Locked snapshots: the async runtime runs
        # this in its own thread against live mutation.
        claims = {c.name: c for c in self.cluster.snapshot_claims()}
        nodes = self.cluster.snapshot_nodes()
        synced = all(n.node_claim is None or n.node_claim in claims
                     for n in nodes)
        if synced:
            with_node = {n.node_claim for n in nodes if n.node_claim}
            synced = all(c.name in with_node for c in claims.values()
                         if c.phase in (NodeClaimPhase.REGISTERED,
                                        NodeClaimPhase.INITIALIZED)
                         and not c.deletion_timestamp)
        self.metrics.gauge("karpenter_cluster_state_synced").set(1.0 if synced else 0.0)
        self.metrics.gauge("karpenter_cluster_state_node_count").set(len(self.cluster.nodes))
        self.metrics.gauge("karpenter_cluster_state_pod_count").set(len(self.cluster.pods))
        self.metrics.gauge("karpenter_ice_cache_size").set(
            sum(1 for _ in self.unavailable.entries()))
        # the mesh surface (docs/reference/sharding.md): device count of
        # the production mesh + the last sharded solve's load balance,
        # straight from the solver's lock-free stats snapshot
        sst = self.solver.stats()
        self.metrics.gauge("karpenter_solver_mesh_devices").set(
            float(sst.get("mesh_devices", 1)))
        self.metrics.gauge("karpenter_solver_shard_imbalance_ratio").set(
            float(sst.get("mesh_shard_imbalance", 0.0)))
        # the solver-pool surface (parallel/pool.py; docs/reference/
        # solver-pool.md): endpoint/health/failover gauges plus one
        # breaker-state gauge per endpoint address — replace() so a
        # re-configured pool never leaves stale endpoint labels
        if hasattr(self.solver, "pool_stats"):
            pst = self.solver.pool_stats()
            self.metrics.gauge("karpenter_solver_pool_endpoints").set(
                float(pst.get("endpoints", 0)))
            self.metrics.gauge(
                "karpenter_solver_pool_healthy_endpoints").set(
                float(pst.get("healthy", 0)))
            self.metrics.gauge("karpenter_solver_pool_failovers").set(
                float(pst.get("failovers", 0)))
            self.metrics.gauge("karpenter_solver_pool_local_solves").set(
                float(pst.get("local_solves", 0)))
            self.metrics.get(
                "karpenter_solver_pool_breaker_state").replace(
                {(addr,): float({"closed": 0, "half-open": 1,
                                 "open": 2}[state])
                 for addr, state in self.solver.breaker_states().items()})
        # the handoff surface (state/replication.py + operator/
        # leaderelection.py; docs/reference/handoff.md): role, fencing
        # token, fenced-write rejections, and replication-stream progress
        # — exported only once wire_handoff() attached an elector
        if self.elector is not None:
            ho = self.handoff_stats()
            self.metrics.gauge("karpenter_operator_leader_state").set(
                1.0 if ho.get("leader") else 0.0)
            self.metrics.gauge("karpenter_operator_handoff_fence_token").set(
                float(ho.get("fence", 0)))
            self.metrics.gauge(
                "karpenter_operator_handoff_fenced_writes").set(
                float(ho.get("fenced_rejections", 0)))
            self.metrics.gauge(
                "karpenter_operator_handoff_lease_transitions").set(
                float(ho.get("transitions", 0)))
            # a replica reports what it applied; a serving leader reports
            # what it streamed out — whichever side this process is on
            self.metrics.gauge("karpenter_operator_handoff_snapshots").set(
                float(ho.get("replica_snapshots",
                             ho.get("source_snapshots", 0))))
            self.metrics.gauge("karpenter_operator_handoff_deltas").set(
                float(ho.get("replica_deltas", ho.get("source_deltas", 0))))
            self.metrics.get("karpenter_operator_handoff_rebuilds").replace(
                {("stale-anchor",): float(
                    ho.get("replica_stale_anchor_rebuilds", 0)),
                 ("snapshot-version-mismatch",): float(
                    ho.get("replica_version_mismatch_rebuilds", 0))})
        # pods by phase (the state pump and the provisioner also refresh
        # this between metrics passes) + the rolling SLO burn decision
        self.metrics.get("karpenter_pods_state").replace(
            {(k,): float(v)
             for k, v in self.cluster.pod_phase_counts().items()})
        self.slo.update()
        # the saturation observatory (introspect/headroom.py): one
        # probe sweep per gauge pass feeds the EWMA fill/drain rates,
        # the first-to-break forecast, and the high-water capture edge;
        # the karpenter_headroom_* families re-render via replace() so
        # an unregistered resource disappears instead of flatlining
        self.headroom.observe()
        hr_rows = self.headroom.table()
        for key, gname in (
                ("depth", "karpenter_headroom_depth"),
                ("capacity", "karpenter_headroom_capacity"),
                ("highwater", "karpenter_headroom_highwater"),
                ("drops", "karpenter_headroom_drops"),
                ("fill_rate", "karpenter_headroom_fill_rate")):
            self.metrics.get(gname).replace(
                {(row["resource"],): float(row[key]) for row in hr_rows})
        self.metrics.get("karpenter_headroom_seconds_to_exhaustion").replace(
            {(row["resource"],): (float(row["seconds_to_exhaustion"])
                                  if row["seconds_to_exhaustion"] is not None
                                  else -1.0)
             for row in hr_rows})
        # depth/drop readouts that predate the observatory now FOLD from
        # the same registry read — one source of truth per number
        if self.interruption is not None:
            self.metrics.get("karpenter_interruption_queue_depth").set(
                self.headroom.read("interruption_queue").get("depth", 0.0))
        # pod startup latency samples observed since the last pass
        startup = self.metrics.get("karpenter_pods_startup_time_seconds")
        for s in self.cluster.drain_startup_samples():
            startup.observe(s)
        # per-pool committed usage + limits (reference metrics.md:16-22).
        # pool_usage() depends only on the node/claim capacity set —
        # re-render on its revision, not on every per-second pass. The
        # envelope status survives user applies (spec/status split), but
        # a watch-delivered pool re-install can still lose the typed
        # pool's hydrated status, so a cheap dict-compare against the
        # last computed status also re-arms the pass — otherwise the
        # wire object could show stale usage until the next node/claim
        # change.
        # snapshot: the async runtime's statesync thread mutates
        # node_pools concurrently with this (metrics-thread) scan
        pools_now = list(self.node_pools.items())
        # deleted pools leave the cache promptly (unbounded growth under
        # pool churn; a stale entry would also fire one spurious
        # re-render if the name is ever reused)
        live = {n for n, _ in pools_now}
        for gone in [n for n in self._pool_status_cache if n not in live]:
            del self._pool_status_cache[gone]
        status_dirty = self.api_server is not None and any(
            p.status_resources != self._pool_status_cache.get(n)
            for n, p in pools_now)
        if self.cluster.capacity_rev != self._pool_gauge_rev or status_dirty:
            self._pool_gauge_rev = self.cluster.capacity_rev
            from ..apis.resources import RESOURCE_AXES, vec_to_quantities
            from ..kube.apiserver import NotFoundError
            usage_g = self.metrics.get("karpenter_nodepool_usage")
            limit_g = self.metrics.get("karpenter_nodepool_limit")
            usage = self.cluster.pool_usage()
            for name, pool in pools_now:
                vec = usage.get(name)
                limit = pool.limits_vec()
                # usage covers the primary axes plus every LIMITED axis —
                # a usage/limit dashboard never sees an unpaired limit
                axes = {"cpu", "memory", "pods"} | (
                    {k for k in pool.limits if k in RESOURCE_AXES}
                    if limit is not None else set())
                for ax in sorted(axes):
                    ai = RESOURCE_AXES.index(ax)
                    usage_g.set(float(vec[ai]) if vec is not None else 0.0,
                                nodepool=name, resource_type=ax)
                    if limit is not None and ax in pool.limits:
                        limit_g.set(float(limit[ai]), nodepool=name,
                                    resource_type=ax)
                # status.resources (the reference NodePool status): keep
                # the typed pool current, and in API mode patch the wire
                # object's STATUS sub-map — controller-owned, outside the
                # user spec, so a user apply can neither wipe it for long
                # nor accidentally re-submit it (spec/status split)
                sr = vec_to_quantities(vec) if vec is not None else {}
                self._pool_status_cache[name] = sr
                if sr != pool.status_resources:
                    # merge-patch deletes need explicit None markers for
                    # axes that dropped to zero (RFC 7386)
                    delta = {**{k: None for k in pool.status_resources
                                if k not in sr}, **sr}
                    pool.status_resources = sr
                    if self.api_server is not None:
                        try:
                            self.api_server.patch(
                                "nodepools", name,
                                status_patch={"resources": delta})
                        except NotFoundError:
                            pass   # pool deleted mid-pass; watch will prune
        # the API stratum's write/fan-out series (karpenter_api_*):
        # straight from the watch hub's stats snapshot, so /metrics and
        # /debug/statusz tell one story about watcher load
        if self.api_server is not None:
            api = self.api_server.stats()
            for key, gname in (
                    ("watchers", "karpenter_api_watchers"),
                    ("watch_queue_depth", "karpenter_api_watch_queue_depth"),
                    ("events_emitted", "karpenter_api_watch_events_delivered"),
                    ("bookmarks", "karpenter_api_watch_bookmarks"),
                    ("bulk_ops", "karpenter_api_bulk_ops"),
                    ("fanout_envelope_copies",
                     "karpenter_api_fanout_envelope_copies")):
                self.metrics.gauge(gname).set(float(api.get(key, 0)))
            # deepest-queue + drop gauges fold from the headroom
            # registry's reading of the SAME probe — never a second
            # hand-walked number
            watch_row = self.headroom.read("api_watch_queues")
            self.metrics.gauge("karpenter_api_watch_max_queue_depth").set(
                float(watch_row.get("depth", 0.0)))
            self.metrics.gauge("karpenter_api_watch_drops").set(
                float(watch_row.get("drops", 0.0)))
        # offering gauge surface: re-emit only when pricing or the ICE set
        # actually changed (both are versioned)
        gstate = (self.lattice.price_version, self.unavailable.seq_num)
        if gstate != self._lattice_gauge_state:
            emit_lattice_gauges(self._lattice_gauges, self.lattice,
                                self.unavailable.mask(self.lattice))
            self._lattice_gauge_state = gstate

    def run(self, duration: float, step: float = 1.0) -> None:
        """Drive the control plane for `duration` simulated (FakeClock) or
        real seconds."""
        end = self.clock.now() + duration
        while self.clock.now() < end:
            self.run_once()
            if isinstance(self.clock, FakeClock):
                self.clock.step(step)
            else:
                self.clock.sleep(step)

    def settle(self, max_rounds: int = 50, step: float = 1.0) -> int:
        """Run until no pending pods and no in-flight claims (or the round
        budget runs out). Returns rounds used. FakeClock only."""
        assert isinstance(self.clock, FakeClock)
        for i in range(max_rounds):
            self.run_once(force_provision=bool(self.cluster.pending_pods()))
            if not self.cluster.pending_pods() and all(
                    self.cluster.node_for_claim(c.name) is not None
                    for c in self.cluster.snapshot_claims() if not c.deletion_timestamp):
                return i + 1
            self.clock.step(step)
        return max_rounds
