"""Leader election — the controller-HA half of the operator runtime.

The reference deploys 2 controller replicas behind Kubernetes
coordination/v1 lease-based leader election (client-go leaderelection;
the helm chart's PDB keeps one alive through node maintenance) and gates
side-effectful startup work on winning the lease (reference
pkg/providers/launchtemplate/launchtemplate.go:100-108 hydrates its cache
"after leader election"). This is the same algorithm over a pluggable
lease store:

- acquire when the lease is unheld or its renew time is older than the
  lease duration (the previous holder died),
- renew while holding; a holder that cannot renew within the lease
  duration loses leadership and must stop acting,
- release on clean shutdown so a standby takes over immediately.

Stores: :class:`MemoryLeaseStore` for simulation/tests (the FakeCloud
analog of the coordination API) and :class:`FileLeaseStore` for real
multi-process deployments on a shared filesystem (atomic rename swap).

Handoff extensions (docs/reference/handoff.md): the lease carries a
monotonic FENCING TOKEN that bumps on every takeover, so a demoted
(zombie) leader's in-flight side effects are rejected against the store
instead of raced (:class:`FenceGuard`, threaded through kube/writer.py);
takeover is gated on the standby's bounded-staleness check
(``promotion_gate`` — state/replication.py ``promotion_ready``), and the
False→True transition fires ``on_promote`` (the orphaned-lease sweep).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Optional

from ..utils.clock import Clock
from ..utils.logging import get_logger

log = get_logger("leaderelection")

LEASE_DURATION = 15.0   # client-go defaults: 15s lease
RETRY_PERIOD = 2.0      # acquire/renew cadence


@dataclass
class Lease:
    holder: str
    renew_time: float
    # the fencing token: +1 on every TAKEOVER (never on renewal), so any
    # write stamped with an older fence provably predates the current
    # leader's term. Old stores/files without the field read as 0.
    fence: int = 0


class MemoryLeaseStore:
    """In-memory lease record with compare-and-swap semantics."""

    def __init__(self):
        self._lock = threading.Lock()
        self._lease: Optional[Lease] = None

    def get(self) -> Optional[Lease]:
        with self._lock:
            return self._lease

    def swap(self, expect_holder: Optional[str], lease: Optional[Lease]) -> bool:
        """Write ``lease`` iff the current holder is ``expect_holder``
        (None = unheld/expired takeover is validated by the caller)."""
        with self._lock:
            current = self._lease.holder if self._lease else None
            if current != expect_holder:
                return False
            self._lease = lease
            return True


class FileLeaseStore:
    """Lease in a JSON file, swapped atomically via rename. Suitable for
    replicas sharing a filesystem. The compare and the write run under an
    exclusive flock on a sidecar lockfile, so two replicas cannot
    interleave the read-check-write and both believe they won (the
    dual-leader window the pre-lock implementation had); a real cluster
    deployment still uses the coordination API (ApiLeaseStore)."""

    def __init__(self, path: str):
        self.path = Path(path)
        self._lockpath = self.path.with_name(self.path.name + ".lock")
        # crash-safety observability: a truncated/zero-byte/garbage lease
        # file reads as "unheld" (counted, warned once) — never an
        # exception out of the election tick
        self.corrupt_reads = 0
        self._warned_corrupt = False

    def get(self) -> Optional[Lease]:
        try:
            text = self.path.read_text()
        except OSError:
            return None   # no file (or unreadable): unheld
        try:
            d = json.loads(text)
            holder = d["holder"]
            if not isinstance(holder, str):
                raise ValueError("non-string holder")
            return Lease(holder=holder, renew_time=float(d["renewTime"]),
                         fence=int(d.get("fence", 0)))
        except (ValueError, KeyError, TypeError):
            # a writer crashed mid-write (zero-byte file), the JSON is
            # truncated, or the body is the wrong shape (TypeError: a
            # JSON scalar/array has no ["holder"]): the lease reads as
            # UNHELD so the election proceeds over the wreckage instead
            # of the tick raising and killing the runtime
            self.corrupt_reads += 1
            if not self._warned_corrupt:
                self._warned_corrupt = True
                log.warning("corrupt lease file treated as unheld",
                            path=str(self.path))
            return None

    def swap(self, expect_holder: Optional[str], lease: Optional[Lease]) -> bool:
        import fcntl
        with open(self._lockpath, "w") as lockf:
            fcntl.flock(lockf.fileno(), fcntl.LOCK_EX)
            try:
                current = self.get()
                if (current.holder if current else None) != expect_holder:
                    return False
                if lease is None:
                    try:
                        self.path.unlink()
                    except OSError:
                        pass
                    return True
                fd, tmp = tempfile.mkstemp(dir=str(self.path.parent))
                with os.fdopen(fd, "w") as f:
                    json.dump({"holder": lease.holder,
                               "renewTime": lease.renew_time,
                               "fence": lease.fence}, f)
                os.replace(tmp, self.path)
                return True
            finally:
                fcntl.flock(lockf.fileno(), fcntl.LOCK_UN)


class LeaderElector:
    def __init__(self, store, identity: str,
                 lease_duration: float = LEASE_DURATION,
                 clock: Optional[Clock] = None,
                 promotion_gate: Optional[Callable[[], bool]] = None,
                 on_promote: Optional[Callable[[], None]] = None):
        self.store = store
        self.identity = identity
        self.lease_duration = lease_duration
        self.clock = clock or Clock()
        self._leading = False
        self.transitions = 0   # leadership changes observed (metrics hook)
        # the fence this elector holds while leading (handoff fencing):
        # set from the lease on renew, bumped on takeover
        self.fence = 0
        # bounded-staleness cutover: a standby may only TAKE OVER once
        # its replica passes the gate (state/replication.py
        # promotion_ready — journal-anchor staleness check). Renewal is
        # never gated: an incumbent must not lose its own lease to a
        # transient replication hiccup.
        self.promotion_gate = promotion_gate
        self.on_promote = on_promote
        self.promotions_blocked = 0
        self.promote_hook_errors = 0

    @property
    def is_leader(self) -> bool:
        return self._leading

    def try_acquire_or_renew(self) -> bool:
        """One election tick; returns current leadership. Call every
        RETRY_PERIOD (the runtime registers this as its own controller)."""
        now = self.clock.now()
        lease = self.store.get()
        if lease is not None and lease.holder == self.identity:
            ok = self.store.swap(self.identity,
                                 Lease(self.identity, now, lease.fence))
            if ok:
                self.fence = lease.fence
            self._set(ok)
            return self._leading
        if lease is None or now - lease.renew_time >= self.lease_duration:
            # unheld, or the holder stopped renewing: take over — but
            # only through the promotion gate (a standby with no usable
            # snapshot must leave the lease on the floor rather than
            # promote an empty mirror)
            if self.promotion_gate is not None and not self.promotion_gate():
                self.promotions_blocked += 1
                self._set(False)
                return False
            expect = lease.holder if lease is not None else None
            fence = (lease.fence if lease is not None else 0) + 1
            ok = self.store.swap(expect, Lease(self.identity, now, fence))
            if ok:
                self.fence = fence
            self._set(ok and self.store.get().holder == self.identity)
            return self._leading
        self._set(False)
        return False

    def release(self) -> None:
        """Clean shutdown: drop the lease so a standby wins immediately."""
        if self._leading:
            self.store.swap(self.identity, None)
            self._set(False)

    def holds_fence(self) -> bool:
        """True iff THE STORE still shows this identity holding the
        lease at the fence this elector acquired. Re-reads the store —
        a zombie whose election thread has not ticked (hung process)
        still fails here the instant a standby's takeover rotates the
        token. The authoritative check behind :class:`FenceGuard`."""
        if not self._leading:
            return False
        lease = self.store.get()
        return (lease is not None and lease.holder == self.identity
                and lease.fence == self.fence)

    def fence_guard(self) -> "FenceGuard":
        return FenceGuard(self)

    def _set(self, leading: bool) -> None:
        if leading != self._leading:
            self.transitions += 1
            self._leading = leading
            if leading and self.on_promote is not None:
                # promotion side effects (orphaned-lease sweep,
                # introspection re-wire) must never cost the new leader
                # its first election tick
                try:
                    self.on_promote()
                except Exception as e:  # noqa: BLE001
                    self.promote_hook_errors += 1
                    log.warning("on_promote hook failed",
                                error=f"{type(e).__name__}: {e}")
            return
        self._leading = leading


class FenceGuard:
    """The write-side fencing check (threaded through kube/writer.py
    ``set_fence``): every side-effectful verb asks ``check()`` first,
    and a False answer raises ``FencedWriteError`` at the verb — a
    demoted leader's queued eviction/claim write is REJECTED against
    the store, not raced against the new leader's."""

    def __init__(self, elector: LeaderElector):
        self._elector = elector
        self.checks = 0
        self.rejections = 0

    def check(self) -> bool:
        self.checks += 1
        ok = self._elector.holds_fence()
        if not ok:
            self.rejections += 1
        return ok

    @property
    def fence(self) -> int:
        return self._elector.fence

    def stats(self) -> dict:
        return {"checks": self.checks, "rejections": self.rejections,
                "fence": self._elector.fence}


class ApiLeaseStore:
    """Lease in the apiserver's coordination resource — true
    client-go-style election: compare-and-swap rides the server's
    optimistic concurrency (a stale resourceVersion on update = lost the
    race), exactly how the reference's replicas elect through the
    coordination/v1 API. Election leases carry ``"election": true`` so
    the StateSync lease applier keeps them OUT of the kube-node-lease
    mirror (they would otherwise be reaped as ownerless by the lease GC —
    the namespace separation the real cluster gives for free)."""

    NAME = "karpenter-tpu-leader-election"

    def __init__(self, server, name: str = NAME):
        self.server = server
        self.name = name

    def get(self) -> Optional[Lease]:
        from ..kube.apiserver import NotFoundError
        try:
            spec = self.server.get("leases", self.name)["spec"]
        except NotFoundError:
            return None
        if spec.get("holder") is None:
            return None
        return Lease(holder=spec["holder"],
                     renew_time=float(spec["renewTime"]),
                     fence=int(spec.get("fence", 0)))

    def swap(self, expect_holder: Optional[str],
             lease: Optional[Lease]) -> bool:
        from ..kube.apiserver import (AlreadyExistsError, ConflictError,
                                      NotFoundError)
        try:
            obj = self.server.get("leases", self.name)
        except NotFoundError:
            if expect_holder is not None:
                return False
            if lease is None:
                return True
            try:
                self.server.create("leases", {
                    "name": self.name, "election": True,
                    "holder": lease.holder, "renewTime": lease.renew_time,
                    "fence": lease.fence})
                return True
            except AlreadyExistsError:
                return False   # lost the creation race
        if obj["spec"].get("holder") != expect_holder:
            return False
        # get() returns the frozen shared envelope (kube/apiserver.py
        # copy-on-read discipline) — deepcopy thaws a mutable CAS copy
        import copy
        obj = copy.deepcopy(obj)
        if lease is None:
            # release: clear the holder (keep the object — its RV history
            # stays useful and re-creation races disappear)
            obj["spec"]["holder"] = None
            obj["spec"]["renewTime"] = 0.0
        else:
            obj["spec"]["holder"] = lease.holder
            obj["spec"]["renewTime"] = lease.renew_time
            obj["spec"]["fence"] = lease.fence
        try:
            self.server.update("leases", obj)
            return True
        except (ConflictError, NotFoundError):
            return False   # another replica wrote first: CAS failed
