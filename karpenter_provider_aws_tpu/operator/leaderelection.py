"""Leader election — the controller-HA half of the operator runtime.

The reference deploys 2 controller replicas behind Kubernetes
coordination/v1 lease-based leader election (client-go leaderelection;
the helm chart's PDB keeps one alive through node maintenance) and gates
side-effectful startup work on winning the lease (reference
pkg/providers/launchtemplate/launchtemplate.go:100-108 hydrates its cache
"after leader election"). This is the same algorithm over a pluggable
lease store:

- acquire when the lease is unheld or its renew time is older than the
  lease duration (the previous holder died),
- renew while holding; a holder that cannot renew within the lease
  duration loses leadership and must stop acting,
- release on clean shutdown so a standby takes over immediately.

Stores: :class:`MemoryLeaseStore` for simulation/tests (the FakeCloud
analog of the coordination API) and :class:`FileLeaseStore` for real
multi-process deployments on a shared filesystem (atomic rename swap).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

from ..utils.clock import Clock

LEASE_DURATION = 15.0   # client-go defaults: 15s lease
RETRY_PERIOD = 2.0      # acquire/renew cadence


@dataclass
class Lease:
    holder: str
    renew_time: float


class MemoryLeaseStore:
    """In-memory lease record with compare-and-swap semantics."""

    def __init__(self):
        self._lock = threading.Lock()
        self._lease: Optional[Lease] = None

    def get(self) -> Optional[Lease]:
        with self._lock:
            return self._lease

    def swap(self, expect_holder: Optional[str], lease: Optional[Lease]) -> bool:
        """Write ``lease`` iff the current holder is ``expect_holder``
        (None = unheld/expired takeover is validated by the caller)."""
        with self._lock:
            current = self._lease.holder if self._lease else None
            if current != expect_holder:
                return False
            self._lease = lease
            return True


class FileLeaseStore:
    """Lease in a JSON file, swapped atomically via rename. Suitable for
    replicas sharing a filesystem. The compare and the write run under an
    exclusive flock on a sidecar lockfile, so two replicas cannot
    interleave the read-check-write and both believe they won (the
    dual-leader window the pre-lock implementation had); a real cluster
    deployment still uses the coordination API (ApiLeaseStore)."""

    def __init__(self, path: str):
        self.path = Path(path)
        self._lockpath = self.path.with_name(self.path.name + ".lock")

    def get(self) -> Optional[Lease]:
        try:
            d = json.loads(self.path.read_text())
            return Lease(holder=d["holder"], renew_time=float(d["renewTime"]))
        except (OSError, ValueError, KeyError):
            return None

    def swap(self, expect_holder: Optional[str], lease: Optional[Lease]) -> bool:
        import fcntl
        with open(self._lockpath, "w") as lockf:
            fcntl.flock(lockf.fileno(), fcntl.LOCK_EX)
            try:
                current = self.get()
                if (current.holder if current else None) != expect_holder:
                    return False
                if lease is None:
                    try:
                        self.path.unlink()
                    except OSError:
                        pass
                    return True
                fd, tmp = tempfile.mkstemp(dir=str(self.path.parent))
                with os.fdopen(fd, "w") as f:
                    json.dump({"holder": lease.holder,
                               "renewTime": lease.renew_time}, f)
                os.replace(tmp, self.path)
                return True
            finally:
                fcntl.flock(lockf.fileno(), fcntl.LOCK_UN)


class LeaderElector:
    def __init__(self, store, identity: str,
                 lease_duration: float = LEASE_DURATION,
                 clock: Optional[Clock] = None):
        self.store = store
        self.identity = identity
        self.lease_duration = lease_duration
        self.clock = clock or Clock()
        self._leading = False
        self.transitions = 0   # leadership changes observed (metrics hook)

    @property
    def is_leader(self) -> bool:
        return self._leading

    def try_acquire_or_renew(self) -> bool:
        """One election tick; returns current leadership. Call every
        RETRY_PERIOD (the runtime registers this as its own controller)."""
        now = self.clock.now()
        lease = self.store.get()
        if lease is not None and lease.holder == self.identity:
            ok = self.store.swap(self.identity,
                                 Lease(self.identity, now))
            self._set(ok)
            return self._leading
        if lease is None or now - lease.renew_time >= self.lease_duration:
            # unheld, or the holder stopped renewing: take over
            expect = lease.holder if lease is not None else None
            ok = self.store.swap(expect, Lease(self.identity, now))
            self._set(ok and self.store.get().holder == self.identity)
            return self._leading
        self._set(False)
        return False

    def release(self) -> None:
        """Clean shutdown: drop the lease so a standby wins immediately."""
        if self._leading:
            self.store.swap(self.identity, None)
            self._set(False)

    def _set(self, leading: bool) -> None:
        if leading != self._leading:
            self.transitions += 1
        self._leading = leading


class ApiLeaseStore:
    """Lease in the apiserver's coordination resource — true
    client-go-style election: compare-and-swap rides the server's
    optimistic concurrency (a stale resourceVersion on update = lost the
    race), exactly how the reference's replicas elect through the
    coordination/v1 API. Election leases carry ``"election": true`` so
    the StateSync lease applier keeps them OUT of the kube-node-lease
    mirror (they would otherwise be reaped as ownerless by the lease GC —
    the namespace separation the real cluster gives for free)."""

    NAME = "karpenter-tpu-leader-election"

    def __init__(self, server, name: str = NAME):
        self.server = server
        self.name = name

    def get(self) -> Optional[Lease]:
        from ..kube.apiserver import NotFoundError
        try:
            spec = self.server.get("leases", self.name)["spec"]
        except NotFoundError:
            return None
        if spec.get("holder") is None:
            return None
        return Lease(holder=spec["holder"],
                     renew_time=float(spec["renewTime"]))

    def swap(self, expect_holder: Optional[str],
             lease: Optional[Lease]) -> bool:
        from ..kube.apiserver import (AlreadyExistsError, ConflictError,
                                      NotFoundError)
        try:
            obj = self.server.get("leases", self.name)
        except NotFoundError:
            if expect_holder is not None:
                return False
            if lease is None:
                return True
            try:
                self.server.create("leases", {
                    "name": self.name, "election": True,
                    "holder": lease.holder, "renewTime": lease.renew_time})
                return True
            except AlreadyExistsError:
                return False   # lost the creation race
        if obj["spec"].get("holder") != expect_holder:
            return False
        # get() returns the frozen shared envelope (kube/apiserver.py
        # copy-on-read discipline) — deepcopy thaws a mutable CAS copy
        import copy
        obj = copy.deepcopy(obj)
        if lease is None:
            # release: clear the holder (keep the object — its RV history
            # stays useful and re-creation races disappear)
            obj["spec"]["holder"] = None
            obj["spec"]["renewTime"] = 0.0
        else:
            obj["spec"]["holder"] = lease.holder
            obj["spec"]["renewTime"] = lease.renew_time
        try:
            self.server.update("leases", obj)
            return True
        except (ConflictError, NotFoundError):
            return False   # another replica wrote first: CAS failed
