"""Operator configuration.

Mirror of the reference's layered flag/env options (reference
pkg/operator/options/options.go:35-57 + website reference/settings.md:13-47):
cluster identity, memory-overhead model, batching windows, interruption
queue, and feature gates. Resolution order: explicit kwargs > environment
variables > defaults, like the reference's flag/env layering.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional


def _env(name: str, default, cast):
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return cast(raw)
    except (TypeError, ValueError):
        raise ValueError(f"invalid value for {name}: {raw!r}")


def _env_bool(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() in ("1", "true", "yes", "on")


@dataclass
class Options:
    cluster_name: str = "sim"
    # apiserver endpoint handed to node bootstrap userdata. Empty =
    # discover from the cloud's network description, like the
    # reference's EKS describe-cluster fallback (operator.go:119-124,
    # 224-236: the CLUSTER_ENDPOINT option wins when set)
    cluster_endpoint: str = ""
    # role to assume for every cloud call (reference operator.go:93-107
    # STS assume-role session layering). The fake session records it;
    # a real backend would chain credentials through it.
    assume_role_arn: str = ""
    # VM memory the hypervisor eats before the OS sees it (options.go
    # VM_MEMORY_OVERHEAD_PERCENT, default 0.075)
    vm_memory_overhead_percent: float = 0.075
    reserved_enis: int = 0
    # assume AWS services without a VPC endpoint are unreachable: live
    # pricing lookups are skipped and the compiled-in static prices serve
    # (reference options.go:53 ISOLATED_VPC; pricing.go:150-163)
    isolated_vpc: bool = False
    # pending-pod batch window (settings.md:17-18)
    batch_idle_duration: float = 1.0
    batch_max_duration: float = 10.0
    # interruption queue name; empty disables the interruption controller
    # (reference controllers.go:60-62)
    interruption_queue: str = ""
    # feature gates (settings.md:40-47)
    drift_enabled: bool = True
    spot_to_spot_consolidation: bool = False
    # force-drain backstop: a terminating claim older than this many
    # seconds evicts even PDB-blocked pods so the instance is never
    # billed forever behind a zero-allowance budget. None = wait forever
    # (the pinned reference release's behavior; later releases added the
    # same escape as NodeClaim spec.terminationGracePeriod)
    termination_grace_period: Optional[float] = None
    # sim-only knob: seconds between launch and (fake) kubelet registration
    registration_delay: float = 5.0
    # gRPC address(es) of solver SIDECAR processes (parallel/sidecar.py
    # main), COMMA-SEPARATED (env SOLVER_ADDRESSES; the singular
    # SOLVER_ADDRESS still works). Set, the operator's provisioning
    # solves ship over the Solve RPC to a failover POOL of
    # accelerator-resident sidecars (parallel/pool.py SolverPool:
    # per-endpoint circuit breakers, split solve/health deadlines,
    # least-outstanding routing, local solve only when the whole pool is
    # dark — docs/reference/solver-pool.md); empty = resident in-process
    # solver
    solver_address: str = ""
    # solve RPC deadline in seconds; 0 = derive from the SLO latency
    # budget (budget x pool.SOLVE_DEADLINE_MULTIPLIER — 10 s at the
    # paper's 200 ms bar). The old behavior was a flat 60 s shared with
    # health probes.
    solver_solve_deadline: float = 0.0
    # health/liveness RPC deadline in seconds: a probe against a HUNG
    # sidecar must answer in about a second, not a solve timeout
    solver_health_deadline: float = 1.0
    # device mesh for the sharded solver (parallel/mesh.py plan_mesh;
    # docs/reference/sharding.md). "" or "auto" auto-selects: every
    # device of a real multi-chip backend, single-device on the cpu
    # backend (its device count is a dry-run knob, not hardware). An
    # integer forces an N-way mesh (the virtual-CPU dry-run / CI shape);
    # "off" pins the single-device path.
    mesh: str = ""
    # directory for JAX's persistent compilation cache (solver/solve.py
    # enable_persistent_compile_cache): a RESTARTED operator loads its
    # bucket-ladder executables from disk instead of re-paying 20-40 s
    # of XLA compile per shape on its first real pass — the cold-start
    # SLO burn spike SOAK_r06 recorded. Empty = in-memory jit cache only
    compile_cache_dir: str = ""
    # API-mode watch hub tuning (kube/apiserver.py; docs/reference/
    # watch.md): a subscriber whose queue exceeds the bound is dropped
    # to 410/relist instead of growing without limit, and a BOOKMARK
    # event (current RV, no object) goes to each watcher after this many
    # deliveries so idle watchers' resume points stay fresh. 0 bookmarks
    # disables them.
    api_watch_queue_bound: int = 8192
    api_bookmark_every: int = 256
    # saturation observatory (introspect/headroom.py; docs/reference/
    # headroom.md): a queue-kind resource whose occupancy crosses this
    # fraction of its capacity triggers one burn-capture per episode
    # (profile + contention evidence at /debug/pprof/captures)
    headroom_high_water_fraction: float = 0.9

    def validate(self) -> None:
        if not self.cluster_name:
            raise ValueError("cluster_name is required")
        if self.cluster_endpoint and not self.cluster_endpoint.startswith(
                "https://"):
            # the reference validates the configured endpoint is a URL
            # (options_validation.go); a bootstrap pointed at plaintext
            # would fail far later and far less legibly
            raise ValueError("cluster_endpoint must be an https:// URL")
        if not (0.0 <= self.vm_memory_overhead_percent < 1.0):
            raise ValueError("vm_memory_overhead_percent must be in [0, 1)")
        if self.batch_idle_duration < 0 or self.batch_max_duration < self.batch_idle_duration:
            raise ValueError("batch windows: need 0 <= idle <= max")
        if self.api_watch_queue_bound < 1:
            raise ValueError("api_watch_queue_bound must be >= 1")
        if self.solver_address and not [
                a.strip() for a in self.solver_address.split(",")
                if a.strip()]:
            # same normalization parallel/pool.py parse_addresses applies
            # (kept inline: Options must stay importable without the
            # solver stack)
            raise ValueError(
                f"solver_address: no endpoint in {self.solver_address!r}")
        if self.solver_solve_deadline < 0:
            raise ValueError("solver_solve_deadline must be >= 0 "
                             "(0 = derive from the latency budget)")
        if self.solver_health_deadline <= 0:
            raise ValueError("solver_health_deadline must be > 0")
        if self.api_bookmark_every < 0:
            raise ValueError("api_bookmark_every must be >= 0 (0 disables)")
        if not (0.0 < self.headroom_high_water_fraction <= 1.0):
            raise ValueError(
                "headroom_high_water_fraction must be in (0, 1]")
        m = (self.mesh or "auto").strip().lower()
        if m not in ("auto", "off", "none", "single"):
            try:
                if int(m) < 1:
                    raise ValueError
            except ValueError:
                raise ValueError(
                    f"mesh must be 'auto', 'off', or a positive device "
                    f"count, got {self.mesh!r}")

    @staticmethod
    def from_env(**overrides) -> "Options":
        opts = Options(
            cluster_name=_env("CLUSTER_NAME", "sim", str),
            cluster_endpoint=_env("CLUSTER_ENDPOINT", "", str),
            assume_role_arn=_env("ASSUME_ROLE_ARN", "", str),
            vm_memory_overhead_percent=_env("VM_MEMORY_OVERHEAD_PERCENT", 0.075, float),
            reserved_enis=_env("RESERVED_ENIS", 0, int),
            isolated_vpc=_env_bool("ISOLATED_VPC", False),
            batch_idle_duration=_env("BATCH_IDLE_DURATION", 1.0, float),
            batch_max_duration=_env("BATCH_MAX_DURATION", 10.0, float),
            interruption_queue=_env("INTERRUPTION_QUEUE", "", str),
            drift_enabled=_env_bool("FEATURE_GATE_DRIFT", True),
            spot_to_spot_consolidation=_env_bool("FEATURE_GATE_SPOT_TO_SPOT", False),
            termination_grace_period=_env("TERMINATION_GRACE_PERIOD", None, float),
            # empty counts as unset on BOTH vars: the deploy template
            # ships SOLVER_ADDRESSES="" as a placeholder, which must not
            # shadow an overlay's legacy SOLVER_ADDRESS
            solver_address=(_env("SOLVER_ADDRESSES", "", str)
                            or _env("SOLVER_ADDRESS", "", str)),
            solver_solve_deadline=_env("SOLVER_SOLVE_DEADLINE", 0.0, float),
            solver_health_deadline=_env("SOLVER_HEALTH_DEADLINE", 1.0,
                                        float),
            mesh=_env("SOLVER_MESH", "", str),
            compile_cache_dir=_env("COMPILE_CACHE_DIR", "", str),
            api_watch_queue_bound=_env("API_WATCH_QUEUE_BOUND", 8192, int),
            api_bookmark_every=_env("API_BOOKMARK_EVERY", 256, int),
            headroom_high_water_fraction=_env(
                "HEADROOM_HIGH_WATER_FRACTION", 0.9, float),
        )
        for k, v in overrides.items():
            setattr(opts, k, v)
        opts.validate()
        return opts
