"""Threaded controller runtime — the controller-runtime analog.

The reference registers each controller with its own workqueue and
``MaxConcurrentReconciles`` (e.g. 10 for the NodeClass controller,
pkg/controllers/nodeclass/controller.go:298-305). Our controllers
reconcile the whole cluster per pass rather than per object, so the
mapping is: each controller ticks on its OWN cadence in its own thread
(never overlapping itself — the per-object serialization guarantee
collapses to per-controller), and different controllers run concurrently
against the locked ClusterState mirror.

The deterministic single-thread loop (Operator.run_once) remains the
test/simulation path; this runtime is the production serving loop behind
``karpenter-tpu-controller --async-runtime``.
"""

from __future__ import annotations

import threading
import traceback
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence


@dataclass
class ControllerSpec:
    name: str
    reconcile: Callable[[], object]
    interval: float = 1.0          # seconds between the END of one pass
                                   # and the start of the next
    gate_on_leadership: bool = True  # False = runs on standbys too (the
                                     # informer pump: client-go reflectors
                                     # run on ALL replicas so a failover
                                     # starts from a warm mirror)


class ControllerRuntime:
    def __init__(self, specs: Sequence[ControllerSpec],
                 on_error: Optional[Callable[[str, BaseException], None]] = None,
                 elector=None):
        """``elector`` (operator/leaderelection.LeaderElector) gates every
        reconcile on holding the lease — the standby replica's controllers
        idle until it wins (the reference's client-go leader election
        around its manager). The election tick itself runs as one more
        controller thread registered here."""
        self.specs = list(specs)
        self.elector = elector
        if elector is not None:
            from .leaderelection import RETRY_PERIOD
            self.specs.append(ControllerSpec(
                "leader-election", elector.try_acquire_or_renew,
                interval=RETRY_PERIOD, gate_on_leadership=False))
        self._on_error = on_error
        self._stop = threading.Event()
        self._pause = threading.Event()
        self._threads: List[threading.Thread] = []
        self.error_counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def _run(self, spec: ControllerSpec) -> None:
        while not self._stop.is_set():
            if self._pause.is_set():
                # hung-operator chaos (weather OperatorKill mode="hang"):
                # nothing reconciles and — critically — the election tick
                # stops renewing, so the lease expires and a standby
                # promotes while this process still believes it leads.
                # resume() releases the queued work straight into the
                # write fence, where it is rejected, not raced.
                self._stop.wait(0.05)
                continue
            try:
                if (self.elector is None or not spec.gate_on_leadership
                        or self.elector.is_leader):
                    spec.reconcile()
            except BaseException as e:  # a controller crash must not kill
                with self._lock:       # its siblings (controller-runtime
                    self.error_counts[spec.name] = \
                        self.error_counts.get(spec.name, 0) + 1  # requeues)
                if self._on_error is not None:
                    self._on_error(spec.name, e)
                else:
                    traceback.print_exc()
            self._stop.wait(spec.interval)

    def start(self) -> "ControllerRuntime":
        # the threaded control plane is many short critical sections
        # under one GIL: at the default 5 ms switch interval, a lock
        # holder needing a few µs of interpreter time can be starved for
        # whole scheduling ROUNDS (15 threads × 5 ms ≈ 75 ms) under CPU
        # saturation — which reads as 100 ms+ lock waits on µs-scale
        # locks. A 1 ms interval trades a few percent of pure-Python
        # throughput for 5× tighter lock-wait tails (the SOAK_r08
        # contention acceptance measured exactly this). Restored by
        # stop(): the cost is for the threaded control plane's lifetime,
        # not the embedding process's.
        import sys
        if sys.getswitchinterval() > 0.001:
            self._prev_switch_interval = sys.getswitchinterval()
            sys.setswitchinterval(0.001)
        self._stop.clear()
        self._threads = [
            threading.Thread(target=self._run, args=(s,),
                             name=f"controller-{s.name}", daemon=True)
            for s in self.specs]
        for t in self._threads:
            t.start()
        return self

    def stop(self, timeout: float = 5.0) -> bool:
        """Signal every controller and join. Returns True when all threads
        exited; a thread still blocked (e.g. mid device solve) past the
        timeout stays tracked, so ``running`` keeps reporting True and a
        caller can stop() again rather than proceed over live mutation.
        A held lease is released so a standby takes over immediately."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout)
        self._threads = [t for t in self._threads if t.is_alive()]
        # release only AFTER the election thread joined — releasing first
        # races its in-flight tick, which would re-acquire the lease and
        # orphan it on a dead process (standby then waits out the full
        # lease duration instead of taking over immediately)
        if self.elector is not None:
            self.elector.release()
        if not self._threads and getattr(self, "_prev_switch_interval",
                                         None) is not None:
            # the control plane's tightened GIL switch interval must not
            # outlive it in the embedding process
            import sys
            sys.setswitchinterval(self._prev_switch_interval)
            self._prev_switch_interval = None
        return not self._threads

    def crash_stop(self, timeout: float = 5.0) -> bool:
        """kill -9 semantics for chaos (weather OperatorKill
        mode="kill"): stop every thread WITHOUT releasing the lease — a
        crashed process never runs its shutdown path, so the standby
        must wait out the full lease duration before it may promote
        (the blackout window the orphaned-lease sweep cleans up after).
        The tightened switch interval is still restored: the embedding
        process lives on, only the operator 'died'."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout)
        self._threads = [t for t in self._threads if t.is_alive()]
        if not self._threads and getattr(self, "_prev_switch_interval",
                                         None) is not None:
            import sys
            sys.setswitchinterval(self._prev_switch_interval)
            self._prev_switch_interval = None
        return not self._threads

    def pause(self) -> None:
        """Freeze every controller thread in place (OperatorKill
        mode="hang"): loops keep spinning but reconcile nothing,
        including the election tick — the hung-leader failure mode."""
        self._pause.set()

    def resume(self) -> None:
        self._pause.clear()

    @property
    def paused(self) -> bool:
        return self._pause.is_set()

    @property
    def running(self) -> bool:
        return any(t.is_alive() for t in self._threads)


def operator_specs(op) -> List[ControllerSpec]:
    """The production cadence map for an Operator's controllers (the
    reference's per-controller registration in controllers.go)."""
    specs = []
    if getattr(op, "sync", None) is not None:
        # API mode: the informer pump feeds the mirror continuously (its
        # own thread = the reflector goroutines of the reference manager).
        # NOT leadership-gated: standbys keep their mirror warm (and their
        # watch queues drained) so failover starts hot, like client-go
        # informers running on every replica
        specs.append(ControllerSpec("statesync", op.sync.sync_once,
                                    interval=0.05,
                                    gate_on_leadership=False))
    specs += [
        ControllerSpec("provisioning",
                       lambda: (op.provisioner.provision_once()
                                if op.provisioner.batch_ready() else None),
                       interval=0.2),
        ControllerSpec("nodeclass", op.nodeclass_controller.reconcile,
                       interval=10.0),
        ControllerSpec("pricing", op.pricing_controller.reconcile,
                       interval=60.0),
        ControllerSpec("lifecycle", op.lifecycle.reconcile, interval=1.0),
        ControllerSpec("tagging", op.tagging.reconcile, interval=5.0),
        ControllerSpec("disruption", op.disruption.reconcile, interval=10.0),
        ControllerSpec("termination", op.termination.reconcile, interval=1.0),
        ControllerSpec("gc", op.gc.reconcile, interval=60.0),
        ControllerSpec("ice-cleanup", op.unavailable.cleanup, interval=10.0),
        ControllerSpec("metrics", op.emit_gauges, interval=5.0),
    ]
    if op.interruption is not None:
        specs.append(ControllerSpec("interruption",
                                    op.interruption.reconcile, interval=1.0))
    return specs
