"""Admission webhooks: defaulting + validation.

Mirror of the reference's knative-style admission controllers (reference
pkg/webhooks/webhooks.go over pkg/apis/v1beta1 CEL rules + core NodePool
validation). Invalid objects are rejected before they enter the control
plane; defaulting fills the canonical optional fields.
"""

from __future__ import annotations

import math
from typing import List

from .apis import wellknown as wk
from .apis.objects import NodeClass, NodePool
from .apis.requirements import Operator, Requirement
from .apis.resources import RESOURCE_AXES, resources_to_vec
from .providers.amifamily import AMI_FAMILIES

# keys users may not constrain (reference restricted label domains)
RESTRICTED_LABEL_DOMAINS = ("kubernetes.io/hostname",)


class AdmissionError(ValueError):
    pass


def default_node_pool(pool: NodePool) -> NodePool:
    """Defaulting admission: canonical capacity-type + arch + os
    requirements when unset (core NodePool defaults)."""
    keys = {r.key for r in pool.requirements}
    if wk.LABEL_CAPACITY_TYPE not in keys:
        pool.requirements.append(Requirement(
            wk.LABEL_CAPACITY_TYPE, Operator.IN, (wk.CAPACITY_TYPE_ON_DEMAND,)))
    if wk.LABEL_ARCH not in keys:
        pool.requirements.append(Requirement(wk.LABEL_ARCH, Operator.IN, ("amd64",)))
    if wk.LABEL_OS not in keys:
        pool.requirements.append(Requirement(wk.LABEL_OS, Operator.IN, ("linux",)))
    return pool


def validate_node_pool(pool: NodePool) -> List[str]:
    """Validation admission; returns error strings (empty = admitted)."""
    errs: List[str] = []
    if not pool.name:
        errs.append("name is required")
    for r in pool.requirements:
        if r.key in RESTRICTED_LABEL_DOMAINS:
            errs.append(f"requirement on restricted key {r.key!r}")
        if r.min_values is not None and r.min_values < 1:
            errs.append(f"minValues must be >= 1 (key {r.key})")
        if r.key == wk.LABEL_OS:
            # a pool's nodes boot ONE OS (the AMI family's): the os
            # requirement must name exactly one of linux|windows
            if (r.operator != Operator.IN or len(r.values) != 1
                    or r.values[0] not in ("linux", "windows")):
                errs.append("the os requirement must be a single-valued In "
                            "over linux|windows (a pool's nodes boot one "
                            f"OS), got {r.operator.value} {r.values}")
    for key in pool.limits:
        if key not in RESOURCE_AXES:
            errs.append(f"unknown limit resource {key!r}")
        else:
            try:
                resources_to_vec({key: pool.limits[key]})
            except Exception as e:
                errs.append(f"bad limit quantity for {key}: {e}")
    d = pool.disruption
    if d.consolidation_policy not in ("WhenUnderutilized", "WhenEmpty"):
        errs.append(f"unknown consolidationPolicy {d.consolidation_policy!r}")
    if d.consolidation_policy == "WhenEmpty" and d.consolidate_after is None:
        errs.append("consolidateAfter is required with WhenEmpty")
    for b in d.budgets:
        spec = str(b.nodes)
        try:
            val = float(spec[:-1]) if spec.endswith("%") else int(spec)
            if val < 0:
                errs.append(f"budget nodes must be >= 0, got {b.nodes!r}")
        except ValueError:
            errs.append(f"bad budget nodes value {b.nodes!r}")
        # CRD karpenter.sh_nodepools.yaml:111-112: 'schedule' must be set
        # with 'duration' (and vice versa); the schedule must parse
        if (b.schedule is None) != (b.duration is None):
            errs.append("budget schedule and duration must be set together")
        if b.duration is not None and b.duration <= 0:
            # a non-positive duration would make the window unsatisfiable
            # and the budget silently never apply
            errs.append("budget duration must be > 0 seconds")
        if b.schedule is not None:
            from .utils.cron import Cron
            try:
                Cron(b.schedule)
            except ValueError as e:
                errs.append(f"bad budget schedule: {e}")
    if pool.weight < 0 or pool.weight > 100:
        errs.append("weight must be in [0, 100]")
    return errs


def validate_node_class(nc: NodeClass) -> List[str]:
    """EC2NodeClass-analog validation (pkg/apis/v1beta1 CEL rules)."""
    errs: List[str] = []
    if not nc.name:
        errs.append("name is required")
    if nc.ami_family not in AMI_FAMILIES:
        errs.append(f"unknown amiFamily {nc.ami_family!r}")
    if nc.ami_family == "Custom" and not nc.ami_selector_terms:
        errs.append("amiSelectorTerms required with the Custom amiFamily")
    if nc.role and nc.instance_profile:
        errs.append("role and instanceProfile are mutually exclusive")
    if not nc.role and not nc.instance_profile:
        errs.append("one of role or instanceProfile is required")
    for t in nc.subnet_selector_terms + nc.security_group_selector_terms + nc.ami_selector_terms:
        if not t.tags and not t.id and not t.name:
            errs.append("selector term needs tags, id, or name")
    mo = nc.metadata_options
    if mo.http_tokens not in ("required", "optional"):
        errs.append(f"httpTokens must be required|optional, got {mo.http_tokens!r}")
    if mo.http_endpoint not in ("enabled", "disabled"):
        errs.append(f"httpEndpoint must be enabled|disabled, got {mo.http_endpoint!r}")
    if nc.instance_store_policy not in (None, "RAID0"):
        errs.append("instanceStorePolicy must be RAID0 when set, got "
                    f"{nc.instance_store_policy!r}")
    roots = 0
    for b in nc.block_device_mappings:
        if not isinstance(b, dict) or not b.get("device_name"):
            errs.append("blockDeviceMapping needs a device_name")
            continue
        if b.get("root_volume"):
            roots += 1
        size = b.get("volume_size_mib")
        if size is not None and (
                isinstance(size, bool)          # bool is an int subclass
                or not isinstance(size, (int, float))
                or not math.isfinite(size) or size <= 0):
            errs.append(f"blockDeviceMapping {b['device_name']!r} "
                        "volume_size_mib must be a positive finite number")
    if roots > 1:
        errs.append("at most one blockDeviceMapping may set root_volume")
    return errs


def validate_pdb(pdb) -> List[str]:
    """policy/v1 PodDisruptionBudget validation: exactly one of
    maxUnavailable / minAvailable, both non-negative."""
    errs: List[str] = []
    if not pdb.name:
        errs.append("name is required")
    has_max = pdb.max_unavailable is not None
    has_min = pdb.min_available is not None
    if has_max == has_min:
        errs.append("exactly one of maxUnavailable / minAvailable is required")
    if has_max and int(pdb.max_unavailable) < 0:
        errs.append("maxUnavailable must be >= 0")
    if has_min and int(pdb.min_available) < 0:
        errs.append("minAvailable must be >= 0")
    return errs


def admit_pdb(pdb):
    errs = validate_pdb(pdb)
    if errs:
        raise AdmissionError(f"PodDisruptionBudget/{pdb.name}: " + "; ".join(errs))
    return pdb


def admit_node_pool(pool: NodePool) -> NodePool:
    pool = default_node_pool(pool)
    errs = validate_node_pool(pool)
    if errs:
        raise AdmissionError(f"NodePool/{pool.name}: " + "; ".join(errs))
    return pool


def admit_node_class(nc: NodeClass) -> NodeClass:
    errs = validate_node_class(nc)
    if errs:
        raise AdmissionError(f"NodeClass/{nc.name}: " + "; ".join(errs))
    return nc


def validate_wire(kind: str, spec) -> List[str]:
    """One validation entry over WIRE dicts: schema first (apis/schema.py,
    the CRD contract), then the semantic webhook for the kind. This is
    what the in-process apiserver admission runs (kube/client.py) and
    what the HTTP /validate endpoint serves (cli.py) — same answer at
    every boundary."""
    from .apis import schema, serde
    KNOWN = ("nodepools", "nodeclasses", "pdbs", "nodeclaims")
    # the NodeClass CRD's real-world plural (deploy/crds,
    # webhooks.yaml registration) — same object, same validation
    if kind == "ec2nodeclasses":
        kind = "nodeclasses"
    if kind not in KNOWN:
        # an "allowed" answer for a kind we cannot validate would be a
        # false green light (the apiserver rejects unknown kinds)
        return [f"unknown kind {kind!r}; validatable kinds: "
                + ", ".join(KNOWN)]
    errs = schema.validate(kind, spec)
    if errs:
        return errs
    try:
        if kind == "nodepools":
            return validate_node_pool(serde.nodepool_from_dict(spec))
        if kind == "nodeclasses":
            return validate_node_class(serde.nodeclass_from_dict(spec))
        if kind == "pdbs":
            return validate_pdb(serde.pdb_from_dict(spec))
    except Exception as e:  # malformed-but-schema-clean input must reject
        return [f"validation failed: {e}"]
    return []   # nodeclaims: schema-only (status is controller-owned)
