"""Grouped-FFD bin-packing scan — the device scheduler kernel.

The reference packs pods one at a time in a sequential Go loop (core
provisioner, designs/bin-packing.md:16-43): O(pods x nodes x types) scalar
work per scheduling pass. This kernel reformulates that loop TPU-first:

- Pods are pre-deduplicated into G groups (solver/problem.py), so the scan
  is over **groups**, not pods — 50k pods collapse to a few dozen steps.
- Each scan step is pure dense vector math over [bins x types (x resources)]
  blocks: per-bin per-type fit counts via broadcasted floor-division,
  offering availability via an einsum that XLA lowers onto the MXU,
  first-fit assignment of the *whole group* via an exclusive cumsum over the
  bin axis, and new-node opening via iota arithmetic — no data-dependent
  control flow, fully static shapes, jit-compiled once per bucket shape.
- A group may split across many bins in one step (exactly what per-pod FFD
  would do for identical pods), so the scan length is G, not P.
- Every bin keeps the full **set** of instance types that can still hold its
  contents (a boolean row over the type axis) instead of committing early;
  finalization picks the cheapest available (type, zone, capacity-type)
  offering per bin — the same "launch the cheapest compatible shape"
  decision the reference delegates to CreateFleet's lowest-price strategy
  (pkg/providers/instance/instance.go:356-372).

Numerical contract: resources are float32 in canonical units (millicores /
MiB / counts); counts are int32. ``EPS`` absorbs float32 rounding in
capacity comparisons.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

EPS = 1e-3

# Finalization backend: the Pallas streaming kernel (ops/offering_argmin.py)
# avoids the [B,T,Z,C] masked-price intermediate the XLA form materializes
# (~185 MB at the 8192-bin bucket). Solver.__init__ probes the backend and
# flips this before the first trace; pack() reads it at trace time.
_PALLAS_ARGMIN = {"enabled": False, "interpret": False}

# Bin-table floor below which the Pallas finalization is not worth its
# compile time (see pack() finalization comment).
_PALLAS_MIN_B = 4096


def _clear_pack_caches() -> None:
    # the flag binds at trace time; a toggle must invalidate every jitted
    # entry point that read it, or same-shape calls keep the old trace
    pack.clear_cache()
    pack_packed.clear_cache()
    pack_packed_fused.clear_cache()
    pack_packed_efused.clear_cache()
    pack_packed_combined.clear_cache()
    pack_probe_fused.clear_cache()


def enable_pallas_argmin(interpret: bool = False) -> bool:
    """Turn on the Pallas finalization if it lowers on this backend (or
    unconditionally in interpreter mode, for tests). Returns enabled."""
    from . import offering_argmin
    if interpret or offering_argmin.probe():
        if not _PALLAS_ARGMIN["enabled"] or \
                _PALLAS_ARGMIN["interpret"] != interpret:
            _clear_pack_caches()
        _PALLAS_ARGMIN["enabled"] = True
        _PALLAS_ARGMIN["interpret"] = interpret
        return True
    return False


def disable_pallas_argmin() -> None:
    if _PALLAS_ARGMIN["enabled"]:
        _clear_pack_caches()
    _PALLAS_ARGMIN["enabled"] = False
    _PALLAS_ARGMIN["interpret"] = False


class BinState(NamedTuple):
    """Scan carry: the open-bin table."""

    cum: jnp.ndarray        # [B,R] f32 committed resources (incl. daemonset overhead)
    tmask: jnp.ndarray      # [B,T] bool instance types that can still hold this bin
    zmask: jnp.ndarray      # [B,Z] bool zones still possible
    cmask: jnp.ndarray      # [B,C] bool capacity types still possible
    np_id: jnp.ndarray      # [B] i32 owning nodepool (-1 = unassigned)
    npods: jnp.ndarray      # [B] i32 pods placed
    open: jnp.ndarray       # [B] bool
    fixed: jnp.ndarray      # [B] bool existing capacity (type pinned, not re-priced)
    alloc_cap: jnp.ndarray  # [B,R] f32 per-bin allocatable ceiling (+inf for new
                            # bins; a real node's reported allocatable for fixed
                            # bins, which may differ from the lattice's)
    pm: jnp.ndarray         # [B,A] i32 count of the bin's pods matching class a
                            # (>0 = presence for affinity; exact count feeds the
                            # hostname-spread skew cap)
    po: jnp.ndarray         # [B,A] bool bin holds >=1 pod owning anti-affinity term a
    next_open: jnp.ndarray  # scalar i32 first unopened bin slot


class GroupBatch(NamedTuple):
    """Scan xs: one row per (FFD-sorted) pod group."""

    req: jnp.ndarray      # [G,R] f32
    count: jnp.ndarray    # [G] i32 (0 = padding row)
    g_type: jnp.ndarray   # [G,T] bool
    g_zone: jnp.ndarray   # [G,Z] bool
    g_cap: jnp.ndarray    # [G,C] bool
    g_np: jnp.ndarray        # [G,NP] bool
    max_per_bin: jnp.ndarray  # [G] i32 per-bin cap (hostname spread maxSkew /
                              # self-anti-affinity=1; INT32_MAX = unlimited)
    spread_class: jnp.ndarray  # [G] i32 class whose per-bin COUNT the cap tracks
                               # (hostname spread selector; -1 = cap is per-row,
                               # counts only this row's own placements)
    single_bin: jnp.ndarray   # [G] bool all replicas must share one bin
                              # (hostname self-affinity)
    match: jnp.ndarray        # [G,A] bool affinity classes matching the group labels
    owner: jnp.ndarray        # [G,A] bool hostname anti-affinity terms the group owns
    need: jnp.ndarray         # [G,A] bool classes whose presence the bin must have
                              # (hostname positive affinity)
    strict_custom: jnp.ndarray  # [G] bool: group has existence-requiring custom-key
                                # constraints -> excluded from unknown-pool bins


class PoolParams(NamedTuple):
    np_type: jnp.ndarray  # [NP,T] bool
    np_zone: jnp.ndarray  # [NP,Z] bool
    np_cap: jnp.ndarray   # [NP,C] bool
    ds: jnp.ndarray       # [NP,R] f32 daemonset overhead for a new node
    cap: jnp.ndarray      # [NP,R] f32 per-pool allocatable ceiling for NEW
                          # bins (+inf = lattice alloc rules alone; the
                          # NodePool kubelet maxPods knob caps the pods axis)


class PackResult(NamedTuple):
    assign: jnp.ndarray     # [G,B] i32 pods of group g placed into bin b
    leftover: jnp.ndarray   # [G] i32 pods that fit nowhere (bucket overflow / infeasible)
    state: BinState
    chosen_t: jnp.ndarray   # [B] i32 instance-type index (finalized, new bins only)
    chosen_z: jnp.ndarray   # [B] i32 zone index
    chosen_c: jnp.ndarray   # [B] i32 capacity-type index
    chosen_price: jnp.ndarray  # [B] f32 $/hr (+inf for fixed/empty bins)


def empty_state(B: int, T: int, Z: int, C: int, R: int, A: int = 1) -> BinState:
    return BinState(
        cum=jnp.zeros((B, R), jnp.float32),
        tmask=jnp.zeros((B, T), bool),
        zmask=jnp.zeros((B, Z), bool),
        cmask=jnp.zeros((B, C), bool),
        np_id=jnp.full((B,), -1, jnp.int32),
        npods=jnp.zeros((B,), jnp.int32),
        open=jnp.zeros((B,), bool),
        fixed=jnp.zeros((B,), bool),
        alloc_cap=jnp.full((B, R), jnp.inf, jnp.float32),
        pm=jnp.zeros((B, A), jnp.int32),
        po=jnp.zeros((B, A), bool),
        next_open=jnp.array(0, jnp.int32),
    )


def _fit_counts(headroom: jnp.ndarray, req: jnp.ndarray) -> jnp.ndarray:
    """[...,R] headroom, [R] request -> [...] how many replicas fit.

    Axes the group doesn't request don't constrain; a group requesting
    nothing at all (padding) fits 'infinitely' and is neutralized by count=0.
    """
    req_safe = jnp.where(req > 0, req, 1.0)
    per_axis = jnp.where(req > 0, jnp.floor((headroom + EPS) / req_safe), jnp.inf)
    n = jnp.min(per_axis, axis=-1)
    return jnp.clip(jnp.nan_to_num(n, posinf=1e9), 0.0, 1e9)


def _offer_reachable(avail_f: jnp.ndarray, zm: jnp.ndarray, cm: jnp.ndarray) -> jnp.ndarray:
    """avail [T,Z,C] f32, zm [...,Z] bool, cm [...,C] bool -> [...,T] bool:
    does type t have any available offering inside the zone x captype mask?
    The contraction is a small matmul -> MXU-friendly."""
    zc = zm.astype(jnp.float32)[..., :, None] * cm.astype(jnp.float32)[..., None, :]
    flat = zc.reshape(zc.shape[:-2] + (-1,))             # [...,Z*C]
    a = avail_f.reshape(avail_f.shape[0], -1)            # [T,Z*C]
    return (flat @ a.T) > 0.5                            # [...,T]


def _pack_step(alloc: jnp.ndarray, avail_f: jnp.ndarray, pools: PoolParams,
               state: BinState, g: GroupBatch) -> Tuple[BinState, Tuple[jnp.ndarray, jnp.ndarray]]:
    B, T = state.tmask.shape
    NP = pools.np_type.shape[0]

    # ---- phase 1: fill existing/open bins, first-fit in bin order ----
    tm = state.tmask & g.g_type[None, :]                       # [B,T]
    zm = state.zmask & g.g_zone[None, :]                       # [B,Z]
    cm = state.cmask & g.g_cap[None, :]                        # [B,C]
    np_ok = jnp.where(state.np_id >= 0,
                      g.g_np[jnp.clip(state.np_id, 0, NP - 1)],
                      # unknown-pool bins: pool-agnostic, but never for groups
                      # with strict custom-key constraints we cannot verify
                      ~g.strict_custom)
    # hostname (anti-)affinity: both directions of the k8s symmetry check —
    # the bin may hold no pod the group anti-affines against, no pod whose
    # anti term matches the group, and must hold every class the group needs
    pm_pos = state.pm > 0                                      # [B,A]
    conflict = ((pm_pos & g.owner[None, :]).any(axis=1)
                | (state.po & g.match[None, :]).any(axis=1))   # [B]
    need_ok = jnp.all(pm_pos | ~g.need[None, :], axis=1)       # [B]
    aff_ok = ~conflict & need_ok
    # a running node needs no *market* availability — only new capacity does
    reachable = _offer_reachable(avail_f, zm, cm) | state.fixed[:, None]  # [B,T]
    # per-(bin,type) allocatable: lattice truth capped by the bin's own
    # reported allocatable (real nodes can reserve more than the lattice says)
    eff_alloc = jnp.minimum(alloc[None, :, :], state.alloc_cap[:, None, :])  # [B,T,R]
    headroom = eff_alloc - state.cum[:, None, :]               # [B,T,R]
    n_fit_t = _fit_counts(headroom, g.req)                     # [B,T]
    valid_t = tm & reachable & (np_ok & aff_ok & state.open)[:, None]
    n_fit = jnp.max(jnp.where(valid_t, n_fit_t, 0.0), axis=1).astype(jnp.int32)  # [B]
    # hostname-spread cap: remaining allowance = maxSkew - pods of the spread
    # class ALREADY in the bin (bound pods + sibling groups count); for
    # class-less caps (self-anti-affinity) the bin history is covered by the
    # affinity conflict check, so the row cap alone applies
    A = state.pm.shape[1]
    cls_cnt = state.pm[:, jnp.clip(g.spread_class, 0, A - 1)]  # [B]
    allowance = jnp.where(g.spread_class >= 0,
                          jnp.maximum(g.max_per_bin - cls_cnt, 0), g.max_per_bin)
    n_fit = jnp.minimum(n_fit, allowance)
    prior = jnp.cumsum(n_fit) - n_fit                          # exclusive cumsum = first-fit order
    take_ff = jnp.clip(g.count - prior, 0, n_fit)              # [B]
    # single-bin groups (hostname self-affinity): all replicas into the first
    # bin that can hold any; the un-fitting remainder becomes leftover
    can = n_fit > 0
    is_first = (jnp.arange(B, dtype=jnp.int32) == jnp.argmax(can).astype(jnp.int32)) & jnp.any(can)
    take = jnp.where(g.single_bin, jnp.where(is_first, jnp.minimum(g.count, n_fit), 0), take_ff)
    rem = g.count - jnp.sum(take)

    updated = take > 0
    cum1 = state.cum + take[:, None].astype(jnp.float32) * g.req[None, :]

    # ---- phase 2: open new bins for the remainder ----
    # pick the highest-weight pool (pools are weight-sorted) where a fresh
    # node can hold >=1 pod of this group
    tm_np = pools.np_type & g.g_type[None, :]                  # [NP,T]
    zm_np = pools.np_zone & g.g_zone[None, :]                  # [NP,Z]
    cm_np = pools.np_cap & g.g_cap[None, :]                    # [NP,C]
    reach_np = _offer_reachable(avail_f, zm_np, cm_np)         # [NP,T]
    # a pool's allocatable ceiling (kubelet maxPods etc.) caps fresh-node
    # headroom alongside the per-type lattice allocatable
    head_np = (jnp.minimum(alloc[None, :, :], pools.cap[:, None, :])
               - pools.ds[:, None, :])                         # [NP,T,R]
    n_per_t = _fit_counts(head_np, g.req)                      # [NP,T]
    valid_np_t = tm_np & reach_np & g.g_np[:, None]
    n_per_np = jnp.max(jnp.where(valid_np_t, n_per_t, 0.0), axis=1).astype(jnp.int32)  # [NP]
    n_per_np = jnp.minimum(n_per_np, g.max_per_bin)
    ok_np = n_per_np >= 1
    np_star = jnp.argmax(ok_np).astype(jnp.int32)              # first True (weight order)
    any_ok = jnp.any(ok_np)
    n_per = n_per_np[np_star]

    # a fresh (empty) bin satisfies presence requirements only by self-seeding:
    # every needed class must match the group's own labels
    seed_ok = jnp.all(g.match | ~g.need)
    want_new = (rem > 0) & any_ok & seed_ok
    # single-bin groups never straddle phase-1 bins + a new bin, and open at
    # most one node
    want_new &= ~(g.single_bin & (jnp.sum(take) > 0))
    n_per_safe = jnp.maximum(n_per, 1)
    n_new = jnp.where(want_new, -(-rem // n_per_safe), 0)      # ceil div
    n_new = jnp.where(g.single_bin, jnp.minimum(n_new, 1), n_new)
    n_new = jnp.minimum(n_new, B - state.next_open)            # bucket overflow clamp

    idx = jnp.arange(B, dtype=jnp.int32)
    rel = idx - state.next_open
    is_new = (rel >= 0) & (rel < n_new)
    take_new = jnp.where(is_new, jnp.clip(rem - rel * n_per_safe, 0, n_per_safe), 0)

    cum2 = jnp.where(is_new[:, None],
                     pools.ds[np_star][None, :] + take_new[:, None].astype(jnp.float32) * g.req[None, :],
                     cum1)

    # ---- shrink masks once, for updated + new bins together ----
    # new bins carry their pool's allocatable ceiling from birth; the
    # fit check this step must already see it (later steps read it from
    # the carried alloc_cap)
    alloc_cap2 = jnp.where(is_new[:, None], pools.cap[np_star][None, :],
                           state.alloc_cap)
    eff_alloc2 = jnp.minimum(alloc[None, :, :], alloc_cap2[:, None, :])
    still_fits = jnp.all(eff_alloc2 + EPS >= cum2[:, None, :], axis=-1)  # [B,T]
    tmask2 = jnp.where(is_new[:, None], tm_np[np_star][None, :] & reach_np[np_star][None, :],
                       jnp.where(updated[:, None], tm & reachable, state.tmask))
    tmask2 = tmask2 & jnp.where((is_new | updated)[:, None], still_fits, True)
    zmask2 = jnp.where(is_new[:, None], zm_np[np_star][None, :],
                       jnp.where(updated[:, None], zm, state.zmask))
    cmask2 = jnp.where(is_new[:, None], cm_np[np_star][None, :],
                       jnp.where(updated[:, None], cm, state.cmask))

    n_placed = take + take_new                                 # [B] i32
    placed = n_placed > 0
    new_state = BinState(
        cum=cum2,
        tmask=tmask2,
        zmask=zmask2,
        cmask=cmask2,
        np_id=jnp.where(is_new, np_star, state.np_id),
        npods=state.npods + take + take_new,
        open=state.open | is_new,
        fixed=state.fixed,
        alloc_cap=alloc_cap2,
        pm=state.pm + n_placed[:, None] * g.match[None, :].astype(jnp.int32),
        po=state.po | (placed[:, None] & g.owner[None, :]),
        next_open=state.next_open + n_new,
    )
    leftover = rem - jnp.sum(take_new)
    return new_state, (take + take_new, leftover)


@partial(jax.jit, static_argnames=())
def pack(alloc: jnp.ndarray, avail: jnp.ndarray, price: jnp.ndarray,
         groups: GroupBatch, pools: PoolParams, init: BinState) -> PackResult:
    """Run the grouped-FFD scan + cheapest-offering finalization.

    All shapes static: G groups (padded), B bins (bucketed), T x Z x C
    lattice. Returns per-group-per-bin assignment counts, per-group leftover
    (infeasible / bucket overflow — host retries with a bigger bucket), the
    final bin table, and each new bin's chosen offering.
    """
    avail_f = avail.astype(jnp.float32)
    step = partial(_pack_step, alloc, avail_f, pools)
    state, (assign, leftover) = jax.lax.scan(step, init, groups)

    # ---- finalization: cheapest available offering per new bin ----
    B = state.cum.shape[0]
    live = state.open & ~state.fixed & (state.npods > 0)
    T, Z, C = price.shape
    from .offering_argmin import _ZCP
    # lattices with more than one lane tile of zone×captype combinations
    # exceed the kernel's padded zc axis — use the XLA form there (the
    # probe can't see this; it runs fixed small shapes). Below
    # _PALLAS_MIN_B bins the XLA intermediate is small enough that the two
    # forms run identically (measured equal at B=1024) while the Mosaic
    # trace adds ~20 s of compile per shape bucket — the kernel only pays
    # at the large buckets (interpret mode bypasses the floor: tests).
    if _PALLAS_ARGMIN["enabled"] and Z * C <= _ZCP and \
            (_PALLAS_ARGMIN["interpret"] or B >= _PALLAS_MIN_B):
        from .offering_argmin import cheapest_offering_pallas
        Tp = -(-T // 128) * 128
        Bp = -(-B // 128) * 128
        p2 = jnp.full((Tp, _ZCP), jnp.inf, jnp.float32)
        p2 = p2.at[:T, : Z * C].set(
            jnp.where(avail, price, jnp.inf).reshape(T, Z * C))
        tm = jnp.zeros((Bp, Tp), jnp.float32)
        tm = tm.at[:B, :T].set(state.tmask.astype(jnp.float32))
        zc2 = (state.zmask[:, :, None] & state.cmask[:, None, :]
               ).reshape(B, Z * C).astype(jnp.float32)
        zc = jnp.zeros((Bp, _ZCP), jnp.float32).at[:B, : Z * C].set(zc2)
        best_v, best_i = cheapest_offering_pallas(
            tm, zc, p2, interpret=_PALLAS_ARGMIN["interpret"])
        best_v, best_i = best_v[:B], best_i[:B]
        chosen_t = (best_i // _ZCP).astype(jnp.int32)
        rem = best_i % _ZCP
        chosen_z = (rem // C).astype(jnp.int32)
        chosen_c = (rem % C).astype(jnp.int32)
        chosen_price = jnp.where(live, best_v, jnp.inf)
    else:
        p = jnp.where(avail, price, jnp.inf)                      # [T,Z,C]
        p_bin = jnp.where(state.tmask[:, :, None, None]
                          & state.zmask[:, None, :, None]
                          & state.cmask[:, None, None, :],
                          p[None, :, :, :], jnp.inf)              # [B,T,Z,C]
        flat = p_bin.reshape(B, -1)
        best = jnp.argmin(flat, axis=1)
        chosen_t = (best // (Z * C)).astype(jnp.int32)
        chosen_z = ((best // C) % Z).astype(jnp.int32)
        chosen_c = (best % C).astype(jnp.int32)
        chosen_price = jnp.where(live, flat[jnp.arange(B), best], jnp.inf)

    return PackResult(assign=assign, leftover=leftover, state=state,
                      chosen_t=chosen_t, chosen_z=chosen_z, chosen_c=chosen_c,
                      chosen_price=chosen_price)


def _encode_decode_set(res: PackResult, lean: bool = False) -> jnp.ndarray:
    """Fuse everything the host decode needs into ONE uint8 buffer.

    The host↔device link pays a ~fixed latency per transfer (measured
    ~100 ms over a tunneled TPU; tens of µs over PCIe) — fetching the 18
    result leaves separately dominated end-to-end solve time. This packs the
    per-bin decode set into a [B+n_trailer, W] uint8 array so the host pays
    exactly one device→host round trip.

    Full row layout (per bin): npods i32 | np_id i32 | chosen_t i32 |
    chosen_z i32 | chosen_c i32 | chosen_price f32 | open u8 | fixed u8 |
    packed tmask | packed zmask | packed cmask | assign-column int16[G] |
    cum f32[R] | alloc_cap f32[R] | pm int16[A] | packed po. Trailer rows:
    leftover int32[G] + next_open i32, zero-padded. Assignment counts and
    pm class counts fit int16: every pod consumes 1 of the node's bounded
    pod capacity, so per-bin counts stay well under 2^15.

    ``lean`` keeps only what the single-device plan decode reads and
    narrows the index dtypes — np_id i16 | chosen_t i16 | chosen_z u8 |
    chosen_c u8 | chosen_price f32 | flags u8 (bit0 open, bit1 fixed) |
    packed tmask | packed zmask | packed cmask | assign int16[G] — a ~33%
    smaller transfer over the latency-bound link. Only the per-shard
    decode of a sharded pack (decode_sharded_pack) still needs the full
    layout: its tail-bin merge rebuilds bin state from cum/alloc_cap/pm/po
    of the SHARD results (the merge's own result is lean again).
    """
    st = res.state
    B, _T = st.tmask.shape
    G = res.assign.shape[0]

    def i32_rows(x):
        return jax.lax.bitcast_convert_type(x, jnp.uint8).reshape(B, -1)

    def i16_rows(x):
        return jax.lax.bitcast_convert_type(
            x.astype(jnp.int16), jnp.uint8).reshape(B, -1)

    # segment shared by both layouts (and by both sides of the decoder)
    masks_assign = [
        jnp.packbits(st.tmask, axis=1),
        jnp.packbits(st.zmask, axis=1),
        jnp.packbits(st.cmask, axis=1),
        jax.lax.bitcast_convert_type(
            res.assign.astype(jnp.int16).T, jnp.uint8).reshape(B, -1),
    ]
    if lean:
        # narrow dtypes hold: T < 2^15 types, Z/C < 2^8 zones/captypes
        assert _T < 2 ** 15 and st.zmask.shape[1] < 256 \
            and st.cmask.shape[1] < 256
        rows = jnp.concatenate([
            i16_rows(st.np_id),
            i16_rows(res.chosen_t),
            res.chosen_z.astype(jnp.uint8)[:, None],
            res.chosen_c.astype(jnp.uint8)[:, None],
            i32_rows(res.chosen_price),
            (st.open.astype(jnp.uint8)
             | (st.fixed.astype(jnp.uint8) << 1))[:, None],
        ] + masks_assign, axis=1)
    else:
        rows = jnp.concatenate([
            i32_rows(st.npods.astype(jnp.int32)),
            i32_rows(st.np_id.astype(jnp.int32)),
            i32_rows(res.chosen_t), i32_rows(res.chosen_z), i32_rows(res.chosen_c),
            i32_rows(res.chosen_price),
            st.open.astype(jnp.uint8)[:, None],
            st.fixed.astype(jnp.uint8)[:, None],
        ] + masks_assign + [
            i32_rows(st.cum),
            i32_rows(st.alloc_cap),
            jax.lax.bitcast_convert_type(
                st.pm.astype(jnp.int16), jnp.uint8).reshape(B, -1),
            jnp.packbits(st.po, axis=1),
        ], axis=1)
    W = rows.shape[1]
    tail = jnp.concatenate([
        jax.lax.bitcast_convert_type(res.leftover.astype(jnp.int32), jnp.uint8).reshape(-1),
        jax.lax.bitcast_convert_type(res.state.next_open.reshape(1), jnp.uint8).reshape(-1),
    ])
    n_trailer = -(-tail.shape[0] // W)
    flat = jnp.zeros((n_trailer * W,), jnp.uint8).at[: tail.shape[0]].set(tail)
    return jnp.concatenate([rows, flat.reshape(n_trailer, W)], axis=0)


@partial(jax.jit, static_argnames=("lean",))
def pack_packed(alloc: jnp.ndarray, avail: jnp.ndarray, price: jnp.ndarray,
                groups: GroupBatch, pools: PoolParams, init: BinState,
                lean: bool = False) -> jnp.ndarray:
    """pack() + single-buffer result encoding (see _encode_decode_set)."""
    # lean narrows np_id to i16; the pool axis must fit (T/Z/C bounds are
    # asserted inside the encoder, where their shapes are visible)
    assert not lean or pools.np_type.shape[0] < 2 ** 15
    return _encode_decode_set(pack(alloc, avail, price, groups, pools, init),
                              lean=lean)


class FieldSpec(NamedTuple):
    """One field of the staged solver input (see group_layout)."""

    name: str       # GroupBatch / PoolParams field
    offset: int     # byte offset in the fused buffer
    dtype: object   # np.float32 | np.int32 | np.uint8 (uint8 = bool)
    shape: tuple
    src: str        # solver.problem.Problem attribute holding the data
    fill: float     # pad value beyond the problem's true extent


def group_layout(G: int, T: int, Z: int, C: int, NP: int, A: int,
                 R: int) -> Tuple[Tuple[FieldSpec, ...], int]:
    """Static spec of the staged solver input: byte layout of the fused
    GroupBatch+PoolParams upload AND the single source of truth for which
    Problem attribute feeds each field with which pad fill — both the
    fused path (every production solve/probe/sharded staging) and the
    per-array path (kernel tests, the __graft_entry__ compile check)
    derive their staging from this table, so pad semantics cannot
    diverge.

    The host↔device link charges a ~fixed latency per transfer; shipping
    the 18 input leaves separately costs more than the bytes do (mirror of
    the fused RESULT buffer, _encode_decode_set). All 4-byte fields lead so
    every numpy .view() on the host stays aligned; bool fields trail as raw
    uint8. Returns (FieldSpec, ...) and total byte size.
    """
    fields = [
        # name, dtype, shape, Problem attr, pad fill
        ("req", np.float32, (G, R), "req", 0),
        ("count", np.int32, (G,), "count", 0),
        ("max_per_bin", np.int32, (G,), "max_per_bin", 0),
        ("spread_class", np.int32, (G,), "g_spread", -1),
        ("ds", np.float32, (NP, R), "ds_overhead", 0),
        ("cap", np.float32, (NP, R), "np_alloc_cap", np.inf),
        ("g_type", np.uint8, (G, T), "g_type", 0),
        ("g_zone", np.uint8, (G, Z), "g_zone", 0),
        ("g_cap", np.uint8, (G, C), "g_cap", 0),
        ("g_np", np.uint8, (G, NP), "g_np", 0),
        ("single_bin", np.uint8, (G,), "single_bin", 0),
        ("match", np.uint8, (G, A), "g_match", 0),
        ("owner", np.uint8, (G, A), "g_owner", 0),
        ("need", np.uint8, (G, A), "g_need", 0),
        ("strict_custom", np.uint8, (G,), "strict_custom", 0),
        ("np_type", np.uint8, (NP, T), "np_type", 0),
        ("np_zone", np.uint8, (NP, Z), "np_zone", 0),
        ("np_cap", np.uint8, (NP, C), "np_cap", 0),
    ]
    out, off = [], 0
    for name, dt, shape, src, fill in fields:
        out.append(FieldSpec(name, off, dt, shape, src, fill))
        off += int(np.prod(shape)) * np.dtype(dt).itemsize
    return tuple(out), off


_GROUP_FIELD_NAMES = frozenset(GroupBatch._fields)


def _unpack_inputs(buf: jnp.ndarray, G: int, T: int, Z: int, C: int,
                   NP: int, A: int, R: int) -> Tuple[GroupBatch, PoolParams]:
    """Slice the fused uint8 upload back into GroupBatch + PoolParams
    inside the trace (static offsets; XLA fuses the bitcasts away)."""
    layout, _total = group_layout(G, T, Z, C, NP, A, R)
    vals = {}
    for f in layout:
        n = int(np.prod(f.shape))
        if f.dtype is np.uint8:
            vals[f.name] = buf[f.offset: f.offset + n].reshape(f.shape).astype(bool)
        else:
            tgt = jnp.float32 if f.dtype is np.float32 else jnp.int32
            seg = jax.lax.bitcast_convert_type(
                buf[f.offset: f.offset + 4 * n].reshape(n, 4), tgt)
            vals[f.name] = seg.reshape(f.shape)
    groups = GroupBatch(**{k: v for k, v in vals.items()
                           if k in _GROUP_FIELD_NAMES})
    pools = PoolParams(**{k: v for k, v in vals.items()
                          if k not in _GROUP_FIELD_NAMES})
    return groups, pools


@partial(jax.jit, static_argnames=("G", "T", "Z", "C", "NP", "A", "lean"))
def pack_packed_fused(alloc: jnp.ndarray, avail: jnp.ndarray,
                      price: jnp.ndarray, buf: jnp.ndarray, init: BinState,
                      G: int, T: int, Z: int, C: int, NP: int, A: int,
                      lean: bool = False) -> jnp.ndarray:
    """pack_packed over a single fused input upload: ONE host→device
    transfer for all group/pool tensors + ONE device→host result buffer."""
    assert not lean or NP < 2 ** 15
    groups, pools = _unpack_inputs(buf, G, T, Z, C, NP, A, alloc.shape[1])
    return _encode_decode_set(pack(alloc, avail, price, groups, pools, init),
                              lean=lean)


def init_layout(B: int, R: int,
                A: int) -> Tuple[Tuple[FieldSpec, ...], int]:
    """Byte layout of the fused EXISTING-BIN upload. An existing bin's
    type/zone/captype masks are one-hot (the node IS one shape), so the
    host ships only per-bin indices + resource rows — ~50 KB for 500
    nodes instead of the ~800 KB of expanded [B,T] bool masks — and the
    kernel rebuilds the masks on device (solve.py _fused_init /
    _unpack_init). FieldSpec.src names the Problem attribute."""
    fields = [
        ("e_used", np.float32, (B, R), "e_used", 0),
        ("e_alloc", np.float32, (B, R), "e_alloc", np.inf),
        ("e_pm", np.int32, (B, A), "e_pm", 0),
        ("e_type", np.int32, (B,), "e_type", -1),
        ("e_zone", np.int32, (B,), "e_zone", -1),
        ("e_cap", np.int32, (B,), "e_cap", -1),
        ("e_np", np.int32, (B,), "e_np", -1),
        ("e_po", np.uint8, (B, A), "e_po", 0),
    ]
    out, off = [], 0
    for name, dt, shape, src, fill in fields:
        out.append(FieldSpec(name, off, dt, shape, src, fill))
        off += int(np.prod(shape)) * np.dtype(dt).itemsize
    return tuple(out), off


def _unpack_init(buf: Optional[jnp.ndarray], n_existing: jnp.ndarray,
                 B: int, T: int, Z: int, C: int, A: int, R: int) -> BinState:
    """Fused existing-bin upload → BinState (one-hot masks built on
    device). ``buf`` None = no existing capacity (empty table, no host
    bytes shipped at all)."""
    if buf is None:
        return empty_state(B, T, Z, C, R, A)
    layout, _total = init_layout(B, R, A)
    vals = {}
    for f in layout:
        n = int(np.prod(f.shape))
        if f.dtype is np.uint8:
            vals[f.name] = buf[f.offset: f.offset + n].reshape(f.shape)
        else:
            tgt = jnp.float32 if f.dtype is np.float32 else jnp.int32
            vals[f.name] = jax.lax.bitcast_convert_type(
                buf[f.offset: f.offset + 4 * n].reshape(n, 4), tgt
            ).reshape(f.shape)
    live = jnp.arange(B, dtype=jnp.int32) < n_existing
    onehot = lambda idx, n: idx[:, None] == jnp.arange(n, dtype=jnp.int32)[None, :]
    # rows >= n_existing are neutralized even when the buffer carries data
    # there: the sharded solve replicates ONE buffer across shards and only
    # shard 0 owns the existing bins (n_existing = 0 elsewhere) — a closed
    # row's cum is overwritten at bin open, but pm/po are accumulated into
    # and MUST start clean
    return BinState(
        cum=jnp.where(live[:, None], vals["e_used"], 0.0),
        tmask=onehot(vals["e_type"], T) & live[:, None],
        zmask=onehot(vals["e_zone"], Z) & live[:, None],
        cmask=onehot(vals["e_cap"], C) & live[:, None],
        np_id=jnp.where(live, vals["e_np"], -1),
        npods=jnp.zeros((B,), jnp.int32),
        open=live, fixed=live,
        alloc_cap=jnp.where(live[:, None], vals["e_alloc"], jnp.inf),
        pm=jnp.where(live[:, None], vals["e_pm"], 0),
        po=vals["e_po"].astype(bool) & live[:, None],
        next_open=jnp.asarray(n_existing, jnp.int32),
    )


@partial(jax.jit,
         static_argnames=("B", "G", "T", "Z", "C", "NP", "A", "lean"))
def pack_packed_efused(alloc: jnp.ndarray, avail: jnp.ndarray,
                       price: jnp.ndarray, gbuf: jnp.ndarray,
                       init_buf: Optional[jnp.ndarray],
                       n_existing: jnp.ndarray,
                       B: int, G: int, T: int, Z: int, C: int, NP: int,
                       A: int, lean: bool = False) -> jnp.ndarray:
    """Fully-fused pack: ONE upload for groups+pools, ONE (optional) for
    existing bins, ONE fused result transfer back."""
    assert not lean or NP < 2 ** 15
    R_ = alloc.shape[1]
    groups, pools = _unpack_inputs(gbuf, G, T, Z, C, NP, A, R_)
    init = _unpack_init(init_buf, n_existing, B, T, Z, C, A, R_)
    return _encode_decode_set(pack(alloc, avail, price, groups, pools, init),
                              lean=lean)


@partial(jax.jit,
         static_argnames=("split", "B", "G", "T", "Z", "C", "NP", "A",
                          "lean"))
def pack_packed_combined(alloc: jnp.ndarray, avail: jnp.ndarray,
                         price: jnp.ndarray, buf: jnp.ndarray, split: int,
                         n_existing: jnp.ndarray,
                         B: int, G: int, T: int, Z: int, C: int, NP: int,
                         A: int, lean: bool = False) -> jnp.ndarray:
    """One-round-trip pack WITH existing bins: groups+pools AND the
    existing-bin table ride ONE uint8 upload (``buf[:split]`` /
    ``buf[split:]``), against pack_packed_efused's two. On a tunneled TPU
    the second upload costs a full link leg — fusing it keeps the solve
    at exactly one host→device and one device→host transfer."""
    assert not lean or NP < 2 ** 15
    R_ = alloc.shape[1]
    groups, pools = _unpack_inputs(buf[:split], G, T, Z, C, NP, A, R_)
    init = _unpack_init(buf[split:], n_existing, B, T, Z, C, A, R_)
    return _encode_decode_set(pack(alloc, avail, price, groups, pools, init),
                              lean=lean)


def seed_layout(B: int, T: int, Z: int, C: int, R: int,
                A: int) -> Tuple[Tuple[FieldSpec, ...], int]:
    """Byte layout of the fused SEEDED-BinState upload (the sharded
    solve's tail-bin merge). Unlike the existing-bin table
    (init_layout), merge seed rows are mid-pack state rebuilt from
    shard results: full cum/alloc_cap rows, multi-hot masks, OPEN
    non-fixed bins, live pm/po accumulators, and an explicit next_open
    cursor — so they cannot ride the one-hot init staging. Staged
    per-array this was eleven device_puts per merge; fused it is one.
    FieldSpec.src is unused here (the host writes rows straight from
    decoded shard state, solver/solve.py _merge_solve)."""
    fields = [
        ("s_cum", np.float32, (B, R), "", 0),
        ("s_alloc", np.float32, (B, R), "", np.inf),
        ("s_pm", np.int32, (B, A), "", 0),
        ("s_np", np.int32, (B,), "", -1),
        ("s_npods", np.int32, (B,), "", 0),
        ("s_next", np.int32, (1,), "", 0),
        ("s_tmask", np.uint8, (B, T), "", 0),
        ("s_zmask", np.uint8, (B, Z), "", 0),
        ("s_cmask", np.uint8, (B, C), "", 0),
        ("s_open", np.uint8, (B,), "", 0),
        ("s_fixed", np.uint8, (B,), "", 0),
        ("s_po", np.uint8, (B, A), "", 0),
    ]
    out, off = [], 0
    for name, dt, shape, src, fill in fields:
        out.append(FieldSpec(name, off, dt, shape, src, fill))
        off += int(np.prod(shape)) * np.dtype(dt).itemsize
    return tuple(out), off


def _unpack_seed(buf: jnp.ndarray, B: int, T: int, Z: int, C: int,
                 A: int, R: int) -> BinState:
    """Fused seed upload → BinState, bit-exact with the per-array
    staging it replaces (bitcasts and bool casts only)."""
    layout, _total = seed_layout(B, T, Z, C, R, A)
    vals = {}
    for f in layout:
        n = int(np.prod(f.shape))
        if f.dtype is np.uint8:
            vals[f.name] = buf[f.offset: f.offset + n].reshape(f.shape).astype(bool)
        else:
            tgt = jnp.float32 if f.dtype is np.float32 else jnp.int32
            vals[f.name] = jax.lax.bitcast_convert_type(
                buf[f.offset: f.offset + 4 * n].reshape(n, 4), tgt
            ).reshape(f.shape)
    return BinState(
        cum=vals["s_cum"], tmask=vals["s_tmask"], zmask=vals["s_zmask"],
        cmask=vals["s_cmask"], np_id=vals["s_np"], npods=vals["s_npods"],
        open=vals["s_open"], fixed=vals["s_fixed"],
        alloc_cap=vals["s_alloc"], pm=vals["s_pm"], po=vals["s_po"],
        next_open=vals["s_next"].reshape(()),
    )


@partial(jax.jit,
         static_argnames=("split", "B", "G", "T", "Z", "C", "NP", "A",
                          "lean"))
def pack_packed_seeded(alloc: jnp.ndarray, avail: jnp.ndarray,
                       price: jnp.ndarray, buf: jnp.ndarray, split: int,
                       B: int, G: int, T: int, Z: int, C: int, NP: int,
                       A: int, lean: bool = False) -> jnp.ndarray:
    """One-round-trip pack over a SEEDED bin table: groups+pools AND the
    merge-seed BinState ride ONE uint8 upload (``buf[:split]`` /
    ``buf[split:]`` per seed_layout). The tail-bin merge refinement of
    every sharded solve goes through here — per-array BinState staging
    paid eleven link legs per merge; this pays exactly one upload and
    one result transfer, which is what lets the device-resident
    microloop bound a merge pass's legs."""
    assert not lean or NP < 2 ** 15
    R_ = alloc.shape[1]
    groups, pools = _unpack_inputs(buf[:split], G, T, Z, C, NP, A, R_)
    init = _unpack_seed(buf[split:], B, T, Z, C, A, R_)
    return _encode_decode_set(pack(alloc, avail, price, groups, pools, init),
                              lean=lean)


@partial(jax.jit,
         static_argnames=("B", "G", "T", "Z", "C", "NP", "A"))
def pack_probe_fused(alloc: jnp.ndarray, avail: jnp.ndarray,
                     price: jnp.ndarray, gbufs: jnp.ndarray,
                     init_bufs: Optional[jnp.ndarray],
                     n_existing: jnp.ndarray,
                     B: int, G: int, T: int, Z: int, C: int, NP: int,
                     A: int) -> jnp.ndarray:
    """K consolidation what-ifs in ONE device call over fused uploads.

    Each probe is a fully-built padded problem ("remove candidate set S:
    do its pods repack onto the remaining capacity + ≤1 cheaper node?",
    reference designs/consolidation.md:9-21). The disruption controller's
    prefix ladder and single-node scan become one vmapped kernel launch
    returning only tiny per-probe aggregates — the full NodePlan is
    decoded later by a single exact solve of the chosen probe (SURVEY.md
    §2.2 "embarrassingly batchable on device"). gbufs [K,·] and
    init_bufs [K,·] replace K×18 separately-staged arrays with two
    host→device transfers for the whole batch, and the result returns as
    ONE [K,6] f32 buffer — fetching the six ProbeSummary leaves
    separately cost six sequential round trips (~90 ms each on the
    tunneled link; measured 2.0-2.6 s → ~0.2 s for K=16 over 300
    existing bins end to end). Columns: leftover, n_new, new_cost,
    cap_c, flex, overflow (decoded by solve.py probe_batch; every count
    is far below f32's 2^24 exact-integer range)."""
    R_ = alloc.shape[1]

    def one(gbuf, init_buf, n_e) -> jnp.ndarray:
        groups, pools = _unpack_inputs(gbuf, G, T, Z, C, NP, A, R_)
        init = _unpack_init(init_buf, n_e, B, T, Z, C, A, R_)
        s = _probe_one(alloc, avail, price, groups, pools, init)
        # ProbeSummary._fields IS the column order; the host decodes with
        # ProbeSummary(*buf.T) so the contract lives in one place
        return jnp.stack([getattr(s, f).astype(jnp.float32)
                          for f in ProbeSummary._fields])

    if init_bufs is None:
        return jax.vmap(lambda g, n: one(g, None, n))(gbufs, n_existing)
    return jax.vmap(one)(gbufs, init_bufs, n_existing)


class ProbeSummary(NamedTuple):
    """Per-probe aggregates of a batched what-if pack (all [K])."""

    leftover: jnp.ndarray   # i32 pods that fit nowhere
    n_new: jnp.ndarray      # i32 new bins opened
    new_cost: jnp.ndarray   # f32 $/hr summed over new bins
    cap_c: jnp.ndarray      # i32 capacity-type index of the single new bin
                            # (valid when n_new == 1; -1 when none)
    flex: jnp.ndarray       # i32 feasible-type count of that bin (offering
                            # flexibility, the spot→spot ≥15-type guard input)
    overflow: jnp.ndarray   # bool bin table exhausted (host retries bigger B)


def _probe_one(alloc: jnp.ndarray, avail: jnp.ndarray, price: jnp.ndarray,
               g: GroupBatch, pl: PoolParams, st: BinState) -> ProbeSummary:
    """One what-if pack reduced to its per-probe aggregates."""
    avail_f = avail.astype(jnp.float32)
    res = pack(alloc, avail, price, g, pl, st)
    B = res.state.open.shape[0]
    live = res.state.open & ~res.state.fixed & (res.state.npods > 0)
    n_new = live.sum().astype(jnp.int32)
    cost = jnp.where(live, res.chosen_price, 0.0).sum()
    leftover = res.leftover.sum()
    b = jnp.argmax(live)
    reach = _offer_reachable(avail_f, res.state.zmask[b], res.state.cmask[b])
    flex = (res.state.tmask[b] & reach).sum().astype(jnp.int32)
    cap_c = jnp.where(n_new > 0, res.chosen_c[b], -1)
    overflow = (leftover > 0) & (res.state.next_open >= B)
    return ProbeSummary(leftover=leftover, n_new=n_new, new_cost=cost,
                        cap_c=cap_c, flex=jnp.where(n_new > 0, flex, 0),
                        overflow=overflow)
