"""Cheapest-offering finalization as a Pallas TPU kernel.

The pack finalization answers, per bin: over every (type, zone,
capacity-type) offering the bin's masks still allow, which is cheapest?
The straightforward XLA form materializes a ``[B, T, Z, C]`` masked price
tensor before the argmin — at the 8192-bin bucket over the full ~700-type
lattice that is a ~185 MB HBM intermediate whose bandwidth dwarfs the
actual reduction. This kernel streams it instead:

- grid over 128-bin blocks; each block holds its ``[128, Tp]`` type mask,
  its ``[128, 128]`` zone×capacity mask, and the shared ``[Tp, 128]``
  price panel in VMEM,
- a ``fori_loop`` over 128-type chunks builds only a ``[128, 128, 128]``
  (8 MB) masked window per step on the VPU, folding a running
  (min, argmin) carry — HBM traffic is exactly the inputs once,
- ties resolve to the lowest flat index, matching ``jnp.argmin``.

The price panel is pre-masked host-side: unavailable / non-offered /
padded lanes carry ``+inf``. Flat index layout: ``t * 128 + z * C + c``
(the zc axis is padded to the 128-lane tile).

``interpret=True`` runs the same kernel on CPU (tests); ``probe()``
compiles a tiny instance to decide availability on the current backend,
so the solver can fall back to the XLA form anywhere Pallas cannot lower
(see ops/binpack.py enable_pallas_argmin).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

_BB = 128     # bins per grid block
_TC = 128     # types per reduction chunk (lane-aligned: Mosaic
              # requires dynamic lane-dim offsets % 128 == 0)
_ZCP = 128    # zone×captype axis padded to one lane tile


def _kernel(tmask_ref, zcmask_ref, price_ref, best_v_ref, best_i_ref):
    import jax.lax as lax
    from jax.experimental import pallas as pl

    zc = zcmask_ref[:]         # [BB, ZCP] f32 (0/1)
    Tp = price_ref.shape[0]
    inf = jnp.float32(jnp.inf)

    def chunk(tc, carry):
        best_v, best_i = carry                         # [BB], [BB] f32/i32
        # slice the REFS per chunk (Mosaic lowers pl.ds ref reads; a
        # dynamic_slice on a loaded value does not lower)
        p = price_ref[pl.ds(tc * _TC, _TC), :]         # [TC, ZCP]
        m = tmask_ref[:, pl.ds(tc * _TC, _TC)]         # [BB, TC]
        cost = jnp.where((m[:, :, None] > 0) & (zc[:, None, :] > 0),
                         p[None, :, :], inf)           # [BB,TC,ZCP]
        flat = cost.reshape(_BB, _TC * _ZCP)
        v = jnp.min(flat, axis=1)                      # [BB]
        # explicit lowest-index tie-break: Mosaic's argmin lowering breaks
        # ties high, jnp.argmin breaks low — pick the first match by hand
        iota = lax.broadcasted_iota(jnp.int32, flat.shape, 1)
        i = jnp.min(jnp.where(flat == v[:, None], iota,
                              jnp.int32(2**31 - 1)), axis=1)
        gi = tc * _TC * _ZCP + i
        better = v < best_v                            # strict: first chunk
        return (jnp.where(better, v, best_v),          # wins ties, matching
                jnp.where(better, gi, best_i))         # jnp.argmin
    n_chunks = Tp // _TC
    v0 = jnp.full((_BB,), inf, jnp.float32)
    i0 = jnp.zeros((_BB,), jnp.int32)
    best_v, best_i = lax.fori_loop(0, n_chunks, chunk, (v0, i0))
    g = pl.program_id(0)
    best_v_ref[0, pl.ds(g * _BB, _BB)] = best_v
    best_i_ref[0, pl.ds(g * _BB, _BB)] = best_i


@partial(jax.jit, static_argnames=("interpret",))
def cheapest_offering_pallas(tmask: jnp.ndarray, zcmask: jnp.ndarray,
                             price: jnp.ndarray,
                             interpret: bool = False):
    """(best_price [B] f32, best_flat_idx [B] i32) per bin.

    tmask  [B, Tp] f32 0/1 (Tp a multiple of 128)
    zcmask [B, 128] f32 0/1 (zc = z*C + c in the first Z*C lanes)
    price  [Tp, 128] f32, +inf where unavailable/padded
    B must be a multiple of 128 (callers pad; see binpack.pack).
    """
    from jax.experimental import pallas as pl

    B, Tp = tmask.shape
    grid = (B // _BB,)
    v2, i2 = pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BB, Tp), lambda i: (i, 0)),
            pl.BlockSpec((_BB, _ZCP), lambda i: (i, 0)),
            pl.BlockSpec((Tp, _ZCP), lambda i: (0, 0)),
        ],
        # outputs are one full-width [1, B] block shared by every grid
        # step; each step writes its 128-lane slice (a flat [B] output's
        # XLA layout tiles at T(1024) for large B, which a 128 block
        # rejects, and a (1, 128) block violates the (8, 128) tile floor)
        out_specs=[
            pl.BlockSpec((1, B), lambda i: (0, 0)),
            pl.BlockSpec((1, B), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, B), jnp.float32),
            jax.ShapeDtypeStruct((1, B), jnp.int32),
        ],
        interpret=interpret,
    )(tmask, zcmask, price)
    return v2.reshape(B), i2.reshape(B)


def cheapest_offering_xla(tmask, zcmask, price):
    """Reference XLA form over the same padded layout (fallback + test
    oracle). Materializes the [B, Tp, 128] intermediate."""
    cost = jnp.where((tmask[:, :, None] > 0) & (zcmask[:, None, :] > 0),
                     price[None, :, :], jnp.inf)
    flat = cost.reshape(tmask.shape[0], -1)
    best = jnp.argmin(flat, axis=1).astype(jnp.int32)
    return jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0], best


_PROBED: dict = {}


def probe() -> bool:
    """Can Pallas lower on the current default backend? Cached per
    process. Never raises."""
    backend = jax.default_backend()
    if backend in _PROBED:
        return _PROBED[backend]
    try:
        tm = jnp.ones((_BB, _TC * 2), jnp.float32)
        zc = jnp.ones((_BB, _ZCP), jnp.float32)
        pr = jnp.ones((_TC * 2, _ZCP), jnp.float32)
        pr = pr.at[_TC + 1, 3].set(0.5)  # unique minimum in chunk 1
        v, i = cheapest_offering_pallas(tm, zc, pr)
        ok = (float(v[0]) == 0.5
              and int(i[0]) == (_TC + 1) * _ZCP + 3)
    except Exception:
        ok = False
    _PROBED[backend] = ok
    return ok
