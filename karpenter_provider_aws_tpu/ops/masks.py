"""Requirements → boolean masks over the lattice axes.

This is the row/column predicate encoding of the constraint matrix: the
reference evaluates `Requirements.Compatible` per pod per instance type in a
Go hot loop (reference pkg/cloudprovider/cloudprovider.go:246-251); here a
requirement set compiles once per *deduplicated pod group* into

- ``type_mask [T]``  over instance types (categorical vocab-id membership +
  numeric interval tests),
- ``zone_mask [Z]``  over availability zones,
- ``cap_mask  [C]``  over capacity types,

which the device kernel then combines with offering availability. Because
groups are deduplicated (50k pods collapse to a handful of distinct
requirement signatures), this compilation is host-side numpy — the O(pods x
types) work the reference burns per scheduling pass simply disappears.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Mapping, Optional, Sequence

import numpy as np

from ..apis import wellknown as wk
from ..apis.requirements import Constraint, Requirements, _num
from ..lattice.tensors import Lattice

# keys that live on dedicated axes rather than the type axis
# structural keys resolved off the type lattice: offering axes, bin/pool
# identity, and the pool-level OS (the AMI family's, not the type's)
_AXIS_KEYS = frozenset({wk.LABEL_ZONE, wk.LABEL_CAPACITY_TYPE,
                        wk.LABEL_NODEPOOL, wk.LABEL_HOSTNAME, wk.LABEL_OS})

_CAT_KEY_INDEX = {k: i for i, k in enumerate(wk.DEVICE_CATEGORICAL_KEYS)}
_NUM_KEY_INDEX = {k: i for i, k in enumerate(wk.DEVICE_NUMERIC_KEYS)}


@dataclass
class CompiledMasks:
    type_mask: np.ndarray  # [T] bool
    zone_mask: np.ndarray  # [Z] bool
    cap_mask: np.ndarray   # [C] bool

    def any_feasible(self, available: np.ndarray) -> bool:
        """Any offering (t,z,c) compatible and available?"""
        m = (self.type_mask[:, None, None] & self.zone_mask[None, :, None]
             & self.cap_mask[None, None, :] & available)
        return bool(m.any())


def _categorical_mask(lattice: Lattice, key: str, c: Constraint) -> np.ndarray:
    ids = lattice.cat_ids[_CAT_KEY_INDEX[key]]  # [T], 0 = undefined
    vocab = lattice.cat_vocab[key]
    allowed = np.zeros((len(vocab) + 1,), dtype=bool)
    allowed[0] = c.allows_absent
    for value, vid in vocab.items():
        allowed[vid] = c.matches(value)
    return allowed[ids]


def _numeric_mask(lattice: Lattice, key: str, c: Constraint) -> np.ndarray:
    vals = lattice.num_vals[_NUM_KEY_INDEX[key]]  # [T], NaN = undefined
    defined = ~np.isnan(vals)
    ok = defined.copy()
    if c.gt is not None:
        ok &= vals > c.gt
    if c.lt is not None:
        ok &= vals < c.lt
    if c.include is not None:
        inc = {f for f in (_num(v) for v in c.include) if f is not None}
        ok &= np.isin(vals, list(inc)) if inc else False
    if c.exclude:
        exc = {f for f in (_num(v) for v in c.exclude) if f is not None}
        if exc:
            ok &= ~np.isin(vals, list(exc))
    return np.where(defined, ok, c.allows_absent)


def compile_masks(reqs: Requirements, lattice: Lattice,
                  extra_labels: Optional[Mapping[str, str]] = None,
                  skip_unresolved_custom: bool = False) -> CompiledMasks:
    """Compile a requirement set against the lattice.

    ``extra_labels`` are labels the eventual node carries beyond its
    instance-type labels (NodePool template labels, e.g. custom team labels)
    — a constraint on such a key resolves to a scalar and either passes or
    zeroes the whole mask.

    ``skip_unresolved_custom`` leaves constraints on unknown custom keys to
    the caller (build_problem resolves them exactly per NodePool via
    ``_custom_keys_ok``) instead of zeroing the mask.
    """
    T, Z, C = lattice.T, lattice.Z, lattice.C
    type_mask = np.ones((T,), dtype=bool)
    zone_mask = np.ones((Z,), dtype=bool)
    cap_mask = np.ones((C,), dtype=bool)
    extra = dict(extra_labels or {})

    for key in reqs.keys():
        c = reqs.get(key)
        if key == wk.LABEL_ZONE:
            zone_mask &= np.array([c.matches(z) for z in lattice.zones], dtype=bool)
        elif key == wk.LABEL_CAPACITY_TYPE:
            cap_mask &= np.array([c.matches(ct) for ct in lattice.capacity_types], dtype=bool)
        elif key in (wk.LABEL_NODEPOOL, wk.LABEL_HOSTNAME):
            continue  # dedicated structural axes (bin identity / pool choice)
        elif key == wk.LABEL_OS:
            # the OS comes from the pool's AMI family, not the instance
            # type (any EC2 type runs either OS): enforced pool-vs-pod via
            # the requirements algebra in build_problem, with an implicit
            # linux default on pools that don't constrain it
            continue
        elif key == wk.LABEL_REGION:
            region = lattice.labels[0].get(wk.LABEL_REGION, "") if lattice.labels else ""
            if not c.matches(region):
                type_mask[:] = False
        elif key in _CAT_KEY_INDEX:
            # lattice-modeled keys: per-type truth always wins; a template
            # label must never shadow real hardware attributes
            type_mask &= _categorical_mask(lattice, key, c)
        elif key in _NUM_KEY_INDEX:
            type_mask &= _numeric_mask(lattice, key, c)
        elif key in extra:
            if not c.matches(extra[key]):
                type_mask[:] = False
        else:
            # custom key undefined on instance types and not provided by the
            # node template: satisfiable only if the constraint tolerates
            # absence (matches Requirements.intersects semantics)
            if not skip_unresolved_custom and not c.allows_absent:
                type_mask[:] = False
    return CompiledMasks(type_mask=type_mask, zone_mask=zone_mask, cap_mask=cap_mask)
