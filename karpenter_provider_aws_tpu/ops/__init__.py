from .masks import compile_masks, CompiledMasks

__all__ = ["compile_masks", "CompiledMasks"]
