"""Zero-dependency request-scoped tracing: spans, context, W3C wire format.

The control plane's per-stage timings (`NodePlan.stage_ms`, PR 3) are
disconnected aggregates — they say how long stages take on average, not
what happened to ONE pod batch at 3 a.m. This module is the causal layer
underneath: Dapper-style spans (Sigelman et al. 2010) with

- **contextvars propagation** — a span opened anywhere on a thread (or
  across an ``await``) parents every span opened inside it, with explicit
  ``capture()``/``parent=`` hand-off for thread pools and batching seams
  (the batcher's drain worker, the solve window),
- **W3C ``traceparent``** carriage (``00-<trace32>-<span16>-<flags>``) so
  context crosses BOTH process boundaries the control plane has: the
  REST apiserver (HTTP header) and the solver sidecar (a field in the
  Solve RPC's JSON body),
- **monotonic timing via utils/clock** — durations come from
  ``Clock.monotonic()`` (steppable under FakeClock), wall anchoring from
  one ``now()`` sample at tracer construction, so spans order correctly
  even when the wall clock jumps,
- a **disabled fast path**: when tracing is off, ``span()`` returns one
  shared no-op singleton — no Span objects, no id generation, no
  contextvar writes. The reconcile loop pays a single attribute read.

Completed spans land in the FlightRecorder (trace/recorder.py), which
applies tail-based retention and serves `/debug/traces` + Chrome
trace-event export (``kpctl trace``).
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from typing import Dict, Iterable, Optional, Sequence, Tuple

from ..utils.clock import Clock

# the active span on this thread/task (None = no ambient trace)
_CURRENT: contextvars.ContextVar[Optional["Span"]] = contextvars.ContextVar(
    "kpat_trace_span", default=None)

_FLAG_SAMPLED = 0x01


# ---- W3C traceparent (https://www.w3.org/TR/trace-context/) ---------------


def format_traceparent(trace_id: str, span_id: str,
                       sampled: bool = True) -> str:
    return f"00-{trace_id}-{span_id}-{_FLAG_SAMPLED if sampled else 0:02x}"


def parse_traceparent(header: Optional[str]
                      ) -> Optional[Tuple[str, str, bool]]:
    """``(trace_id, span_id, sampled)`` from a traceparent header, or None
    for anything malformed (a bad header must never fail a request)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().lower().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16 \
            or len(flags) != 2:
        return None
    try:
        int(version, 16)
        int(trace_id, 16)
        int(span_id, 16)
        fl = int(flags, 16)
    except ValueError:
        return None
    if version == "ff" or set(trace_id) == {"0"} or set(span_id) == {"0"}:
        return None
    return trace_id, span_id, bool(fl & _FLAG_SAMPLED)


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


# ---- spans ----------------------------------------------------------------


class Span:
    """One timed operation. Use as a context manager:

        with trace.span("solver.solve", pods=32) as sp:
            ...
            sp.set(degraded=True)

    ``start`` is wall-anchored epoch seconds (monotonic offsets from the
    tracer's anchor — see Tracer), ``duration`` is monotonic seconds.
    ``links`` name causally-related spans in OTHER traces (the batching
    seams: a coalesced drain links every producer it served).
    """

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start",
                 "duration", "attrs", "status", "links", "svc", "thread",
                 "_tracer", "_t0", "_token")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 span_id: str, parent_id: Optional[str],
                 links: Sequence[Tuple[str, str]] = (),
                 attrs: Optional[Dict] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.links = list(links)
        self.attrs = attrs or {}
        self.status = "ok"
        self.svc = tracer.service
        self.thread = threading.get_ident()
        self.start = 0.0
        self.duration = 0.0
        self._tracer = tracer
        self._t0 = 0.0
        self._token = None

    # -- context-manager protocol --

    def __enter__(self) -> "Span":
        tr = self._tracer
        self._t0 = tr.clock.monotonic()
        self.start = tr.anchor_wall + (self._t0 - tr.anchor_mono)
        self._token = _CURRENT.set(self)
        if tr.recorder is not None:
            tr.recorder.on_start(self.trace_id)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.duration = self._tracer.clock.monotonic() - self._t0
        if exc_type is not None:
            self.status = "error"
            self.attrs.setdefault("error", f"{exc_type.__name__}: {exc}")
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        if self._tracer.recorder is not None:
            self._tracer.recorder.on_end(self)
        return False

    # -- helpers --

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id)

    def to_dict(self) -> Dict:
        return {
            "name": self.name, "traceId": self.trace_id,
            "spanId": self.span_id, "parentId": self.parent_id,
            "svc": self.svc, "thread": self.thread,
            "start": round(self.start, 6),
            "durationMs": round(self.duration * 1000.0, 3),
            "status": self.status, "attrs": dict(self.attrs),
            "links": [list(l) for l in self.links],
        }


class _NoopSpan:
    """The disabled-path singleton: every operation is a no-op, every
    tracing call site stays branch-free. Identity-testable (tests assert
    the disabled path allocates nothing)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self

    def traceparent(self) -> None:
        return None


NOOP_SPAN = _NoopSpan()


# ---- tracer ---------------------------------------------------------------


class Tracer:
    """Owns the enabled flag, the wall/monotonic anchor, and the recorder.

    One process-global instance (``get_tracer()``); the sidecar service
    marks its spans with ``svc`` so a merged export shows which process
    ran what.
    """

    def __init__(self, clock: Optional[Clock] = None,
                 service: str = "operator"):
        self.clock = clock or Clock()
        self.service = service
        self.enabled = False
        self.recorder = None
        self.anchor_wall = self.clock.now()
        self.anchor_mono = self.clock.monotonic()

    def enable(self, recorder=None, clock: Optional[Clock] = None) -> None:
        if clock is not None:
            self.clock = clock
        if recorder is None and self.recorder is None:
            from .recorder import FlightRecorder
            recorder = FlightRecorder()
        if recorder is not None:
            self.recorder = recorder
        self.anchor_wall = self.clock.now()
        self.anchor_mono = self.clock.monotonic()
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def span(self, name: str, parent=_CURRENT, links: Iterable = (),
             **attrs):
        """Open a span. ``parent`` accepts a live Span, a traceparent
        header string (remote parent), a ``(trace_id, span_id)`` pair, or
        None to force a new root; omitted = the ambient current span.
        ``links`` is an iterable of the same forms."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is _CURRENT:
            parent = _CURRENT.get()
        trace_id = parent_id = None
        if isinstance(parent, Span):
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif isinstance(parent, str):
            parsed = parse_traceparent(parent)
            if parsed is not None:
                trace_id, parent_id = parsed[0], parsed[1]
        elif isinstance(parent, tuple) and len(parent) == 2:
            trace_id, parent_id = parent
        if trace_id is None:
            trace_id = _new_trace_id()
        link_ids = []
        for l in links:
            if isinstance(l, Span):
                link_ids.append((l.trace_id, l.span_id))
            elif isinstance(l, str):
                p = parse_traceparent(l)
                if p is not None:
                    link_ids.append((p[0], p[1]))
            elif isinstance(l, tuple) and len(l) == 2:
                link_ids.append(tuple(l))
        svc = attrs.pop("svc", None) if attrs else None
        sp = Span(self, name, trace_id, _new_span_id(), parent_id,
                  links=link_ids, attrs=attrs or None)
        if svc:
            # per-span service override: the sidecar handler marks its
            # subtree even when it shares the operator's process (the
            # in-process sidecar of cli --sidecar-address)
            sp.svc = svc
        return sp


_TRACER = Tracer()


def get_tracer() -> Tracer:
    return _TRACER


# ---- module-level convenience API (what call sites import) ---------------


def enabled() -> bool:
    return _TRACER.enabled


def enable(recorder=None, clock: Optional[Clock] = None) -> None:
    _TRACER.enable(recorder=recorder, clock=clock)


def disable() -> None:
    _TRACER.disable()


def span(name: str, parent=_CURRENT, links: Iterable = (), **attrs):
    return _TRACER.span(name, parent=parent, links=links, **attrs)


def current() -> Optional[Span]:
    """The ambient span, or None. Cheap when disabled."""
    if not _TRACER.enabled:
        return None
    return _CURRENT.get()


def capture() -> Optional[str]:
    """The ambient span's traceparent header (for hand-off across thread
    pools / wires), or None."""
    sp = current()
    return sp.traceparent() if sp is not None else None


def annotate(**attrs) -> None:
    """Attach attributes to the ambient span, if any."""
    sp = current()
    if sp is not None:
        sp.set(**attrs)


def recorder():
    return _TRACER.recorder
