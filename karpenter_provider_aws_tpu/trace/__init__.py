"""End-to-end tracing & flight recorder (docs/reference/tracing.md).

Causal spans from REST admission through informer delta, batch window,
solve-window coalescing, the pipelined device waves, decode, CreateFleet
and NodeClaim registration — with tail-sampled retention and Chrome
trace-event (Perfetto) export. Zero dependencies beyond the stdlib.

    from karpenter_provider_aws_tpu import trace

    trace.enable()                      # flight recorder attached
    with trace.span("my.op", key=1) as sp:
        ...
        sp.set(result="ok")

Disabled (the default), every call site costs one attribute read and
``span()`` returns a shared no-op singleton — no allocation.
"""

from .recorder import FlightRecorder, ImportedSpan
from .span import (NOOP_SPAN, Span, Tracer, annotate, capture, current,
                   disable, enable, enabled, format_traceparent, get_tracer,
                   parse_traceparent, recorder, span)

__all__ = [
    "FlightRecorder", "ImportedSpan", "NOOP_SPAN", "Span", "Tracer",
    "annotate", "capture", "current", "disable", "enable", "enabled",
    "format_traceparent", "get_tracer", "parse_traceparent", "recorder",
    "span",
]
