"""Flight recorder: bounded in-process trace retention with TAIL sampling.

Head-based samplers decide at trace start and therefore keep a uniform
slice of boring traffic while dropping the one 3 a.m. solve that
degraded. This recorder decides at trace END (Canopy, Kaldor et al.
2017): every completed trace enters a bounded ring, and traces that

- **errored** (any span finished with an exception),
- **degraded** (any span carries a truthy ``degraded`` attribute — the
  solver's ladder, host-FFD fallback, device retries), or
- **blew the latency budget** (end-to-end wall time over
  ``latency_budget_ms``)

are additionally pinned in a separate retained set that survives ring
wrap-around — the evidence stays until ``retained`` newer incidents push
it out. Everything is O(1) per span and bounded: the recorder can run
forever inside the operator.

Serving: ``debug_doc(path, query)`` renders the ``/debug/traces`` routes
(both the REST apiserver and the CLI's metrics server mount it), and
``to_chrome(trace_id)`` emits Chrome trace-event JSON loadable in
Perfetto / chrome://tracing next to xprof device traces (``kpctl trace
export``).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional


class _Rec:
    """One trace's accumulating state."""

    __slots__ = ("trace_id", "spans", "open", "retain_reason")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.spans: List = []
        self.open = 0
        self.retain_reason: Optional[str] = None


class ImportedSpan:
    """A span completed in ANOTHER process, rebuilt from its wire dict
    (Span.to_dict form — the sidecar ships these back in the Solve
    response). Quacks enough like trace/span.py Span for every recorder
    query and the Chrome export."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "svc",
                 "thread", "start", "duration", "attrs", "status", "links")

    def __init__(self, d: Dict):
        self.name = d.get("name", "")
        self.trace_id = d.get("traceId", "")
        self.span_id = d.get("spanId", "")
        self.parent_id = d.get("parentId")
        self.svc = d.get("svc", "remote")
        self.thread = d.get("thread", 0)
        self.start = float(d.get("start", 0.0))
        self.duration = float(d.get("durationMs", 0.0)) / 1000.0
        self.attrs = dict(d.get("attrs", {}))
        self.status = d.get("status", "ok")
        self.links = [tuple(l) for l in d.get("links", ())]

    def to_dict(self) -> Dict:
        return {
            "name": self.name, "traceId": self.trace_id,
            "spanId": self.span_id, "parentId": self.parent_id,
            "svc": self.svc, "thread": self.thread,
            "start": round(self.start, 6),
            "durationMs": round(self.duration * 1000.0, 3),
            "status": self.status, "attrs": dict(self.attrs),
            "links": [list(l) for l in self.links],
        }


class FlightRecorder:
    def __init__(self, ring: int = 256, retained: int = 64,
                 latency_budget_ms: float = 1000.0):
        # instrumented (introspect/contention.py): every span end takes
        # this lock; contention here means tracing itself is a bottleneck
        from ..introspect import contention
        self._lock = contention.lock("flight_recorder")
        self.ring_size = max(int(ring), 1)
        self.retained_size = max(int(retained), 1)
        self.latency_budget_ms = float(latency_budget_ms)
        # trace_id -> _Rec; insertion-ordered so eviction is oldest-first
        self._active: "OrderedDict[str, _Rec]" = OrderedDict()
        self._ring: "OrderedDict[str, _Rec]" = OrderedDict()
        self._retained: "OrderedDict[str, _Rec]" = OrderedDict()
        self.stats = {"started": 0, "completed": 0, "retained": 0,
                      "dropped": 0, "discarded": 0}

    def introspect_stats(self) -> Dict:
        """Introspection snapshot (``stats`` is already the raw counter
        dict attribute): counters + live ring/retained occupancy."""
        with self._lock:
            out: Dict = dict(self.stats)
            out.update({"active": len(self._active),
                        "ring": len(self._ring),
                        "retained_pinned": len(self._retained),
                        "latency_budget_ms": self.latency_budget_ms})
            return out

    # ---- span lifecycle (called by the tracer) ----------------------------

    def on_start(self, trace_id: str) -> None:
        with self._lock:
            rec = self._active.get(trace_id)
            if rec is None:
                # a finalized trace can re-open: a sidecar RPC (or a late
                # linked controller span) joins an already-completed trace
                rec = self._ring.pop(trace_id, None) \
                    or self._retained.pop(trace_id, None)
                if rec is None:
                    rec = _Rec(trace_id)
                    self.stats["started"] += 1
                self._active[trace_id] = rec
                # bound the active set: a span leaked open forever must
                # not grow memory without bound
                while len(self._active) > 4 * self.ring_size:
                    self._active.popitem(last=False)
                    self.stats["dropped"] += 1
            rec.open += 1

    def on_end(self, span) -> None:
        with self._lock:
            rec = self._active.get(span.trace_id)
            if rec is None:     # evicted while open; drop the orphan span
                self.stats["dropped"] += 1
                return
            rec.spans.append(span)
            rec.open -= 1
            if rec.open <= 0:
                del self._active[span.trace_id]
                self._finalize(rec)

    # ---- cross-process span import ----------------------------------------

    def ingest(self, span_dicts) -> int:
        """Import spans completed in another process (wire-dict form).

        Spans join their trace's accumulating record when it is still
        OPEN here (the normal case: SolverClient ingests inside the RPC
        call, under the caller's still-open span) so the tail decision at
        trace end sees the remote subtree too — a solve that degraded
        only in the sidecar still pins the whole trace. Already-finalized
        traces re-run the retention decision with the new spans. Dedupe
        is by span id: the in-process sidecar (cli --sidecar-address)
        shares this recorder, so its spans arrive twice."""
        added = 0
        by_tid: Dict[str, List[ImportedSpan]] = {}
        for d in span_dicts:
            sp = ImportedSpan(d)
            if sp.trace_id and sp.span_id:
                by_tid.setdefault(sp.trace_id, []).append(sp)
        with self._lock:
            for tid, spans in by_tid.items():
                rec = self._active.get(tid)
                refinalize = False
                if rec is None:
                    rec = self._ring.pop(tid, None) \
                        or self._retained.pop(tid, None)
                    refinalize = rec is not None
                if rec is None:
                    rec = _Rec(tid)
                    refinalize = True
                    self.stats["started"] += 1
                seen = {s.span_id for s in rec.spans}
                for sp in spans:
                    if sp.span_id in seen:
                        continue
                    rec.spans.append(sp)
                    seen.add(sp.span_id)
                    added += 1
                if refinalize:
                    if tid in self._retained:
                        del self._retained[tid]
                    if rec.retain_reason is not None:
                        self.stats["retained"] -= 1   # re-decided below
                    rec.retain_reason = None
                    self._finalize(rec, count=False)
        return added

    # ---- tail-sampling decision -------------------------------------------

    def _finalize(self, rec: _Rec, count: bool = True) -> None:
        if count:
            self.stats["completed"] += 1
        reason = self._retain_reason(rec)
        if reason == "discard":
            self.stats["discarded"] += 1
            return
        self._ring[rec.trace_id] = rec
        while len(self._ring) > self.ring_size:
            self._ring.popitem(last=False)
        if reason is not None:
            rec.retain_reason = reason
            self.stats["retained"] += 1
            self._retained[rec.trace_id] = rec
            while len(self._retained) > self.retained_size:
                self._retained.popitem(last=False)

    def _retain_reason(self, rec: _Rec) -> Optional[str]:
        """The tail-based policy, in precedence order. ``discard`` (a root
        span attribute) drops no-op traces entirely — e.g. a disruption
        reconcile that found nothing is not evidence of anything."""
        error = degraded = False
        for s in rec.spans:
            if s.status == "error":
                error = True
            if s.attrs.get("degraded"):
                degraded = True
        if error:
            return "error"
        if degraded:
            return "degraded"
        roots = [s for s in rec.spans if s.parent_id is None]
        if roots and all(s.attrs.get("discard") for s in roots):
            return "discard"
        if self._duration_ms(rec) > self.latency_budget_ms:
            return "slow"
        return None

    @staticmethod
    def _duration_ms(rec: _Rec) -> float:
        if not rec.spans:
            return 0.0
        t0 = min(s.start for s in rec.spans)
        t1 = max(s.start + s.duration for s in rec.spans)
        return (t1 - t0) * 1000.0

    # ---- queries ----------------------------------------------------------

    def _all(self) -> "OrderedDict[str, _Rec]":
        # retained traces may have fallen out of the ring: union, ring
        # order first (oldest → newest), then retained-only stragglers
        out: "OrderedDict[str, _Rec]" = OrderedDict()
        for tid, rec in self._retained.items():
            out[tid] = rec
        for tid, rec in self._ring.items():
            out[tid] = rec
        return out

    def summaries(self) -> List[Dict]:
        with self._lock:
            recs = list(self._all().values())
        out = []
        for rec in recs:
            roots = [s for s in rec.spans if s.parent_id is None]
            root = min(roots or rec.spans, key=lambda s: s.start)
            out.append({
                "traceId": rec.trace_id,
                "root": root.name,
                "svc": sorted({s.svc for s in rec.spans}),
                "spans": len(rec.spans),
                "start": round(min(s.start for s in rec.spans), 6),
                "durationMs": round(self._duration_ms(rec), 3),
                "retained": rec.retain_reason,
            })
        out.sort(key=lambda d: d["start"], reverse=True)
        return out

    def get(self, trace_id: str) -> Optional[List]:
        with self._lock:
            rec = (self._retained.get(trace_id) or self._ring.get(trace_id)
                   or self._active.get(trace_id))
            return list(rec.spans) if rec is not None else None

    # ---- Chrome trace-event export (Perfetto / chrome://tracing) ----------

    def to_chrome(self, trace_id: str) -> Optional[Dict]:
        """Chrome trace-event JSON: one complete ("X") event per span,
        process rows per service (operator / sidecar), thread rows per OS
        thread — loadable in Perfetto next to an xprof device trace."""
        spans = self.get(trace_id)
        if spans is None:
            return None
        pids: Dict[str, int] = {}
        events: List[Dict] = []
        for s in spans:
            pid = pids.setdefault(s.svc, len(pids) + 1)
            args = {"traceId": s.trace_id, "spanId": s.span_id,
                    "parentId": s.parent_id, "status": s.status}
            args.update({k: v for k, v in s.attrs.items()
                         if isinstance(v, (str, int, float, bool))})
            if s.links:
                args["links"] = [f"{t}:{sp}" for t, sp in s.links]
            events.append({
                "name": s.name, "ph": "X", "cat": "kpat",
                "ts": round(s.start * 1e6, 3),
                "dur": round(s.duration * 1e6, 3),
                "pid": pid, "tid": s.thread, "args": args,
            })
        for svc, pid in pids.items():
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": svc}})
        return {"displayTimeUnit": "ms", "traceEvents": events}

    # ---- HTTP surface (mounted by kube/httpserver.py and cli.py) ----------

    def debug_doc(self, path: str, query: Dict[str, List[str]]
                  ) -> Optional[Dict]:
        """Render a ``/debug/traces`` route; None = not found.

        GET /debug/traces                 → {"traces": [...], "stats": ...}
        GET /debug/traces/{id}            → {"traceId", "spans": [...]}
        GET /debug/traces/{id}?format=chrome → Chrome trace-event JSON
        """
        parts = [p for p in path.split("/") if p]
        if parts[:2] != ["debug", "traces"]:
            return None
        if len(parts) == 2:
            return {"traces": self.summaries(), "stats": dict(self.stats),
                    "latencyBudgetMs": self.latency_budget_ms,
                    "ring": self.ring_size, "retained": self.retained_size}
        if len(parts) == 3:
            tid = parts[2]
            if query.get("format", [""])[0] == "chrome":
                return self.to_chrome(tid)
            spans = self.get(tid)
            if spans is None:
                return None
            return {"traceId": tid,
                    "spans": [s.to_dict() for s in spans]}
        return None
