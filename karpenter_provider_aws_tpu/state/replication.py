"""Operator handoff: ClusterState snapshot + dirty-journal delta
streaming to a warm standby (docs/reference/handoff.md).

The reference ships HA as 2 replicas behind lease-based leader election,
where the loser idles COLD: a failover pays a full informer resync, a
cold scheduler, and (here) a compile storm. This module is the warm half
of the handoff story — the dirty journal IS a replication log, so the
same machinery that feeds the incremental problem builder
(`DirtyJournalCoalescer.take`) feeds a standby's mirror:

- :class:`ReplicationSource` (leader side) serializes the whole mirror
  into a VERSIONED snapshot anchored at ``state_rev``, then answers
  incremental delta polls with exactly what the journal localized since
  the standby's anchor — named pods by value (or a tombstone), table
  refreshes for the axes the journal only flags (bins → nodes+claims,
  volumes → PVCs+StorageClasses, daemonset churn → the ds-pod table).
  Leases and PDBs never journal (their appliers don't ``_note``), so
  they ride EVERY delta as small full tables — polling refresh is the
  only correct channel for them.
- :class:`StandbyReplica` (standby side) applies snapshots/deltas behind
  its own ``ClusterState`` through the same watch-stream appliers
  StateSync uses, and runs the cutover ladder: fresh anchor → delta
  catch-up; anchor outside the leader's journal window → ``full: true``
  comes back (``stale-anchor``) and the standby re-snapshots in the same
  poll — the delta solve path's always-correct fallback, verbatim; a
  snapshot version this standby does not speak → refuse and keep the
  held state (``snapshot-version-mismatch``) — a half-understood
  snapshot is worse than a stale one.

Transport is the solver sidecar's family (parallel/sidecar.py): unary
gRPC, raw-bytes JSON bodies (no protoc codegen), ``unix:`` sockets for
same-host pairs or ``host:port`` across DCN, every RPC deadline-bounded
so a hung leader can never wedge the standby's poll loop.

Methods:
- /karpenter.replication.v1.Replication/Snapshot — {} → versioned full doc
- /karpenter.replication.v1.Replication/Delta    — {since} → delta doc
- /karpenter.replication.v1.Replication/Health   — {} → {version, anchor}

Live nominations are deliberately EXCLUDED from the stream: they expire
on the leader's clock and self-clean on bind/delete; a promoted standby
simply re-nominates on its first pass.
"""

from __future__ import annotations

import json
from concurrent import futures
from typing import Callable, Dict, Optional

import grpc

from ..apis import serde
from ..solver.taxonomy import SNAPSHOT_VERSION_MISMATCH, STALE_ANCHOR, reason
from .cluster import _JOURNAL_MAX, ClusterState, DirtyJournalCoalescer

# bump when the snapshot/delta document shape changes incompatibly: a
# standby refuses (and counts) any document carrying a different version
SNAPSHOT_VERSION = 1

_SNAPSHOT = "/karpenter.replication.v1.Replication/Snapshot"
_DELTA = "/karpenter.replication.v1.Replication/Delta"
_HEALTH = "/karpenter.replication.v1.Replication/Health"

# deadlines: a delta is a short journal drain (bounded like the solve
# RPC's); a snapshot serializes the whole mirror, so it gets more rope;
# health answers from a counter read
DELTA_TIMEOUT_SECONDS = 2.0
SNAPSHOT_TIMEOUT_SECONDS = 10.0
HEALTH_TIMEOUT_SECONDS = 1.0


class ReplicationProtocolError(RuntimeError):
    """The leader ANSWERED, but not with a replication document (body
    failed to decode, or decoded to a non-object). Classifies like a
    transport failure at the poll site — counted, never raised out of
    the standby's sync loop."""


# ---- leader side ----------------------------------------------------------


class ReplicationSource:
    """Serves snapshot/delta documents over a ClusterState.

    Owns its own :class:`DirtyJournalCoalescer` anchored at the LAST
    REPLICATED revision (the provisioner's coalescer is anchored at the
    builder's — same journal, independent cursors). ``tick()`` may ride
    any leader-side poll loop to amortize the locked journal walk;
    ``delta_doc`` stays correct without it (``take`` falls back to a
    direct ``dirty_since``).
    """

    def __init__(self, cluster: ClusterState):
        from ..introspect import contention
        self._cluster = cluster
        self._coalescer = DirtyJournalCoalescer(cluster)
        # serializes delta drains: gRPC workers may overlap polls and the
        # coalescer is single-owner by contract
        self._lock = contention.lock("replication")
        self._last_rev = -1
        # observability (the handoff introspection provider folds these)
        self.snapshots = 0
        self.deltas = 0
        self.full_answers = 0

    def anchor(self) -> int:
        return self._cluster.state_rev

    def tick(self) -> None:
        """Drain the journal incrementally toward the next delta poll."""
        with self._lock:
            if self._last_rev >= 0:
                self._coalescer.tick(self._last_rev)

    def headroom_probe(self) -> Dict[str, float]:
        """Replication window (introspect/headroom.py): revisions the
        standby has not acknowledged yet. Exhausting the journal window
        forces a ``full: true`` delta → standby re-snapshot — counted by
        the pre-existing ``full_answers``."""
        with self._lock:
            last = self._last_rev
        window = (self._cluster.state_rev - last) if last >= 0 else 0
        return {"depth": float(max(window, 0)),
                "capacity": float(_JOURNAL_MAX),
                "drops": float(self.full_answers)}

    def snapshot_doc(self) -> Dict:
        """The whole mirror under ONE lock hold, anchored at the revision
        the cut was taken at — the delta stream continues exactly here."""
        c = self._cluster
        with c._lock:
            doc = {
                "version": SNAPSHOT_VERSION,
                "anchor": c.state_rev,
                "pods": [serde.pod_to_dict(p)
                         for _, p in sorted(c.pods.items())],
                "nodes": [serde.node_to_dict(n)
                          for _, n in sorted(c.nodes.items())],
                "claims": [serde.nodeclaim_to_dict(cl)
                           for _, cl in sorted(c.claims.items())],
                "pvcs": [serde.pvc_to_dict(v)
                         for _, v in sorted(c.pvcs.items())],
                "storageClasses": [serde.storage_class_to_dict(s)
                                   for _, s in sorted(c.storage_classes.items())],
                "leases": [serde.lease_to_dict(l)
                           for _, l in sorted(c.leases.items())],
                "pdbs": [serde.pdb_to_dict(p)
                         for _, p in sorted(c.pdbs.items())],
            }
        self.snapshots += 1
        self._last_rev = doc["anchor"]
        return doc

    def delta_doc(self, since: int) -> Dict:
        """What changed in (``since``, now], as applicable documents.
        ``full: true`` when the journal cannot answer (anchor outside the
        ring, or from another life of the mirror) — the standby's cue to
        re-snapshot."""
        with self._lock:
            ds = self._coalescer.take(int(since))
            self._last_rev = ds.rev
        doc: Dict = {"version": SNAPSHOT_VERSION, "since": ds.since,
                     "anchor": ds.rev, "ticks": ds.ticks}
        self.deltas += 1
        if ds.full:
            doc["full"] = True
            self.full_answers += 1
            return doc
        c = self._cluster
        with c._lock:
            pods = []
            for name in sorted(ds.pods):
                p = c.pods.get(name)
                pods.append({"name": name, "deleted": True} if p is None
                            else serde.pod_to_dict(p))
            doc["pods"] = pods
            if ds.daemonsets:
                doc["daemonsetPods"] = [
                    serde.pod_to_dict(p) for _, p in sorted(c.pods.items())
                    if p.is_daemonset]
            if ds.bins or ds.other:
                doc["nodes"] = [serde.node_to_dict(n)
                                for _, n in sorted(c.nodes.items())]
                doc["claims"] = [serde.nodeclaim_to_dict(cl)
                                 for _, cl in sorted(c.claims.items())]
            if ds.volumes or ds.other:
                doc["pvcs"] = [serde.pvc_to_dict(v)
                               for _, v in sorted(c.pvcs.items())]
                doc["storageClasses"] = [
                    serde.storage_class_to_dict(s)
                    for _, s in sorted(c.storage_classes.items())]
            # leases and PDBs never journal: small tables, every delta
            doc["leases"] = [serde.lease_to_dict(l)
                             for _, l in sorted(c.leases.items())]
            doc["pdbs"] = [serde.pdb_to_dict(p)
                           for _, p in sorted(c.pdbs.items())]
        return doc

    def stats(self) -> Dict[str, int]:
        return {"snapshots": self.snapshots, "deltas": self.deltas,
                "full_answers": self.full_answers,
                "anchor": self.anchor()}


class ReplicationService:
    """Raw-bytes request handling around a ReplicationSource (the
    sidecar's SolverService shape: payload bytes in, JSON bytes out)."""

    def __init__(self, source: ReplicationSource):
        self._source = source

    def snapshot(self, payload: bytes) -> bytes:
        return json.dumps(self._source.snapshot_doc()).encode()

    def delta(self, payload: bytes) -> bytes:
        req = json.loads(payload.decode()) if payload else {}
        return json.dumps(
            self._source.delta_doc(int(req.get("since", -1)))).encode()

    def health(self, payload: bytes) -> bytes:
        return json.dumps({"version": SNAPSHOT_VERSION,
                           "anchor": self._source.anchor()}).encode()


class _Handler(grpc.GenericRpcHandler):
    def __init__(self, service: ReplicationService):
        self._service = service

    def service(self, handler_call_details):
        m = handler_call_details.method
        if m == _SNAPSHOT:
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: self._service.snapshot(req))
        if m == _DELTA:
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: self._service.delta(req))
        if m == _HEALTH:
            return grpc.unary_unary_rpc_method_handler(
                lambda req, ctx: self._service.health(req))
        return None


def serve_replication(service: ReplicationService, address: str,
                      max_workers: int = 2):
    """Start a replication server on ``address`` (``unix:`` or
    ``host:port``); returns the started grpc.Server."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((_Handler(service),))
    # unix sockets return 1 on success; 0 means the bind failed
    if server.add_insecure_port(address) == 0:
        raise RuntimeError(
            f"replication server failed to bind {address!r}")
    server.start()
    return server


# ---- standby side ---------------------------------------------------------


class ReplicationClient:
    """Deadline-bounded unary JSON client (the SolverClient idiom)."""

    def __init__(self, address: str,
                 timeout: float = DELTA_TIMEOUT_SECONDS,
                 snapshot_timeout: float = SNAPSHOT_TIMEOUT_SECONDS,
                 health_timeout: float = HEALTH_TIMEOUT_SECONDS):
        self.address = address
        self.timeout = timeout
        self.snapshot_timeout = snapshot_timeout
        self.health_timeout = health_timeout
        # tight reconnect backoff: a restarted leader should be found in
        # ~250-500 ms, not gRPC's default exponential crawl
        self._channel = grpc.insecure_channel(address, options=[
            ("grpc.initial_reconnect_backoff_ms", 250),
            ("grpc.min_reconnect_backoff_ms", 250),
            ("grpc.max_reconnect_backoff_ms", 500),
        ])
        self._snapshot = self._channel.unary_unary(_SNAPSHOT)
        self._delta = self._channel.unary_unary(_DELTA)
        self._health = self._channel.unary_unary(_HEALTH)

    def _call(self, fn, req: Dict, timeout: float) -> Dict:
        resp = fn(json.dumps(req).encode(), timeout=timeout)
        try:
            doc = json.loads(resp.decode())
            if not isinstance(doc, dict):
                raise ValueError("non-object body")
        except (ValueError, UnicodeDecodeError) as e:
            raise ReplicationProtocolError(
                f"undecodable replication body from {self.address}: {e}")
        return doc

    def snapshot(self) -> Dict:
        return self._call(self._snapshot, {}, self.snapshot_timeout)

    def delta(self, since: int) -> Dict:
        return self._call(self._delta, {"since": int(since)}, self.timeout)

    def health(self) -> Dict:
        resp = self._health(b"{}", timeout=self.health_timeout,
                            wait_for_ready=True)
        try:
            doc = json.loads(resp.decode())
            if not isinstance(doc, dict):
                raise ValueError("non-object body")
        except (ValueError, UnicodeDecodeError) as e:
            raise ReplicationProtocolError(
                f"undecodable health body from {self.address}: {e}")
        return doc

    def close(self) -> None:
        self._channel.close()


class StandbyReplica:
    """Applies the replication stream behind the standby's own
    ClusterState and answers the bounded-staleness promotion gate.

    ``prebuild`` (optional zero-arg callable, typically the standby
    provisioner's ``warm_build``) runs after every successful sync so
    the resident device problem and the persistent compile cache stay
    warm — the first post-promotion pass starts from a delta, not a
    compile storm.
    """

    def __init__(self, cluster: ClusterState, client: ReplicationClient,
                 prebuild: Optional[Callable[[], object]] = None):
        self.cluster = cluster
        self.client = client
        self._prebuild = prebuild
        # the leader state_rev this mirror has applied through; -1 = no
        # snapshot held (a delta cannot be asked for)
        self.anchor = -1
        self.last_reason = ""
        self.last_error = ""
        self.snapshots = 0
        self.deltas = 0
        self.delta_pods = 0
        self.stale_anchor_rebuilds = 0
        self.version_mismatch_rebuilds = 0
        self.stale_promotions = 0
        self.promotions_blocked = 0
        self.poll_errors = 0
        self.prebuilds = 0
        self.prebuild_errors = 0

    # ---- appliers ---------------------------------------------------------

    def _apply_snapshot(self, doc: Dict) -> bool:
        if doc.get("version") != SNAPSHOT_VERSION:
            self.version_mismatch_rebuilds += 1
            self.last_reason = reason(
                SNAPSHOT_VERSION_MISMATCH,
                f"leader speaks v{doc.get('version')}, "
                f"standby v{SNAPSHOT_VERSION}")
            return False
        c = self.cluster
        c.reset()
        # StorageClasses before PVCs (add_pvc's Immediate-binding pin
        # consults them), nodes/claims before pods (bind side effects)
        for d in doc.get("storageClasses", ()):
            c.add_storage_class(serde.storage_class_from_dict(d))
        for d in doc.get("pvcs", ()):
            c.add_pvc(serde.pvc_from_dict(d))
        for d in doc.get("nodes", ()):
            c.add_node(serde.node_from_dict(d))
        for d in doc.get("claims", ()):
            c.add_claim(serde.nodeclaim_from_dict(d))
        for d in doc.get("pods", ()):
            c.add_pod(serde.pod_from_dict(d))
        for d in doc.get("leases", ()):
            c.add_lease(serde.lease_from_dict(d))
        for d in doc.get("pdbs", ()):
            c.add_pdb(serde.pdb_from_dict(d))
        self.anchor = int(doc["anchor"])
        self.snapshots += 1
        self.last_reason = ""
        return True

    def _reconcile(self, docs, from_dict, current, apply_one, delete_one):
        """Table refresh: apply every incoming object, delete mirror
        entries the table no longer carries."""
        names = set()
        for d in docs:
            obj = from_dict(d)
            names.add(obj.name)
            apply_one(obj)
        for gone in set(current()) - names:
            delete_one(gone)

    def _apply_delta(self, doc: Dict) -> bool:
        if doc.get("version") != SNAPSHOT_VERSION:
            self.version_mismatch_rebuilds += 1
            self.last_reason = reason(
                SNAPSHOT_VERSION_MISMATCH,
                f"leader speaks v{doc.get('version')}, "
                f"standby v{SNAPSHOT_VERSION}")
            return False
        if doc.get("full"):
            # the anchor fell out of the leader's journal window (or the
            # leader's mirror lived another life): re-snapshot — the
            # delta path's always-correct fallback
            self.stale_anchor_rebuilds += 1
            self.last_reason = reason(
                STALE_ANCHOR,
                f"anchor {self.anchor} outside the leader's journal window")
            self.anchor = -1
            return False
        c = self.cluster
        for d in doc.get("pods", ()):
            if d.get("deleted"):
                c.delete_pod(d["name"])
            else:
                c.apply_pod_spec(serde.pod_from_dict(d))
            self.delta_pods += 1
        if "daemonsetPods" in doc:
            names = set()
            for d in doc["daemonsetPods"]:
                p = serde.pod_from_dict(d)
                names.add(p.name)
                c.apply_pod_spec(p)
            for p in c.daemonset_pods():
                if p.name not in names:
                    c.delete_pod(p.name)
        if "nodes" in doc:
            self._reconcile(doc["nodes"], serde.node_from_dict,
                            lambda: list(c.nodes), c.apply_node,
                            c.delete_node)
        if "claims" in doc:
            self._reconcile(doc["claims"], serde.nodeclaim_from_dict,
                            lambda: list(c.claims), c.apply_claim,
                            c.delete_claim)
        if "storageClasses" in doc:
            self._reconcile(doc["storageClasses"],
                            serde.storage_class_from_dict,
                            lambda: list(c.storage_classes),
                            c.add_storage_class, c.delete_storage_class)
        if "pvcs" in doc:
            self._reconcile(doc["pvcs"], serde.pvc_from_dict,
                            lambda: list(c.pvcs), c.apply_pvc, c.delete_pvc)
        self._reconcile(doc.get("leases", ()), serde.lease_from_dict,
                        lambda: list(c.leases), c.add_lease, c.delete_lease)
        self._reconcile(doc.get("pdbs", ()), serde.pdb_from_dict,
                        lambda: list(c.pdbs), c.add_pdb, c.delete_pdb)
        self.anchor = int(doc["anchor"])
        self.deltas += 1
        self.last_reason = ""
        return True

    # ---- the poll loop ----------------------------------------------------

    def sync_once(self) -> bool:
        """One replication poll: snapshot when cold, delta otherwise; a
        stale-anchor answer re-snapshots IN THE SAME POLL. Never raises —
        transport failures count and the next poll retries."""
        try:
            if self.anchor < 0:
                ok = self._apply_snapshot(self.client.snapshot())
            else:
                ok = self._apply_delta(self.client.delta(self.anchor))
                if not ok and self.anchor < 0:
                    ok = self._apply_snapshot(self.client.snapshot())
        except Exception as e:  # noqa: BLE001 — the poll loop must survive
            self.poll_errors += 1
            self.last_error = f"{type(e).__name__}: {e}"
            return False
        if ok and self._prebuild is not None:
            try:
                self._prebuild()
                self.prebuilds += 1
            except Exception as e:  # noqa: BLE001 — warmth is best-effort
                self.prebuild_errors += 1
                self.last_error = f"prebuild: {type(e).__name__}: {e}"
        return ok

    def promotion_ready(self) -> bool:
        """The bounded-staleness promotion gate (wired as the elector's
        ``promotion_gate``): one last-chance sync against the (possibly
        dead) leader. Fresh sync → promote on caught-up state; leader
        unreachable but a snapshot held → promote STALE (the first pass
        full-rebuilds — always correct, just not warm); no snapshot ever
        applied → refuse, promoting an empty mirror would read every
        live node as an orphan."""
        if self.sync_once():
            return True
        if self.anchor >= 0:
            self.stale_promotions += 1
            return True
        self.promotions_blocked += 1
        self.last_reason = "no snapshot applied yet; refusing promotion"
        return False

    def stats(self) -> Dict[str, object]:
        return {
            "anchor": self.anchor,
            "snapshots": self.snapshots,
            "deltas": self.deltas,
            "delta_pods": self.delta_pods,
            "stale_anchor_rebuilds": self.stale_anchor_rebuilds,
            "version_mismatch_rebuilds": self.version_mismatch_rebuilds,
            "stale_promotions": self.stale_promotions,
            "promotions_blocked": self.promotions_blocked,
            "poll_errors": self.poll_errors,
            "prebuilds": self.prebuilds,
            "prebuild_errors": self.prebuild_errors,
            "last_reason": self.last_reason,
            "last_error": self.last_error,
        }
