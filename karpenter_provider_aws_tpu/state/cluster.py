"""In-memory cluster state.

Mirror of the core's cluster-state component (reference
cmd/controller/main.go:50 `state.NewCluster`; metrics
karpenter_cluster_state_* per website reference/metrics.md:150-157): a
thread-safe mirror of pods, nodes, and NodeClaims that is the solver's
input-tensor source — it renders registered nodes and in-flight claims
into ``ExistingBin`` rows and bound pods into ``BoundPod`` topology
accounting for build_problem.

Nominations track pods the provisioner has assigned to a not-yet-registered
NodeClaim so the next scheduling pass neither double-schedules the pods nor
double-counts the headroom (the core nominates pods to in-flight nodes the
same way).
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..apis import wellknown as wk
from ..apis.objects import Node, NodeClaim, NodeClaimPhase, Pod
from ..apis.resources import R, axis, canonical_to_vec, resources_to_vec
from ..lattice.tensors import Lattice
from ..solver.problem import ExistingBin, csi_claims_count
from ..solver.topology import BoundPod
from ..utils.clock import Clock

NOMINATION_TTL = 20.0  # core nominates pods to in-flight capacity ~20s

_VOL_AXIS = axis("attachable-volumes")


@dataclass
class _Nomination:
    target: str            # NodeClaim name (or node name)
    expires: float


# dirty-journal entry kinds (see ClusterState.dirty_since): "pod" names a
# pod whose pending-relevance may have changed; "bin" marks any mutation
# that can move existing-bin rows (node/claim add/delete/refresh, binds);
# "volume" and "other" poison the incremental path entirely — PVC zone
# pins and untracked mutations have non-local effects on the problem.
_JOURNAL_MAX = 65536


@dataclass
class DirtySet:
    """What changed between two cluster-state revisions (the provisioner
    feeds this to solver/incremental.py). ``full`` means the journal
    could not answer (overflowed past ``since``) and the caller must
    rebuild from scratch — the always-correct fallback."""

    since: int
    rev: int
    full: bool = False
    pods: Set[str] = field(default_factory=set)   # names to re-examine
    bins: bool = False         # existing-bin inputs changed
    # node/claim names the bin mutations localized to, when the journal
    # entry carried one; ``bins_unnamed=True`` means at least one bin
    # mutation could NOT be localized, so per-name consumers (the
    # consolidation engine's candidate-delta cache) must treat the whole
    # bin table as dirty — never a silently-partial answer
    bin_names: Set[str] = field(default_factory=set)
    bins_unnamed: bool = False
    volumes: bool = False      # PVC / StorageClass mutations
    daemonsets: bool = False   # daemonset pod set changed (ds_overhead)
    other: bool = False        # anything the journal cannot localize
    # journal drains merged into this set (DirtyJournalCoalescer): >1
    # means the controller fell behind and several batch-window ticks
    # were coalesced into one device-block delta
    ticks: int = 1

    def merge(self, newer: "DirtySet") -> None:
        """Fold a LATER drain into this one. Valid only when ``newer``
        continues exactly where this set ends (newer.since == rev) —
        the coalescer guarantees it, so the merged set covers
        (self.since, newer.rev] with no gap."""
        assert newer.since == self.rev, "non-contiguous journal drains"
        self.rev = newer.rev
        self.full = self.full or newer.full
        self.pods |= newer.pods
        self.bins = self.bins or newer.bins
        self.bin_names |= newer.bin_names
        self.bins_unnamed = self.bins_unnamed or newer.bins_unnamed
        self.volumes = self.volumes or newer.volumes
        self.daemonsets = self.daemonsets or newer.daemonsets
        self.other = self.other or newer.other
        self.ticks += newer.ticks


class DirtyJournalCoalescer:
    """Streams the dirty journal into a pending device-block delta
    BETWEEN provisioning passes (docs/reference/microloop.md).

    ``dirty_since`` walks the journal tail under the cluster mirror's
    lock — the hottest lock in the process. A controller that falls
    behind (long batch window, slow pass) otherwise pays one long
    locked walk at pass start, exactly when latency matters most. The
    coalescer drains in small increments on every batch-window poll
    (:meth:`tick`) and merges the drains, so the pass itself picks up
    an already-coalesced set covering every journal tick since the
    last build (:meth:`take`) — one short drain instead of the whole
    backlog. An anchor mismatch (builder rebuilt at a different
    revision, another life of the mirror) falls back to a direct
    ``dirty_since`` — never a silently-partial answer.
    """

    def __init__(self, cluster: "ClusterState"):
        self._cluster = cluster
        self._merged: Optional[DirtySet] = None
        # observability: provisioner stats surface these
        self.ticks = 0
        self.takes = 0
        self.fallbacks = 0

    def tick(self, since: int) -> None:
        """Drain journal entries newer than what is already pending
        (anchored at ``since``, the incremental builder's revision)."""
        self.ticks += 1
        m = self._merged
        if m is not None and m.since == since:
            if m.rev != self._cluster.state_rev:
                m.merge(self._cluster.dirty_since(m.rev))
            return
        self._merged = self._cluster.dirty_since(since)

    def take(self, since: int) -> DirtySet:
        """The coalesced set covering (``since``, now] — consumed. Falls
        back to a direct journal read when the pending set is anchored
        elsewhere (or nothing was ticked)."""
        self.takes += 1
        m, self._merged = self._merged, None
        if m is None or m.since != since:
            if m is not None:
                self.fallbacks += 1
            return self._cluster.dirty_since(since)
        if m.rev != self._cluster.state_rev:
            # mutations landed after the last tick: top the set up so
            # the pass never builds against a stale horizon
            m.merge(self._cluster.dirty_since(m.rev))
        return m

    def headroom_probe(self) -> Dict[str, float]:
        """Undrained journal backlog (introspect/headroom.py): revisions
        landed since the pending set's horizon. It exhausts at
        _JOURNAL_MAX — a backlog older than the ring retains forces the
        full-rebuild fallback, the latency cliff the forecast exists to
        see coming. ``fallbacks`` is the pre-existing miss counter."""
        m = self._merged
        backlog = self._cluster.state_rev - (m.rev if m is not None
                                             else self._cluster.state_rev)
        return {"depth": float(max(backlog, 0)),
                "capacity": float(_JOURNAL_MAX),
                "drops": float(self.fallbacks)}


class ClusterState:
    def __init__(self, clock: Optional[Clock] = None):
        self._clock = clock or Clock()
        # instrumented (introspect/contention.py): the mirror's lock is
        # the most-acquired lock in the process — wait/hold accounting
        # shows when API-mode churn turns it into a convoy
        from ..introspect import contention
        self._lock = contention.rlock("cluster_state")
        self.pods: Dict[str, Pod] = {}
        self.nodes: Dict[str, Node] = {}
        self.claims: Dict[str, NodeClaim] = {}
        self.pvcs: Dict[str, "PersistentVolumeClaim"] = {}
        self.leases: Dict[str, "Lease"] = {}   # kube-node-lease mirror
        self.storage_classes: Dict[str, "StorageClass"] = {}
        self.pdbs: Dict[str, "PodDisruptionBudget"] = {}
        self._nominations: Dict[str, _Nomination] = {}   # pod -> claim
        self._pod_added: Dict[str, float] = {}           # pod -> arrival ts
        self._startup_samples: List[float] = []          # unbilled durations
        # bumps on node/claim add/delete AND on in-place state flips that
        # change committed capacity (touch_capacity — e.g. a claim marked
        # TERMINATING leaves pool_usage immediately); gauge emitters
        # re-render on a rev change instead of rebuilding vectors per pass
        self.capacity_rev = 0
        # the per-pass dirty journal (docs/concepts/performance.md
        # "Steady-state reconciles"): every mutation that can change the
        # next provisioning pass's problem appends one (rev, kind, name)
        # entry, so the incremental problem builder re-examines only what
        # actually moved since the revision it last built at. Entries
        # carry CONSECUTIVE revisions; a reader asking further back than
        # the ring retains gets DirtySet(full=True) — the always-correct
        # rebuild path, never a silently-partial answer.
        self.state_rev = 0
        self._journal: Deque[Tuple[int, str, str]] = deque(maxlen=_JOURNAL_MAX)
        # leases GC'd by sweep_orphaned_leases (promotion wires it in)
        self.leases_swept = 0

    # ---- dirty journal ----------------------------------------------------

    def _note(self, kind: str, name: str = "") -> None:
        """Append one journal entry (caller holds the lock)."""
        self.state_rev += 1
        self._journal.append((self.state_rev, kind, name))

    def headroom_probe(self) -> Dict[str, float]:
        """The dirty-journal ring itself (introspect/headroom.py).
        ``kind="ring"``: sitting full is its retention policy, not data
        loss — readers that fall off the tail get the full-rebuild
        answer, which the coalescer probe's queue-kind row forecasts."""
        return {"depth": float(len(self._journal)),
                "capacity": float(_JOURNAL_MAX),
                "kind": "ring"}

    def dirty_since(self, since: int) -> DirtySet:
        """What changed in (``since``, ``state_rev``]. ``full=True`` when
        the journal cannot answer (ring overflowed past ``since``, or
        ``since`` is from another life of this mirror). Pods with LIVE
        nominations are always included: a nomination expiring between
        passes re-pends its pod with no mutation to journal."""
        with self._lock:
            rev = self.state_rev
            out = DirtySet(since=since, rev=rev)
            if since > rev or since < rev - len(self._journal):
                out.full = True
                return out
            for erev, kind, name in reversed(self._journal):
                if erev <= since:
                    break
                if kind == "pod":
                    out.pods.add(name)
                elif kind == "bin":
                    out.bins = True
                    if name:
                        out.bin_names.add(name)
                    else:
                        out.bins_unnamed = True
                elif kind == "volume":
                    out.volumes = True
                elif kind == "dspod":
                    out.daemonsets = True
                else:
                    out.other = True
            # nominations expire on the clock, silently re-pending their
            # pods — treat every nominated pod as touched (the set is
            # small and self-cleans on bind/delete), and their usage on
            # unregistered claims' bins as movable
            if self._nominations:
                out.pods.update(self._nominations.keys())
                out.bins = True
                out.bin_names.update(n.target
                                     for n in self._nominations.values())
            return out

    def touched_pods(self, names) -> Dict[str, Tuple[str, Optional[Pod]]]:
        """Classify journal-touched pods for the incremental problem
        builder: name -> (state, pod) with state one of "pending" (pod is
        schedulable input right now), "gone", "bound", "nominated",
        "deleting", "daemonset". One lock hold for the whole set."""
        now = self._clock.now()
        out: Dict[str, Tuple[str, Optional[Pod]]] = {}
        with self._lock:
            for n in names:
                pod = self.pods.get(n)
                if pod is None:
                    out[n] = ("gone", None)
                elif pod.is_daemonset:
                    out[n] = ("daemonset", pod)
                elif pod.node_name is not None:
                    out[n] = ("bound", pod)
                elif pod.deletion_timestamp:
                    out[n] = ("deleting", pod)
                else:
                    nom = self._nominations.get(n)
                    if nom is not None and nom.expires > now:
                        out[n] = ("nominated", pod)
                    else:
                        out[n] = ("pending", pod)
        return out

    # ---- pods ------------------------------------------------------------

    def add_pod(self, pod: Pod) -> None:
        with self._lock:
            self.pods[pod.name] = pod
            self._note("dspod" if pod.is_daemonset else "pod", pod.name)
            if pod.node_name is not None:
                # first seen ALREADY BOUND (sync relist, external
                # scheduler): its node's used vector just grew
                self._note("bin", pod.node_name)
            # arrival stamp for the pods_startup_time metric (reference
            # karpenter_pods_startup_time_seconds: created → scheduled).
            # Already-bound pods (operator resync) are NOT arrivals — a
            # later evict+rebind of one must not emit a bogus multi-hour
            # "startup" measured from sync time
            if pod.node_name is None:
                self._pod_added.setdefault(pod.name, self._clock.now())

    def delete_pod(self, name: str) -> None:
        with self._lock:
            pod = self.pods.pop(name, None)
            self._nominations.pop(name, None)
            self._pod_added.pop(name, None)
            self._note("dspod" if pod is not None and pod.is_daemonset
                       else "pod", name)
            if pod is not None and pod.node_name is not None:
                # a bound pod leaving frees its node's used vector
                self._note("bin", pod.node_name)

    def drain_startup_samples(self) -> List[float]:
        """Newly-observed pod startup latencies (arrival → first bind)
        since the last call; the metrics loop feeds them to the
        karpenter_pods_startup_time_seconds histogram."""
        with self._lock:
            out, self._startup_samples = self._startup_samples, []
            return out

    def bind_pod(self, pod_name: str, node_name: str) -> None:
        with self._lock:
            pod = self.pods.get(pod_name)
            if pod is not None:
                # a bind changes BOTH the pending set and the target
                # bin's used vector
                self._note("pod", pod_name)
                self._note("bin", node_name)
                if pod.node_name is None:
                    added = self._pod_added.pop(pod_name, None)
                    if added is not None:
                        # first bind since arrival: startup latency sample
                        # (re-binds after eviction are not pod startups)
                        self._startup_samples.append(
                            max(self._clock.now() - added, 0.0))
                pod.node_name = node_name
                # WaitForFirstConsumer: the CSI driver creates the PV in the
                # zone the pod lands in; later consumers of the claim are
                # pinned there (reference scheduling.md:389-398)
                if pod.volume_claims:
                    node = self.nodes.get(node_name)
                    zone = node.labels.get(wk.LABEL_ZONE) if node else None
                    if zone:
                        for c in pod.volume_claims:
                            pvc = self.pvcs.get(c)
                            if pvc is not None and pvc.bound_zone is None:
                                pvc.bound_zone = zone
            self._nominations.pop(pod_name, None)

    # ---- volumes ---------------------------------------------------------

    def bind_volumes(self, pod_name: str, zone: Optional[str]) -> None:
        """Bind the pod's unbound claims to ``zone``. Called as soon as the
        pod's target zone is knowable — at launch success for nominated
        pods, at bind for pods landing on registered nodes — so a claim
        shared across batches converges on one zone even while the first
        consumer's node is still registering."""
        if not zone:
            return
        with self._lock:
            pod = self.pods.get(pod_name)
            if pod is None:
                return
            if pod.volume_claims:
                self._note("volume")
            for c in pod.volume_claims:
                pvc = self.pvcs.get(c)
                if pvc is not None and pvc.bound_zone is None:
                    pvc.bound_zone = zone

    def add_storage_class(self, sc) -> None:
        with self._lock:
            self.storage_classes[sc.name] = sc
            self._note("volume")

    def add_pvc(self, pvc) -> None:
        with self._lock:
            if pvc.bound_zone is None:
                sc = self.storage_classes.get(pvc.storage_class)
                if sc is not None and sc.binding_mode == "Immediate" and sc.zones:
                    # Immediate binding provisions the PV before any pod
                    # exists: the claim pins a zone now and consumers follow
                    # it (the inverse of WaitForFirstConsumer)
                    pvc.bound_zone = sc.zones[0]
            self.pvcs[pvc.name] = pvc
            self._note("volume")

    def volume_state(self):
        """Locked snapshot of (pvcs, storage_classes) for one solve: the
        solver must not observe bind_pod mutating bound_zone mid-round."""
        import dataclasses
        with self._lock:
            return ({k: dataclasses.replace(v) for k, v in self.pvcs.items()},
                    dict(self.storage_classes))

    def unbind_pods_on(self, node_name: str) -> List[Pod]:
        """Eviction: pods on the node become pending again (termination drain)."""
        with self._lock:
            out = []
            for pod in self.pods.values():
                if pod.node_name == node_name:
                    pod.node_name = None
                    self._note("pod", pod.name)
                    out.append(pod)
            if out:
                self._note("bin", node_name)
            return out

    # ---- node leases (kube-node-lease mirror) -----------------------------

    def add_lease(self, lease) -> None:
        with self._lock:
            self.leases[lease.name] = lease

    def delete_lease(self, name: str) -> None:
        with self._lock:
            self.leases.pop(name, None)

    def orphaned_leases(self) -> List[str]:
        """Leases with no owner reference, or whose owner node is gone —
        the lease GC sweep's input (reference core GCs ownerless
        kube-node-lease Leases; integration/lease_garbagecollection_test)."""
        with self._lock:
            return [l.name for l in self.leases.values()
                    if l.owner_node is None or l.owner_node not in self.nodes]

    def sweep_orphaned_leases(self, delete) -> int:
        """GC every orphaned lease through ``delete(name)`` (the writer's
        delete_lease verb), counting the sweep in :meth:`stats`. A newly
        promoted leader runs this once: holders that died during the
        blackout window left leases the periodic GC would only catch on
        its long interval."""
        names = self.orphaned_leases()
        for name in names:
            delete(name)
        with self._lock:
            self.leases_swept += len(names)
        return len(names)

    # ---- PodDisruptionBudgets ---------------------------------------------

    def add_pdb(self, pdb) -> None:
        with self._lock:
            self.pdbs[pdb.name] = pdb

    def delete_pdb(self, name: str) -> None:
        with self._lock:
            self.pdbs.pop(name, None)

    def _pdb_allowance(self, pdb) -> int:
        """Voluntary evictions the budget currently permits (the
        disruptions-allowed math of policy/v1): healthy = bound matching
        pods; desired = all matching pods (our controller-replica
        analog). Caller holds the lock."""
        matching = [p for p in self.pods.values()
                    if not p.is_daemonset and pdb.matches(p)]
        healthy = sum(1 for p in matching
                      if p.node_name is not None and not p.deletion_timestamp)
        allowed = len(matching)
        if pdb.min_available is not None:
            allowed = min(allowed, healthy - int(pdb.min_available))
        if pdb.max_unavailable is not None:
            unavailable = len(matching) - healthy
            allowed = min(allowed,
                          int(pdb.max_unavailable) - unavailable)
        return max(allowed, 0)

    def zero_allowance_pdbs(self) -> List["PodDisruptionBudget"]:
        """The budgets that currently permit no eviction. Allowance is
        node-independent, so candidate scans compute this ONCE per pass
        (one O(pdbs × pods) sweep) and match per-node pods against only
        this set."""
        with self._lock:
            return [pdb for pdb in self.pdbs.values()
                    if self._pdb_allowance(pdb) <= 0]

    def pdb_blockers(self, pods: List[Pod],
                     zero_pdbs: Optional[List["PodDisruptionBudget"]] = None,
                     ) -> Dict[str, str]:
        """pod name → name of a matching PDB with zero allowance right now
        (the reference's `pdb ... prevents pod evictions` condition,
        disruption.md:112). Pass ``zero_pdbs`` (from zero_allowance_pdbs)
        when checking many nodes in one pass."""
        if zero_pdbs is None:
            zero_pdbs = self.zero_allowance_pdbs()
        blocked: Dict[str, str] = {}
        for pdb in zero_pdbs:
            for p in pods:
                if not p.is_daemonset and pdb.matches(p):
                    blocked.setdefault(p.name, pdb.name)
        return blocked

    def evict_node(self, node_name: str) -> List[Pod]:
        """Final node teardown: every remaining pod unbinds, DAEMONSET
        pods are deleted outright (their controller stamps a fresh one on
        the next node; an unbound daemonset pod would live forever as
        phantom overhead in every future node sizing), and the node object
        goes. Returns the evicted non-daemonset pods."""
        evicted = []
        for pod in self.unbind_pods_on(node_name):
            if pod.is_daemonset:
                self.delete_pod(pod.name)
            else:
                evicted.append(pod)
        self.delete_node(node_name)
        return evicted

    def drain_node(self, node_name: str) -> Tuple[List[Pod], List[Pod]]:
        """PDB-respecting eviction pass over a cordoned node (reference
        disruption.md:33: evict via the Eviction API, wait for the node to
        fully drain before terminating). Returns (evicted, still_blocked);
        daemonset pods are ignored — they leave with the node. Each
        eviction decrements its budgets' live allowance, so one pass
        evicts at most what every matching budget permits and the rest
        waits for rescheduled pods to report healthy again."""
        with self._lock:
            allowance = {name: self._pdb_allowance(pdb)
                         for name, pdb in self.pdbs.items()}
            evicted: List[Pod] = []
            blocked: List[Pod] = []
            for pod in self.pods.values():
                if pod.node_name != node_name or pod.is_daemonset:
                    continue
                holders = [n for n, pdb in self.pdbs.items()
                           if pdb.matches(pod)]
                if all(allowance[n] > 0 for n in holders):
                    for n in holders:
                        allowance[n] -= 1
                    pod.node_name = None
                    self._note("pod", pod.name)
                    self._note("bin", node_name)
                    evicted.append(pod)
                else:
                    blocked.append(pod)
            return evicted, blocked

    def nominate(self, pod_name: str, target: str, ttl: float = NOMINATION_TTL) -> None:
        with self._lock:
            self._nominations[pod_name] = _Nomination(target, self._clock.now() + ttl)
            # nominated pods charge their unregistered claim's bin
            # (existing_bins sums nominated usage)
            self._note("pod", pod_name)
            self._note("bin", target)

    def nominated_pods(self, target: str) -> List[Pod]:
        now = self._clock.now()
        with self._lock:
            return [self.pods[p] for p, n in self._nominations.items()
                    if n.target == target and n.expires > now and p in self.pods]

    def pending_pods(self) -> List[Pod]:
        """Unbound, un-nominated, non-daemonset pods awaiting capacity."""
        now = self._clock.now()
        with self._lock:
            out = []
            for pod in self.pods.values():
                if pod.node_name is not None or pod.is_daemonset or pod.deletion_timestamp:
                    continue
                nom = self._nominations.get(pod.name)
                if nom is not None and nom.expires > now:
                    continue
                out.append(pod)
            return out

    def daemonset_pods(self) -> List[Pod]:
        with self._lock:
            return [p for p in self.pods.values() if p.is_daemonset]

    def pod_phase_counts(self) -> Dict[str, int]:
        """Every pod classified into exactly ONE phase — the
        karpenter_pods_state{phase} gauge surface: bound (on a node),
        deleting (unbound with a deletion timestamp), nominated (awaiting
        a pending claim's registration), pending (awaiting capacity)."""
        now = self._clock.now()
        counts = {"bound": 0, "pending": 0, "nominated": 0, "deleting": 0}
        with self._lock:
            for pod in self.pods.values():
                if pod.node_name is not None:
                    counts["bound"] += 1
                elif pod.deletion_timestamp:
                    counts["deleting"] += 1
                else:
                    nom = self._nominations.get(pod.name)
                    if nom is not None and nom.expires > now:
                        counts["nominated"] += 1
                    else:
                        counts["pending"] += 1
        return counts

    def stats(self) -> Dict[str, int]:
        """Introspection snapshot of the mirror (one lock hold, counter
        reads + one pod scan for the phase split)."""
        phases = self.pod_phase_counts()
        with self._lock:
            claims_deleting = sum(1 for c in self.claims.values()
                                  if c.deletion_timestamp)
            return {
                "pods": len(self.pods),
                "pods_bound": phases["bound"],
                "pods_pending": phases["pending"],
                "pods_nominated": phases["nominated"],
                "pods_deleting": phases["deleting"],
                "nodes": len(self.nodes),
                "claims": len(self.claims),
                "claims_deleting": claims_deleting,
                "pvcs": len(self.pvcs),
                "leases": len(self.leases),
                "leases_swept": self.leases_swept,
                "pdbs": len(self.pdbs),
                "capacity_rev": self.capacity_rev,
            }

    # ---- nodes / claims ---------------------------------------------------

    def touch_capacity(self, name: str = "") -> None:
        """Record an in-place mutation that changes pool_usage() without
        an add/delete (a claim marked for deletion, a node cordon that
        excludes it from capacity). ``name`` localizes the mutation to a
        node/claim for the dirty journal; "" poisons per-name consumers."""
        with self._lock:
            self.capacity_rev += 1
            self._note("bin", name)

    def add_node(self, node: Node) -> None:
        with self._lock:
            self.nodes[node.name] = node
            self.capacity_rev += 1
            self._note("bin", node.name)

    def delete_node(self, name: str) -> None:
        with self._lock:
            self.nodes.pop(name, None)
            self.capacity_rev += 1
            self._note("bin", name)

    def add_claim(self, claim: NodeClaim) -> None:
        with self._lock:
            self.claims[claim.name] = claim
            self.capacity_rev += 1
            self._note("bin", claim.name)

    def delete_claim(self, name: str) -> None:
        with self._lock:
            self.claims.pop(name, None)
            self.capacity_rev += 1
            self._note("bin", name)
            stale = [p for p, n in self._nominations.items() if n.target == name]
            for p in stale:
                del self._nominations[p]
                self._note("pod", p)

    def node_for_claim(self, claim_name: str) -> Optional[Node]:
        with self._lock:
            for node in self.nodes.values():
                if node.node_claim == claim_name:
                    return node
            return None

    def snapshot_claims(self) -> List[NodeClaim]:
        """Locked list copy — Python-level iteration over the raw dict can
        raise mid-loop if a concurrent controller mutates it."""
        with self._lock:
            return list(self.claims.values())

    def snapshot_pods(self) -> List[Pod]:
        with self._lock:
            return list(self.pods.values())

    def snapshot_nodes(self) -> List[Node]:
        with self._lock:
            return list(self.nodes.values())

    def nodes_by_claim(self) -> Dict[str, Node]:
        """Snapshot index claim name -> node (one pass instead of an
        O(nodes) node_for_claim scan per claim)."""
        with self._lock:
            return {n.node_claim: n for n in self.nodes.values()
                    if n.node_claim}

    def pods_by_node(self, include_daemonsets: bool = True) -> Dict[str, List[Pod]]:
        """Locked snapshot of the node -> bound pods index."""
        with self._lock:
            by_node = self._pods_by_node()
            if include_daemonsets:
                return by_node
            return {n: [p for p in ps if not p.is_daemonset]
                    for n, ps in by_node.items()}

    # ---- solver inputs ----------------------------------------------------

    def _pods_by_node(self) -> Dict[str, List[Pod]]:
        by_node: Dict[str, List[Pod]] = {}
        for pod in self.pods.values():
            if pod.node_name is not None:
                by_node.setdefault(pod.node_name, []).append(pod)
        return by_node

    def existing_bins(self, lattice: Lattice) -> List[ExistingBin]:
        """Registered nodes + launched-but-unregistered claims as packer bins."""
        with self._lock:
            by_node = self._pods_by_node()
            bins: List[ExistingBin] = []
            for node in self.nodes.values():
                itype = node.labels.get(wk.LABEL_INSTANCE_TYPE)
                zone = node.labels.get(wk.LABEL_ZONE)
                cap = node.labels.get(wk.LABEL_CAPACITY_TYPE, "on-demand")
                if itype not in lattice.name_to_idx or zone not in lattice.zones:
                    continue
                # a cordoned (disruption-tainted) or terminating node is
                # not schedulable capacity: offering it would bounce
                # drained pods straight back to the node being emptied
                if any(t.key == wk.DISRUPTION_TAINT_KEY for t in node.taints):
                    continue
                claim = self.claims.get(node.node_claim) if node.node_claim else None
                if claim is not None and claim.deletion_timestamp:
                    continue
                used = np.zeros((R,), np.float32)
                vol_claims: set = set()
                for pod in by_node.get(node.name, ()):
                    used += resources_to_vec(pod.requests, implicit_pod=True)
                    vol_claims.update(pod.volume_claims)
                if vol_claims:
                    # resident CSI volumes hold attach slots against the
                    # node's limit (reference troubleshooting.md:277-288);
                    # the set dedups pods sharing one claim — a volume
                    # attaches to the node once
                    used[_VOL_AXIS] += csi_claims_count(
                        vol_claims, self.pvcs, self.storage_classes)
                alloc_override = None
                if node.allocatable:
                    # node status resources are canonical-unit floats; NaN
                    # marks unreported axes so the solver falls back to the
                    # lattice prediction there (e.g. attachable-volumes
                    # before the CSINode registers)
                    alloc_override = canonical_to_vec(node.allocatable,
                                                      missing=np.nan)
                bins.append(ExistingBin(
                    name=node.name, node_pool=node.node_pool or "",
                    instance_type=itype, zone=zone, capacity_type=cap,
                    used=used, alloc_override=alloc_override,
                    labels=dict(node.labels)))
            registered = {n.node_claim for n in self.nodes.values() if n.node_claim}
            for claim in self.claims.values():
                if claim.name in registered or claim.deletion_timestamp:
                    continue
                if claim.phase not in (NodeClaimPhase.LAUNCHED,):
                    continue
                if claim.instance_type not in lattice.name_to_idx:
                    continue
                used = np.zeros((R,), np.float32)
                vol_claims = set()
                for pod in self.nominated_pods(claim.name):
                    used += resources_to_vec(pod.requests, implicit_pod=True)
                    vol_claims.update(pod.volume_claims)
                if vol_claims:
                    # nominated volume pods hold attach slots on the
                    # in-flight claim too, or a second pass before the
                    # CSINode registers over-packs it
                    used[_VOL_AXIS] += csi_claims_count(
                        vol_claims, self.pvcs, self.storage_classes)
                bins.append(ExistingBin(
                    name=claim.name, node_pool=claim.node_pool,
                    instance_type=claim.instance_type,
                    zone=claim.zone or lattice.zones[0],
                    capacity_type=claim.capacity_type or "on-demand",
                    used=used, labels=dict(claim.labels),
                    # an in-flight claim's allocatable (e.g. a kubelet
                    # maxPods clamp) binds exactly like a registered
                    # node's — omitting it let consolidation what-ifs
                    # overpack unregistered claims and churn forever
                    alloc_override=(canonical_to_vec(claim.allocatable)
                                    if claim.allocatable else None)))
            return bins

    def bound_pods(self) -> List[BoundPod]:
        with self._lock:
            out: List[BoundPod] = []
            for pod in self.pods.values():
                if pod.node_name is None:
                    continue
                node = self.nodes.get(pod.node_name)
                zone = node.labels.get(wk.LABEL_ZONE, "") if node else ""
                cap = node.labels.get(wk.LABEL_CAPACITY_TYPE, "on-demand") if node else "on-demand"
                out.append(BoundPod(pod=pod, node_name=pod.node_name, zone=zone,
                                    capacity_type=cap,
                                    node_labels=dict(node.labels) if node else {}))
            return out

    def pool_usage(self) -> Dict[str, np.ndarray]:
        """Per-NodePool committed capacity (registered nodes + in-flight
        claims) for NodePool limits enforcement (nodepools.md limits)."""
        with self._lock:
            usage: Dict[str, np.ndarray] = {}
            counted = set()
            for node in self.nodes.values():
                pool = node.node_pool
                if not pool:
                    continue
                vec = canonical_to_vec(node.capacity) if node.capacity else np.zeros((R,), np.float32)
                usage[pool] = usage.get(pool, np.zeros((R,), np.float32)) + vec
                if node.node_claim:
                    counted.add(node.node_claim)
            for claim in self.claims.values():
                if claim.name in counted or claim.deletion_timestamp:
                    continue
                if claim.phase in (NodeClaimPhase.TERMINATING, NodeClaimPhase.TERMINATED):
                    continue
                vec = canonical_to_vec(claim.capacity) if claim.capacity else np.zeros((R,), np.float32)
                usage[claim.node_pool] = usage.get(claim.node_pool, np.zeros((R,), np.float32)) + vec
            return usage

    # ---- watch-stream appliers (operator/sync.py StateSync) ---------------
    # The mirror as informer cache: these locked appliers replace whole
    # objects from watch events while routing state TRANSITIONS through
    # the same side-effecting paths the direct stratum uses (bind_pod's
    # startup samples + WaitForFirstConsumer pins, capacity_rev bumps).

    def apply_pod_spec(self, pod: Pod) -> None:
        with self._lock:
            existing = self.pods.get(pod.name)
            if existing is None:
                self.add_pod(pod)
                return
            old_node, new_node = existing.node_name, pod.node_name
            if old_node is None and new_node is not None:
                # install unbound, then bind — side effects fire exactly
                # as in the direct stratum
                pod.node_name = None
                self.pods[pod.name] = pod
                self.bind_pod(pod.name, new_node)
            else:
                self.pods[pod.name] = pod
                self._note("dspod" if pod.is_daemonset else "pod", pod.name)
                if new_node is not None or old_node is not None:
                    # a refresh of a bound pod can change its requests —
                    # its node's used vector moves with it
                    self._note("bin", new_node or old_node or "")
                    if old_node and new_node and old_node != new_node:
                        self._note("bin", old_node)

    def apply_node(self, node: Node) -> None:
        with self._lock:
            if node.name in self.nodes:
                # in-place refresh (e.g. a cordon taint) can flip capacity
                # semantics without an add/delete
                self.nodes[node.name] = node
                self.capacity_rev += 1
                self._note("bin", node.name)
            else:
                self.add_node(node)

    def apply_claim(self, claim: NodeClaim) -> None:
        with self._lock:
            prev = self.claims.get(claim.name)
            if prev is None:
                self.add_claim(claim)
                return
            self.claims[claim.name] = claim
            self._note("bin", claim.name)
            if (bool(prev.deletion_timestamp) != bool(claim.deletion_timestamp)
                    or prev.phase != claim.phase):
                # deletion stamp / phase flips change pool_usage() without
                # an add/delete
                self.capacity_rev += 1

    def delete_pvc(self, name: str) -> None:
        with self._lock:
            self.pvcs.pop(name, None)
            self._note("volume")

    def delete_storage_class(self, name: str) -> None:
        with self._lock:
            self.storage_classes.pop(name, None)
            self._note("volume")

    def apply_pvc(self, pvc) -> None:
        with self._lock:
            existing = self.pvcs.get(pvc.name)
            if existing is not None and existing.bound_zone and not pvc.bound_zone:
                # the mirror may have fast-forwarded a WaitForFirstConsumer
                # pin before the server write landed — never regress it
                pvc.bound_zone = existing.bound_zone
            self.add_pvc(pvc)

    def reset(self) -> None:
        with self._lock:
            self.pods.clear()
            self.nodes.clear()
            self.claims.clear()
            self.pvcs.clear()
            self.leases.clear()
            self.storage_classes.clear()
            self.pdbs.clear()
            self._nominations.clear()
            self._pod_added.clear()
            self._startup_samples.clear()
            # a reset is another life of the mirror: drop the journal and
            # advance the revision so any held revision reads as stale
            self._journal.clear()
            self.state_rev += 1
