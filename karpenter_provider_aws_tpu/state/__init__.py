from .cluster import ClusterState

__all__ = ["ClusterState"]
