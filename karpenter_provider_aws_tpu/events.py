"""Event recorder.

Mirror of the reference's k8s event recorder usage (reference
pkg/controllers/interruption/events/events.go, pkg/cloudprovider/events):
controllers publish typed events about API objects; tests and the ops
surface read them back. Host-side, thread-safe, and BOUNDED: a ring
buffer keeps the newest MAX_EVENTS (a real apiserver ages events out the
same way; an append-only list would leak in a long-running controller
whose reconcile loops publish steadily).
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List, Optional

MAX_EVENTS = 10_000


@dataclass(frozen=True)
class Event:
    time: float
    type: str          # Normal | Warning
    reason: str
    object_kind: str   # Pod | NodeClaim | Node | NodePool | ...
    object_name: str
    message: str


class Recorder:
    def __init__(self, clock=None):
        from .utils.clock import Clock
        self._clock = clock or Clock()
        self._events: Deque[Event] = deque(maxlen=MAX_EVENTS)
        self._lock = threading.Lock()
        self.published = 0      # lifetime count (the ring forgets; this doesn't)
        self.warnings = 0
        # optional mirror (kube.eventsink.ApiEventSink in API mode):
        # called per event, under the lock, so the mirrored stream keeps
        # publish order. A sink failure must never break the publishing
        # controller — events are observability, not control flow.
        self.sink = None

    def publish(self, type: str, reason: str, object_kind: str, object_name: str,
                message: str) -> None:
        ev = Event(self._clock.now(), type, reason, object_kind, object_name, message)
        with self._lock:
            self._events.append(ev)
            self.published += 1
            if type == "Warning":
                self.warnings += 1
            if self.sink is not None:
                try:
                    self.sink(ev)
                except Exception:
                    pass

    def events(self, reason: Optional[str] = None,
               object_name: Optional[str] = None) -> List[Event]:
        with self._lock:
            out = list(self._events)
        if reason is not None:
            out = [e for e in out if e.reason == reason]
        if object_name is not None:
            out = [e for e in out if e.object_name == object_name]
        return out

    def stats(self) -> dict:
        """Introspection snapshot: ring occupancy + lifetime counters."""
        with self._lock:
            return {"ring": len(self._events), "published": self.published,
                    "warnings": self.warnings}

    def headroom_probe(self) -> dict:
        """Event-ring occupancy (introspect/headroom.py). ``kind="ring"``
        — aging the oldest events out is the retention policy a real
        apiserver applies too, not data loss; "drops" reports how many
        have aged out so the registry's counter parity holds."""
        with self._lock:
            return {"depth": float(len(self._events)),
                    "capacity": float(MAX_EVENTS),
                    "drops": float(max(self.published - len(self._events), 0)),
                    "kind": "ring"}

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
