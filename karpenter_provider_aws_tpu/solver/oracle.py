"""Host-side First-Fit-Decreasing oracle.

A faithful, per-pod sequential reimplementation of the reference's scheduling
algorithm (reference designs/bin-packing.md:16-43: sort pods by size
descending; first-fit into existing simulated nodes; else open a new node
from the highest-weight compatible NodePool; finally price each node at its
cheapest compatible offering), including the hostname-scoped topology rules
the kernel enforces (per-bin caps, affinity-class presence; zone/captype
scoped rules are already resolved into the Problem's group rows). Pure
Python/numpy, deliberately simple — the regression referee for the device
kernel's pack quality (the ≤2% cost envelope in BASELINE.md) and the
semantics oracle for parity tests.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from . import taxonomy
from .explain import unplaced_reason
from .problem import Problem


@dataclass
class OracleBin:
    np_idx: int
    cum: np.ndarray            # [R]
    tmask: np.ndarray          # [T] feasible types so far
    zmask: np.ndarray          # [Z]
    cmask: np.ndarray          # [C]
    pods: List[str] = field(default_factory=list)
    group_counts: Dict[int, int] = field(default_factory=dict)
    pm: np.ndarray = None      # [A] i32 count of pods matching each class
    po: np.ndarray = None      # [A] anti-term owners present
    existing_idx: Optional[int] = None   # fixed bin: index into problem.existing

    @property
    def is_existing(self) -> bool:
        return self.existing_idx is not None


@dataclass
class OraclePlan:
    bins: List[OracleBin]
    new_node_cost: float                       # $/hr of newly created nodes
    chosen: List[Tuple[int, int, int]]         # per new bin: (type, zone, cap) indices
    unschedulable: Dict[str, str]

    @property
    def num_new_nodes(self) -> int:
        return sum(1 for b in self.bins if not b.is_existing and b.pods)


def ffd_oracle(problem: Problem) -> OraclePlan:
    lat = problem.lattice
    alloc, avail, price = lat.alloc, lat.available, lat.price
    # per-pool allocatable ceiling (kubelet maxPods): a new bin of pool
    # pi fits against min(lattice alloc, pool cap) exactly like the kernel
    eff_alloc = np.minimum(alloc[None, :, :],
                           problem.np_alloc_cap[:, None, :])  # [NP,T,R]
    unschedulable = dict(problem.unschedulable)
    A = problem.A

    bins: List[OracleBin] = []
    for ei in range(problem.E):
        ti = int(problem.e_type[ei])
        tmask = np.zeros((lat.T,), dtype=bool)
        tmask[ti] = True
        zmask = np.zeros((lat.Z,), dtype=bool)
        zmask[int(problem.e_zone[ei])] = True
        cmask = np.zeros((lat.C,), dtype=bool)
        cmask[int(problem.e_cap[ei])] = True
        bins.append(OracleBin(np_idx=int(problem.e_np[ei]), cum=problem.e_used[ei].copy(),
                              tmask=tmask, zmask=zmask, cmask=cmask,
                              pm=problem.e_pm[ei].copy() if A else np.zeros((0,), np.int32),
                              po=problem.e_po[ei].copy() if A else np.zeros((0,), bool),
                              existing_idx=ei))

    def type_has_offering(tm: np.ndarray, zm: np.ndarray, cm: np.ndarray) -> np.ndarray:
        """[T] bool: type compatible AND has an available offering in zm x cm."""
        return tm & (avail & zm[None, :, None] & cm[None, None, :]).any(axis=(1, 2))

    single_bin_home: Dict[int, int] = {}  # group idx -> bin idx for single_bin groups

    # groups are already FFD-sorted; expand each group pod by pod
    for gi, group in enumerate(problem.groups):
        cap = int(problem.max_per_bin[gi])
        for pod_name in group.pod_names:
            req = group.req
            placed = False
            for bi, b in enumerate(bins):
                if group.single_bin and gi in single_bin_home and single_bin_home[gi] != bi:
                    continue
                if b.np_idx >= 0:
                    if not group.np_ok[b.np_idx]:
                        continue
                elif not b.is_existing:
                    continue
                elif group.strict_custom:
                    # unknown-pool node: cannot verify custom-label selectors
                    continue
                # per-bin cap: hostname spread tracks the whole class's
                # count (bound + sibling groups, same as the kernel's pm);
                # class-less caps (self-anti) count this row's placements
                if group.spread_class >= 0:
                    if b.pm[group.spread_class] >= cap:
                        continue
                elif b.group_counts.get(gi, 0) >= cap:
                    continue
                if A:
                    # k8s symmetry (same test as the kernel): bin holds no pod
                    # we anti-affine against, no pod anti-affining against us,
                    # and every class we need is present
                    if ((b.pm > 0) & group.owner).any() or (b.po & group.match).any():
                        continue
                    if not np.all((b.pm > 0) | ~group.need):
                        continue
                if b.is_existing:
                    # fixed node: capacity check against its own allocatable
                    new_cum = b.cum + req
                    ei = b.existing_idx
                    if (new_cum <= problem.e_alloc[ei] + 1e-3).all() and group.type_mask[int(problem.e_type[ei])] \
                            and group.zone_mask[int(problem.e_zone[ei])] and group.cap_mask[int(problem.e_cap[ei])]:
                        b.cum = new_cum
                        b.pods.append(pod_name)
                        b.group_counts[gi] = b.group_counts.get(gi, 0) + 1
                        if A:
                            b.pm += group.match.astype(np.int32)
                            b.po |= group.owner
                        if group.single_bin:
                            single_bin_home[gi] = bi
                        placed = True
                        break
                    continue
                tm = b.tmask & group.type_mask
                zm = b.zmask & group.zone_mask
                cm = b.cmask & group.cap_mask
                new_cum = b.cum + req
                fits = tm & (eff_alloc[b.np_idx] >= new_cum[None, :] - 1e-3).all(axis=1)
                fits = type_has_offering(fits, zm, cm)
                if fits.any():
                    b.cum, b.tmask, b.zmask, b.cmask = new_cum, fits, zm, cm
                    b.pods.append(pod_name)
                    b.group_counts[gi] = b.group_counts.get(gi, 0) + 1
                    if A:
                        b.pm += group.match.astype(np.int32)
                        b.po |= group.owner
                    if group.single_bin:
                        single_bin_home[gi] = bi
                    placed = True
                    break
            if placed:
                continue
            # distinct taxonomy codes per cause (solver/taxonomy.py):
            # the single generic string hid three different triages
            if group.single_bin and gi in single_bin_home:
                unschedulable[pod_name] = taxonomy.reason(
                    taxonomy.SINGLE_BIN_FULL,
                    "hostname self-affinity pins the group to one node "
                    "and it cannot hold more pods")
                continue
            # a fresh bin satisfies presence needs only by self-seeding
            if A and not np.all(group.match | ~group.need):
                unschedulable[pod_name] = taxonomy.reason(
                    taxonomy.AFFINITY_PRESENCE,
                    "required affinity class present on no node and the "
                    "group cannot self-seed it")
                continue
            # open a new node: highest-weight compatible pool with a feasible type
            for pi in np.nonzero(group.np_ok)[0]:
                pi = int(pi)
                cum = problem.ds_overhead[pi] + req
                tm = group.type_mask & problem.np_type[pi]
                zm = group.zone_mask & problem.np_zone[pi]
                cm = group.cap_mask & problem.np_cap[pi]
                fits = tm & (eff_alloc[pi] >= cum[None, :] - 1e-3).all(axis=1)
                fits = type_has_offering(fits, zm, cm)
                if fits.any():
                    nb = OracleBin(np_idx=pi, cum=cum, tmask=fits, zmask=zm, cmask=cm,
                                   pods=[pod_name], group_counts={gi: 1},
                                   pm=group.match.astype(np.int32) if A else np.zeros((0,), np.int32),
                                   po=group.owner.copy() if A else np.zeros((0,), bool))
                    bins.append(nb)
                    if group.single_bin:
                        single_bin_home[gi] = len(bins) - 1
                    placed = True
                    break
            if not placed:
                # no-existing-fit: no compatible pool can open a node at
                # all, so only existing capacity could have hosted it;
                # no-new-node-shape: pools exist but no empty node of any
                # feasible type holds the pod. The group's ledger refines
                # further (an ICE-zeroed group reads ice-hold).
                unschedulable[pod_name] = unplaced_reason(
                    group,
                    fallback=(taxonomy.NO_EXISTING_FIT
                              if not group.np_ok.any()
                              else taxonomy.NO_NEW_NODE_SHAPE))

    # finalize: cheapest available offering per new bin
    cost = 0.0
    chosen: List[Tuple[int, int, int]] = []
    for b in bins:
        if b.is_existing or not b.pods:
            continue
        p = np.where(avail & b.tmask[:, None, None] & b.zmask[None, :, None] & b.cmask[None, None, :],
                     price, np.inf)
        t, z, c = np.unravel_index(int(np.argmin(p)), p.shape)
        assert np.isfinite(p[t, z, c]), "oracle invariant: open bin must have an offering"
        chosen.append((int(t), int(z), int(c)))
        cost += float(p[t, z, c])
    return OraclePlan(bins=bins, new_node_cost=cost, chosen=chosen, unschedulable=unschedulable)
