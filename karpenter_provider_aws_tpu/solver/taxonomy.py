"""The structured unschedulable-reason taxonomy.

The reference answers "why is this pod pending" with `FailedScheduling`
events and nodeclaim status conditions; until this module the repo
answered it with free-text strings — `solver/oracle.py` emitted ONE
generic "does not fit any existing node or new-node shape" for three
distinct causes, and nothing machine-readable survived to the metric or
event surface. Every unschedulable reason is now a bounded enum CODE
plus a human detail, carried as ``"<code>: <detail>"`` on
``NodePlan.unschedulable`` (and therefore across the sidecar wire's
``unschedulable`` map unchanged), on `FailedScheduling` events, and as
the ``code`` label of ``karpenter_pods_unschedulable_reasons_total``.

Codes are DECLARED here and nowhere else: the graftlint ``reason-code``
rule (tools/lint/rules.py ReasonRule) fails any ``reason(...)`` call or
``code=`` label literal not in :data:`CODES` — the same
declaration-lockstep discipline the metrics rule enforces for series
names. Add a code by adding a constant; the lint, the docs table
(docs/reference/explain.md), and every consumer stay in step.
"""

from __future__ import annotations

# ---- the bounded code set -------------------------------------------------

# pre-solve: the pod's requests name a resource axis the lattice does
# not model; no amount of capacity helps
UNKNOWN_RESOURCE = "unknown-resource"
# problem build: no (nodepool, instance-type, zone, capacity-type)
# offering is compatible with the pod's requirements at all
NO_OFFERING = "no-offering"
# problem build: every compatible offering exists in the catalog but is
# currently held out of the market (ICE / unavailable mask) — weather-
# caused pending, distinct from genuine infeasibility
ICE_HOLD = "ice-hold"
# problem build: zone anti-affinity demands more zones than are eligible
ZONE_ANTI_AFFINITY = "zone-anti-affinity"
# pack: the pod fits neither existing capacity nor any new-node shape
# (the device decode's generic leftover; the host-FFD rung refines it)
NO_FIT = "no-fit"
# host FFD: only existing capacity could host this pod (no compatible
# pool can open a node for it) and none of it fits
NO_EXISTING_FIT = "no-existing-fit"
# host FFD: compatible pools exist but no empty node of any feasible
# type can hold the pod (+ daemonset overhead)
NO_NEW_NODE_SHAPE = "no-new-node-shape"
# host FFD: hostname self-affinity pinned the group to one bin and that
# bin is full
SINGLE_BIN_FULL = "single-bin-full"
# host FFD: a hostname-affinity presence requirement no bin satisfies
# and the group cannot self-seed
AFFINITY_PRESENCE = "affinity-presence"
# provisioning: the plan's node was dropped by NodePool spec.limits and
# no fallback pool could take the pods
POOL_LIMITS = "pool-limits"
# provisioning: the solve itself failed; the whole batch stays pending
# for the next pass (partial-result guard)
SOLVE_ERROR = "solve-error"
# control-plane degradation provenance (parallel/pool.py SolverPool;
# docs/reference/solver-pool.md): these ride NodePlan.degraded_reason
# (and the karpenter_solver_degraded_total reason label), never a pod's
# unschedulable reason — the pool's job is that pods still place.
# sidecar RPC missed its solve deadline: the endpoint accepted the
# connection and stalled (a hung process, the failure mode a flat
# connect error never surfaces); its breaker opens immediately
SIDECAR_HUNG = "sidecar-hung"
# sidecar RPC failed any other way: connection refused/reset, or the
# endpoint answered with something that is not a NodePlan (junk body,
# connection died mid-response)
SIDECAR_UNREACHABLE = "sidecar-unreachable"
# every pool endpoint's breaker is open: the pass ran on the LOCAL
# solver — the final ladder rung below the whole sidecar fleet
POOL_EXHAUSTED = "pool-exhausted"
# operator-handoff provenance (state/replication.py +
# operator/leaderelection.py; docs/reference/handoff.md): these ride the
# standby's cutover ladder and the writer's fencing gate, never a pod's
# unschedulable reason.
# the standby's journal anchor fell out of the leader's dirty-journal
# window (or is from another life of the mirror): the delta stream
# cannot answer and the standby re-snapshots / the promoted operator
# full-rebuilds — the same always-correct fallback the delta solve path
# takes on a coalescer miss
STALE_ANCHOR = "stale-anchor"
# the leader streams a snapshot format this standby does not speak:
# refuse to apply it (a half-understood snapshot is worse than a cold
# start) and keep rebuilding from scratch
SNAPSHOT_VERSION_MISMATCH = "snapshot-version-mismatch"
# a side-effectful write was attempted under a fencing token the lease
# store no longer carries: a demoted (zombie) leader's in-flight
# eviction/claim write, rejected instead of raced
FENCED_WRITE_REJECTED = "fenced-write-rejected"
# consolidation provenance (solver/consolidate.py ConsolidationEngine;
# docs/reference/consolidation.md): these answer "why was this node NOT
# consolidated" — they ride the per-node explain ledger
# (`kpctl explain node`) and the karpenter_disruption_consolidation_
# skips_total code label, never a pod's unschedulable reason.
# a PodDisruptionBudget leaves zero eviction headroom for a pod on the
# node: the node cannot drain (reference Unconsolidatable event)
NOT_CONSOLIDATABLE_PDB = "not-consolidatable-pdb"
# the NodePool's disruption budget window currently allows zero (or too
# few) voluntary disruptions: the decision is deferred, not rejected
NOT_CONSOLIDATABLE_BUDGET = "not-consolidatable-budget"
# the what-if repack found no plan that saves money — or the device
# plan lost to the host FFD referee's costing of the same what-if by
# more than the ≤2% envelope (the savings referee rule)
CONSOLIDATION_NO_SAVINGS = "consolidation-no-savings"
# the weather advisory holds voluntary consolidation: an active storm
# or spot-crash regime window — consolidating INTO distressed capacity
# trades a standing node for one about to be reclaimed or repriced
CONSOLIDATION_WEATHER_HOLD = "consolidation-weather-hold"
# spot-to-spot replacement consolidation gated off: the feature flag is
# disabled, or the replacement lacks the minimum instance-type
# flexibility the reference demands (SpotToSpotConsolidation)
CONSOLIDATION_SPOT_GUARD = "consolidation-spot-guard"

CODES = frozenset({
    UNKNOWN_RESOURCE, NO_OFFERING, ICE_HOLD, ZONE_ANTI_AFFINITY,
    NO_FIT, NO_EXISTING_FIT, NO_NEW_NODE_SHAPE, SINGLE_BIN_FULL,
    AFFINITY_PRESENCE, POOL_LIMITS, SOLVE_ERROR,
    SIDECAR_HUNG, SIDECAR_UNREACHABLE, POOL_EXHAUSTED,
    STALE_ANCHOR, SNAPSHOT_VERSION_MISMATCH, FENCED_WRITE_REJECTED,
    NOT_CONSOLIDATABLE_PDB, NOT_CONSOLIDATABLE_BUDGET,
    CONSOLIDATION_NO_SAVINGS, CONSOLIDATION_WEATHER_HOLD,
    CONSOLIDATION_SPOT_GUARD,
})

# the parse-failure sentinel for strings minted before the taxonomy (or
# by an older sidecar across the wire) — NOT a member of CODES, so the
# lint can never accept it as a declared literal
UNCODED = "uncoded"


def reason(code: str, detail: str = "") -> str:
    """Render a coded unschedulable reason: ``"<code>: <detail>"`` (or
    the bare code with no detail). The inverse of :func:`code_of`."""
    assert code in CODES, f"undeclared reason code {code!r}"
    return f"{code}: {detail}" if detail else code


def code_of(reason_str: str) -> str:
    """The taxonomy code of a reason string; :data:`UNCODED` for
    free-text strings minted before the taxonomy (an old sidecar across
    the wire must not crash the metric/event path)."""
    head = reason_str.split(":", 1)[0].strip()
    return head if head in CODES else UNCODED


def detail_of(reason_str: str) -> str:
    """The human detail of a coded reason ("" when none)."""
    if code_of(reason_str) == UNCODED:
        return reason_str
    parts = reason_str.split(":", 1)
    return parts[1].strip() if len(parts) > 1 else ""
