"""Deterministic fault injection for the solver's device path.

The cloud backend already has one-shot error injection (cloud/fake.py
``inject_error``, the reference's AtomicError); this is the same idea
for the SOLVE path, so tests and soaks can force every rung of the
degradation ladder (docs/concepts/degradation.md) on demand:

- ``g_limit``   — pretend the largest group bucket is this value, so a
  modest batch exercises the wave-split planner exactly as a >4,096-
  group batch would in production.
- ``b_limit``   — cap bin-table growth at this bucket, so the overflow
  retry ladder exhausts and the host-FFD fallback engages.
- ``device_errors`` — raise on the next N device pack calls (the XLA
  compile error / device OOM stand-in); N=1 proves the retry path, a
  larger N proves the fallback.

Attach with ``solver.inject_faults(FaultInjector(...))``; every
injection is counted in ``fired`` so a soak can assert the schedule
actually exercised the path it meant to.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class FaultInjector:
    g_limit: Optional[int] = None       # fake ceiling for the group axis
    b_limit: Optional[int] = None       # fake ceiling for the bin table
    device_errors: int = 0              # raise on the next N device calls
    fired: Dict[str, int] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)

    def _count(self, key: str) -> None:
        with self._lock:
            self.fired[key] = self.fired.get(key, 0) + 1

    def take_device_error(self) -> bool:
        """Consume one pending device-error injection (thread-safe)."""
        with self._lock:
            if self.device_errors <= 0:
                return False
            self.device_errors -= 1
            self.fired["device_error"] = self.fired.get("device_error", 0) + 1
            return True

    def note(self, key: str) -> None:
        """Record that an injected ceiling steered the solve (g/b limit)."""
        self._count(key)
