"""Pipelined-solve support: stage timing + device-resident input deltas.

The tunneled-TPU link charges ~100 ms per round trip, and the round-5
bench put the fixed link share of the north-star config at ~2/3 of the
whole e2e latency (BENCH_r05 cfg5: e2e_p50 144.5 ms, device_link_rtt_ms
97.8, device_algo_ms ~9). Everything here exists to keep host work and
link legs OFF the critical path of the device solve:

- ``StageTimer`` — names the five stages of a device solve
  (build / upload / compute / download / decode) and accumulates
  wall-clock per stage, so `NodePlan.stage_ms`, the
  ``karpenter_solver_stage_duration_seconds`` metric, and the bench
  detail can prove (or disprove) that overlap actually happened.

- ``ResidentInputCache`` — device-resident copies of the fused input
  buffers (solver/solve.py _fused_inputs_np / _fused_init_np), delta-
  refreshed. A steady-state reconcile loop re-solves a nearly identical
  problem every pass; re-uploading the whole padded buffer pays the
  link for bytes that did not change. The cache keeps the last host
  copy per (kind, bucket, layout-size) key, block-diffs the new buffer
  against it, and ships only the changed blocks, which a tiny on-device
  scatter applies to the resident copy. Correctness never depends on
  the key: the diff runs against the actual previous content, so a key
  collision only costs a full re-upload, never a wrong solve.

Both are owned by ``Solver`` (solver/solve.py) and engaged only when its
``pipeline`` switch is on; the sequential path never touches them, which
is what makes the pipelined-vs-sequential byte-parity tests
(tests/test_pipeline.py) meaningful.
"""

from __future__ import annotations

import time
import warnings
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import trace

# the five stages of a device solve, in pipeline order; NodePlan.stage_ms
# and the stage-duration metric use exactly these names
STAGES = ("build", "upload", "compute", "download", "decode")


class StageTimer:
    """Accumulates wall-clock milliseconds per named stage.

    ``with timer.span("upload"): ...`` adds the block's duration to the
    stage; repeated spans (overflow retries, waves) accumulate. The
    resulting dict is cheap enough to ride every NodePlan.
    """

    __slots__ = ("ms",)

    def __init__(self):
        self.ms: Dict[str, float] = {}

    def span(self, stage: str):
        return _Span(self, stage)

    def add(self, stage: str, seconds: float) -> None:
        self.ms[stage] = self.ms.get(stage, 0.0) + seconds * 1000.0

    def merge(self, other_ms: Dict[str, float]) -> None:
        for k, v in other_ms.items():
            self.ms[k] = self.ms.get(k, 0.0) + v


class _Span:
    __slots__ = ("_timer", "_stage", "_t0", "_ts")

    def __init__(self, timer: StageTimer, stage: str):
        self._timer = timer
        self._stage = stage

    def __enter__(self):
        # when tracing is on, every stage interval doubles as a REAL
        # trace span nested under the ambient solve span — the stage_ms
        # aggregate becomes a causal span tree (docs/reference/tracing.md);
        # disabled, this is the shared no-op singleton (no allocation)
        self._ts = trace.span("stage." + self._stage).__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._timer.add(self._stage, time.perf_counter() - self._t0)
        self._ts.__exit__(*exc)
        return False


def fetch_async(dev_buf) -> None:
    """Start the device→host transfer of a result buffer without
    blocking. On a tunneled link the blocking ``np.asarray`` at the end
    of a solve otherwise serializes ready-wait and transfer into separate
    legs; issuing the copy right after dispatch lets the runtime stream
    the buffer out the moment the kernel finishes, while the host runs
    decode prep. Backends without the API just skip the hint — the later
    blocking fetch stays correct either way."""
    fn = getattr(dev_buf, "copy_to_host_async", None)
    if fn is not None:
        try:
            fn()
        except Exception:
            pass  # the blocking fetch later is always correct


@jax.jit
def _apply_blocks(base2d: jnp.ndarray, rows: jnp.ndarray,
                  idx: jnp.ndarray) -> jnp.ndarray:
    """Scatter changed blocks into the resident copy (device-side; the
    only link traffic is the ``rows``/``idx`` upload)."""
    return base2d.at[idx].set(rows)


# the DONATED delta program (docs/reference/microloop.md): the resident
# base buffer is consumed and the updated problem state is written in
# place instead of allocating a second device copy per pass. Only the
# microloop requests this (``upload(..., donate=True)``) — the caller
# contract is that NOTHING may read the previous resident buffer after
# the scatter dispatches, which the cache upholds by replacing its entry
# atomically with the scatter's output. Backends without donation
# support (cpu) warn and fall back to a copy; the warning is filtered
# here because the fallback is exactly the non-donated semantics.
_apply_blocks_donated = jax.jit(
    lambda base2d, rows, idx: base2d.at[idx].set(rows),
    donate_argnums=(0,))

# installed ONCE at import: a per-call catch_warnings() would mutate
# process-global filter state on the hottest per-pass path and race
# every other thread's warning evaluation (operator controllers run
# concurrently)
warnings.filterwarnings(
    "ignore", message=".*[Dd]onat.*")   # "Some donated buffers…"


def _run_donated_scatter(base2d, rows, idx):
    return _apply_blocks_donated(base2d, rows, idx)


@jax.jit
def _differs(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Changed-plan fingerprint: EXACT on-device inequality reduction
    between this pass's fused result buffer and the retained previous
    one. A bool scalar crosses the link instead of the whole plan; the
    microloop fetches the full buffer only when this says the plan
    actually moved. Composes with the mesh unchanged: comparing two
    identically-sharded stacked buffers reduces shard-locally and the
    replicated bool is fetched once."""
    return jnp.any(a != b)


def plan_changed(new_buf, prev_buf) -> bool:
    """Host-side wrapper over :func:`_differs` (the one O(1) sync of a
    skipped-fetch pass). Shape mismatch = trivially changed, no device
    work at all."""
    if prev_buf is None or new_buf.shape != prev_buf.shape:
        return True
    return bool(_differs(new_buf, prev_buf))


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class ResidentInputCache:
    """Device-resident fused input buffers refreshed by block delta.

    ``upload(key, buf)`` returns a device uint8 vector with exactly
    ``buf``'s content. The first upload under a key (or a layout-size
    change) ships the whole buffer; subsequent uploads diff against the
    retained host copy in ``block``-byte blocks and ship only changed
    blocks (padded to a power-of-two count so the scatter compiles a
    bounded set of shapes). A mostly-changed buffer (> half the blocks)
    re-uploads whole — the delta machinery must never cost more than the
    thing it replaces.

    ``sharding`` (a jax Sharding, e.g. parallel/sharded.py
    ``replicated_sharding(mesh)``) pins the resident device copy's
    placement: a mesh-replicated entry stays replicated across passes,
    so a steady-state delta solve on an N-way mesh ships each dirty
    block over the host link once and the on-device scatter applies it
    under the mesh sharding — an unchanged buffer never re-replicates.
    Callers key mesh entries by device count (solver/solve.py uses
    ("g", D, ...)), so a mesh-shape change can never delta-hit a buffer
    resident under the old mesh.
    """

    def __init__(self, max_entries: int = 128, block: int = 4096):
        self._entries: Dict[Tuple, Tuple[np.ndarray, jnp.ndarray]] = {}
        self._max_entries = max_entries
        self._block = block
        # observability: soaks and tests assert the cache actually engaged
        self.hits = 0            # uploads served by delta (incl. no-op)
        self.misses = 0          # full uploads (cold key or bulk change)
        self.blocks_shipped = 0  # delta blocks that crossed the link
        self.blocks_resident = 0  # blocks delta uploads did NOT ship
        self.bytes_shipped = 0   # bytes that actually crossed the link
                                 # (full uploads + delta blocks) — the
                                 # steady-state bench row's upload-bytes
                                 # evidence
        # link-leg accounting hook (docs/reference/microloop.md): the
        # owning Solver installs a callable(direction, nbytes) invoked
        # once per TRANSFER that actually crosses the host↔device link
        # (a delta upload whose diff found zero changed blocks calls
        # nothing — no bytes moved). Feeds the
        # karpenter_solver_link_legs_total / _link_bytes_total counters.
        self.account: Optional[Callable[[str, int], None]] = None

    def _ship(self, nbytes: int) -> None:
        self.bytes_shipped += int(nbytes)
        if self.account is not None:
            self.account("upload", int(nbytes))

    def stats(self) -> Dict[str, int]:
        return {"hits": self.hits, "misses": self.misses,
                "blocks_shipped": self.blocks_shipped,
                "blocks_resident": self.blocks_resident,
                "bytes_shipped": self.bytes_shipped}

    def headroom_probe(self) -> Dict[str, float]:
        """Residency occupancy (introspect/headroom.py). ``kind="ring"``
        in the registry's sense — full-by-design: at capacity, cold keys
        take the admission bypass (plain uploads, never thrash), so a
        full cache is a working-set fact, not impending loss."""
        return {"depth": float(len(self._entries)),
                "capacity": float(self._max_entries),
                "kind": "ring"}

    def upload(self, key: Tuple, buf: np.ndarray,
               sharding=None, donate: bool = False) -> jnp.ndarray:
        """``donate=True`` routes the delta scatter through the DONATED
        program: the previous resident device buffer is consumed and the
        update lands in place (one device allocation per steady-state
        pass instead of two). Safe exactly because the entry swap below
        is the only live reference to the consumed buffer — callers get
        back a fresh view of the NEW buffer, never the old one."""
        total = int(buf.size)
        nblk = -(-total // self._block)
        padded = np.zeros((nblk, self._block), np.uint8)
        padded.reshape(-1)[:total] = buf
        ent = self._entries.get(key)
        if ent is None or ent[0].shape[0] != nblk:
            dev2d = self._store(key, padded, sharding)
            self.misses += 1
            self._ship(padded.size)
            return dev2d.reshape(-1)[:total]
        prev, dev2d = ent
        changed = np.nonzero((padded != prev).any(axis=1))[0]
        if changed.size > nblk // 2:
            dev2d = self._store(key, padded, sharding)
            self.misses += 1
            self._ship(padded.size)
            return dev2d.reshape(-1)[:total]
        if changed.size:
            # pad the scatter to a power-of-two row count (duplicate
            # indices write identical rows — idempotent) so XLA compiles
            # O(log nblk) shapes, not one per distinct delta size
            k = _pow2(int(changed.size))
            idx = np.empty((k,), np.int32)
            idx[: changed.size] = changed
            idx[changed.size:] = changed[0]
            apply = _run_donated_scatter if donate else _apply_blocks
            try:
                dev2d = apply(dev2d, jnp.asarray(padded[idx]),
                              jnp.asarray(idx))
            except Exception:
                if donate:
                    # the scatter may have consumed the donated base
                    # before failing: drop the entry so no later upload
                    # can delta against a dead buffer
                    self._entries.pop(key, None)
                raise
            self.blocks_shipped += int(changed.size)
            # the rows and their index vector ride one dispatch: ONE
            # coalesced leg carrying both payloads
            self._ship(k * self._block + idx.nbytes)
            self._entries[key] = (padded, dev2d)
        self.hits += 1
        self.blocks_resident += nblk - int(changed.size)
        return dev2d.reshape(-1)[:total]

    def _store(self, key: Tuple, padded: np.ndarray,
               sharding=None) -> jnp.ndarray:
        dev2d = (jax.device_put(padded, sharding) if sharding is not None
                 else jnp.asarray(padded))
        if key in self._entries or len(self._entries) < self._max_entries:
            self._entries[key] = (padded, dev2d)
        # else: admission bypass. A cold key arriving at capacity uploads
        # WITHOUT residency rather than evicting — eviction would let a
        # >max_entries cyclic working set (a very high-G wave split)
        # evict exactly the entry needed next, every time, AND churn out
        # the steady-state group/init entries. Bypass costs the same
        # full upload a cache-less solve would pay, keeps the resident
        # set intact, and the bound (128) already covers ~128k
        # scheduling signatures' worth of 1024-group waves. A shifted
        # working set whose old keys never hit again degrades to plain
        # uploads, never to thrash; invalidate() (device-error ladder)
        # resets the admission set.
        return dev2d

    def invalidate(self) -> None:
        self._entries.clear()
