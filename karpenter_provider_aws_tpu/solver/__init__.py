from .problem import Problem, ExistingBin, build_problem
from .oracle import ffd_oracle, OraclePlan
from .faults import FaultInjector
from .solve import Solver, NodePlan, PlannedNode

__all__ = [
    "Problem", "ExistingBin", "build_problem",
    "ffd_oracle", "OraclePlan",
    "FaultInjector",
    "Solver", "NodePlan", "PlannedNode",
]
