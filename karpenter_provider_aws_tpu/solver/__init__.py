from .problem import Problem, ExistingBin, build_problem
from .oracle import ffd_oracle, OraclePlan
from .solve import Solver, NodePlan, PlannedNode

__all__ = [
    "Problem", "ExistingBin", "build_problem",
    "ffd_oracle", "OraclePlan",
    "Solver", "NodePlan", "PlannedNode",
]
